"""mx.image — host-side image IO + augmentation pipeline.

Reference: python/mxnet/image/image.py (2,649 LoC over OpenCV). TPU
re-design: decoding/augmentation stays on host (same as the reference — this
is the CPU side of the input pipeline; the TPU sees only batched tensors),
but the backend is PIL + numpy instead of OpenCV, and resize can ride
jax.image.resize when arrays are already device-resident. All functions
take/return NDArray (HWC, uint8 or float32), matching the reference API.
"""
from __future__ import annotations

import io as _io
import random as _pyrandom

import numpy as _np

from ..ndarray.ndarray import NDArray

try:
    from PIL import Image as _PILImage

    _HAS_PIL = True
except ImportError:  # pragma: no cover
    _HAS_PIL = False

__all__ = [
    "imread", "imdecode", "imresize", "imrotate", "scale_down",
    "resize_short", "copyMakeBorder", "fixed_crop", "random_crop",
    "center_crop", "random_size_crop", "color_normalize", "random_rotate",
    "Augmenter", "SequentialAug", "ResizeAug", "ForceResizeAug",
    "RandomCropAug", "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
    "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
    "HueJitterAug", "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
    "RandomGrayAug", "HorizontalFlipAug", "CastAug", "CreateAugmenter",
    "ImageIter",
]


def _require_pil():
    if not _HAS_PIL:
        raise RuntimeError("mx.image requires Pillow for decode/resize")


def _as_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return _np.asarray(img)


def _interp_pil(interp):
    """Map the reference's cv2 interp codes (0-4) to PIL resamplers."""
    _require_pil()
    table = {
        0: _PILImage.NEAREST, 1: _PILImage.BILINEAR, 2: _PILImage.BICUBIC,
        3: _PILImage.BOX,   # cv2 INTER_AREA ≈ PIL box filter
        4: _PILImage.LANCZOS,
    }
    return table.get(interp, _PILImage.BILINEAR)


def imread(filename, flag=1, to_rgb=True, **kwargs):  # noqa: ARG001
    """Read an image file → NDArray (H, W, C) uint8
    (reference: image.py:51 over cv2.imread)."""
    _require_pil()
    img = _PILImage.open(filename)
    img = img.convert("RGB" if flag else "L")
    arr = _np.asarray(img, _np.uint8)
    if not flag:
        arr = arr[:, :, None]
    if flag and not to_rgb:
        arr = arr[:, :, ::-1]  # BGR like cv2 default
    return NDArray(arr)


def imdecode(buf, flag=1, to_rgb=True, **kwargs):  # noqa: ARG001
    """Decode a jpeg/png byte buffer (reference: image.py:154)."""
    _require_pil()
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    img = _PILImage.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = _np.asarray(img, _np.uint8)
    if not flag:
        arr = arr[:, :, None]
    if flag and not to_rgb:
        arr = arr[:, :, ::-1]
    return NDArray(arr)


def imresize(src, w, h, interp=1):
    """Resize to (h, w) (reference: image.py:96)."""
    _require_pil()
    arr = _as_np(src)
    squeeze = arr.shape[-1] == 1
    pil = _PILImage.fromarray(arr.squeeze(-1) if squeeze else arr)
    out = _np.asarray(pil.resize((w, h), _interp_pil(interp)))
    if squeeze:
        out = out[:, :, None]
    return NDArray(out)


def scale_down(src_size, size):
    """Scale requested crop down to fit the source (reference: image.py:214)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals `size` (reference: image.py:357)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def copyMakeBorder(src, top, bot, left, right, type=0, values=0):  # noqa: A002,N802,ARG001
    """Pad borders (reference: image.py:249 over cv2.copyMakeBorder)."""
    arr = _as_np(src)
    out = _np.pad(arr, ((top, bot), (left, right), (0, 0)),
                  mode="constant", constant_values=values)
    return NDArray(out)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _as_np(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(NDArray(out), size[0], size[1], interp)
    return NDArray(out)


def random_crop(src, size, interp=2):
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    arr = _as_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2, **kwargs):  # noqa: ARG001
    """Random area/aspect crop, ImageNet-style (reference: image.py:563)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * aspect) ** 0.5))
        new_h = int(round((target_area / aspect) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = _as_np(src).astype(_np.float32)
    mean = _as_np(mean) if isinstance(mean, NDArray) else _np.asarray(mean)
    arr = arr - mean
    if std is not None:
        std = _as_np(std) if isinstance(std, NDArray) else _np.asarray(std)
        arr = arr / std
    return NDArray(arr.astype(_np.float32))


def imrotate(src, rotation_degrees, zoom_in=False, zoom_out=False):
    """Rotate about the center (reference: image.py:618)."""
    _require_pil()
    if zoom_in and zoom_out:
        raise ValueError("zoom_in and zoom_out are exclusive")
    arr = _as_np(src)
    h, w = arr.shape[:2]
    pil = _PILImage.fromarray(arr.squeeze(-1) if arr.shape[-1] == 1 else arr)
    if zoom_out:
        # rotate with expand so nothing is clipped, then shrink back
        out = _np.asarray(pil.rotate(rotation_degrees, _PILImage.BILINEAR,
                                     expand=True))
        out = _np.asarray(_PILImage.fromarray(out).resize(
            (w, h), _PILImage.BILINEAR))
    else:
        out = _np.asarray(pil.rotate(rotation_degrees, _PILImage.BILINEAR,
                                     expand=False))
    if out.ndim == 2:
        out = out[:, :, None]
    if zoom_in:
        rad = _np.deg2rad(abs(rotation_degrees) % 90)
        zoom = abs(_np.cos(rad)) + abs(_np.sin(rad))
        ch, cw = int(h / zoom), int(w / zoom)
        y0, x0 = (h - ch) // 2, (w - cw) // 2
        out = _np.asarray(_PILImage.fromarray(
            out[y0:y0 + ch, x0:x0 + cw].squeeze(-1)
            if out.shape[-1] == 1 else out[y0:y0 + ch, x0:x0 + cw]
        ).resize((w, h), _PILImage.BILINEAR))
        if out.ndim == 2:
            out = out[:, :, None]
    return NDArray(out)


def random_rotate(src, angle_limits, zoom_in=False, zoom_out=False):
    angle = _pyrandom.uniform(*angle_limits)
    return imrotate(src, angle, zoom_in, zoom_out)


# --- augmenters ------------------------------------------------------------

class Augmenter:
    """Image augmenter base (reference: image.py:761)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return NDArray((_as_np(src).astype(_np.float32) * alpha))


class ContrastJitterAug(Augmenter):
    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        arr = _as_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        gray = (arr * self._coef).sum(-1, keepdims=True)
        mean = gray.mean()
        return NDArray(arr * alpha + mean * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        arr = _as_np(src).astype(_np.float32)
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        gray = (arr * self._coef).sum(-1, keepdims=True)
        return NDArray(arr * alpha + gray * (1 - alpha))


# ImageNet RGB PCA statistics (the AlexNet lighting-noise constants)
PCA_EIGVAL = _np.array([55.46, 4.794, 1.148])
PCA_EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]])


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], _np.float32)
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], _np.float32)

    def __call__(self, src):
        arr = _as_np(src).astype(_np.float32)
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, w = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       _np.float32)
        t = self.ityiq @ bt @ self.tyiq
        return NDArray(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting jitter (reference: image.py:1072)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return NDArray(_as_np(src).astype(_np.float32) + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = _np.array([[0.21, 0.21, 0.21],
                      [0.72, 0.72, 0.72],
                      [0.07, 0.07, 0.07]], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return NDArray(_as_np(src).astype(_np.float32) @ self._mat)
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return NDArray(_as_np(src)[:, ::-1].copy())
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return NDArray(_as_np(src).astype(self.typ))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,  # noqa: N802
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmentation list (reference: image.py:1171)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(pca_noise, PCA_EIGVAL, PCA_EIGVEC))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image iterator over an .lst/.rec source with augmenters
    (reference: image.py:1285)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", aug_list=None,
                 shuffle=False, label_width=1, **kwargs):  # noqa: ARG001
        from ..io import DataBatch, DataDesc  # noqa: F401

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self._items = []
        if path_imgrec:
            from ..recordio import IndexedRecordIO, unpack_img

            self._rec = IndexedRecordIO(path_imgrec)
            self._unpack = unpack_img
            self._items = list(range(len(self._rec)))
            self._mode = "rec"
        elif path_imglist:
            import os

            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    idx, labels, fname = parts[0], parts[1:-1], parts[-1]
                    self._items.append(
                        (float(labels[0]) if labels else 0.0,
                         os.path.join(path_root, fname)))
            self._mode = "list"
        else:
            raise ValueError("need path_imgrec or path_imglist")
        self._cursor = 0
        self.reset()

    def reset(self):
        if self._shuffle:
            _pyrandom.shuffle(self._items)
        self._cursor = 0

    def __iter__(self):
        return self

    def _read_one(self, item):
        if self._mode == "rec":
            header, img = self._unpack(self._rec.read_idx(item))
            label = header.label
            arr = imdecode(img)
        else:
            label, fname = item
            arr = imread(fname)
        for aug in self.auglist:
            arr = aug(arr)
        return arr, float(_np.asarray(label).ravel()[0])

    def __next__(self):
        from .. import numpy as mxnp
        from ..io import DataBatch

        if self._cursor >= len(self._items):
            raise StopIteration
        datas, labels = [], []
        while len(datas) < self.batch_size:
            if self._cursor >= len(self._items):
                break
            arr, label = self._read_one(self._items[self._cursor])
            self._cursor += 1
            datas.append(_as_np(arr).transpose(2, 0, 1))  # HWC -> CHW
            labels.append(label)
        pad = self.batch_size - len(datas)
        while len(datas) < self.batch_size:
            datas.append(datas[-1])
            labels.append(labels[-1])
        return DataBatch(data=[mxnp.array(_np.stack(datas))],
                         label=[mxnp.array(_np.asarray(labels))], pad=pad)

    next = __next__
