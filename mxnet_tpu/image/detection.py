"""Detection augmenters (reference: python/mxnet/image/detection.py).

Labels are (N, 5+) arrays: [class, xmin, ymin, xmax, ymax, ...] with
coordinates normalized to [0, 1]. Augmenters transform image + label
together; the host-side design rationale is in image.py.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..ndarray.ndarray import NDArray
from .image import (
    Augmenter,
    CastAug,
    ColorJitterAug,
    HueJitterAug,
    LightingAug,
    RandomGrayAug,
    _as_np,
    fixed_crop,
    imresize,
)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetForceResizeAug",
           "CreateDetAugmenter"]


class DetAugmenter:
    """Detection augmenter base (reference: detection.py:41)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter (reference: detection.py:72)."""

    def __init__(self, augmenter):
        super().__init__()
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = NDArray(_as_np(src)[:, ::-1].copy())
            label = _np.array(label, copy=True)
            xmin = 1.0 - label[:, 3]
            xmax = 1.0 - label[:, 1]
            label[:, 1], label[:, 3] = xmin, xmax
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by object coverage (reference:
    detection.py:118): a crop is accepted only when at least one box keeps
    >= min_object_covered of its area; boxes falling below
    min_eject_coverage are dropped from the label."""

    def __init__(self, min_object_covered=0.5, min_crop_size=0.5,
                 max_crop_size=1.0, min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered)
        self.min_object_covered = min_object_covered
        self.min_crop_size = min_crop_size
        self.max_crop_size = max_crop_size
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        label = _np.asarray(label)
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(self.min_crop_size, self.max_crop_size)
            cw, ch = int(w * scale), int(h * scale)
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            crop = (x0 / w, y0 / h, (x0 + cw) / w, (y0 + ch) / h)
            cov = _coverage(label, crop)
            if len(cov) == 0 or cov.max() < self.min_object_covered:
                continue
            new_label = _crop_boxes(label, crop, self.min_eject_coverage)
            if len(new_label):
                out = fixed_crop(NDArray(arr), x0, y0, cw, ch)
                return out, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad to a random larger canvas with random aspect ratio
    (reference: detection.py:472). Per-channel fill values honored."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50, pad_val=(127,)):
        super().__init__()
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        nh, nw = h, w
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(max(1.0, self.area_range[0]),
                                     self.area_range[1]) * h * w
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cand_w = int(round((area * ratio) ** 0.5))
            cand_h = int(round((area / ratio) ** 0.5))
            if cand_w >= w and cand_h >= h:
                nh, nw = cand_h, cand_w
                break
        y0 = _pyrandom.randint(0, nh - h) if nh > h else 0
        x0 = _pyrandom.randint(0, nw - w) if nw > w else 0
        fill = _np.asarray(self.pad_val, arr.dtype)
        if fill.size == 1:
            fill = _np.full((arr.shape[2],), fill.ravel()[0], arr.dtype)
        out = _np.broadcast_to(
            fill[:arr.shape[2]], (nh, nw, arr.shape[2])).copy()
        out[y0:y0 + h, x0:x0 + w] = arr
        label = _np.array(label, copy=True)
        label[:, 1] = (label[:, 1] * w + x0) / nw
        label[:, 3] = (label[:, 3] * w + x0) / nw
        label[:, 2] = (label[:, 2] * h + y0) / nh
        label[:, 4] = (label[:, 4] * h + y0) / nh
        return NDArray(out), label


class DetForceResizeAug(DetAugmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1], self.interp), label


def _coverage(label, crop):
    """Fraction of each box's area retained by the crop region."""
    cx0, cy0, cx1, cy1 = crop
    covs = []
    for row in label:
        x0, y0, x1, y1 = row[1:5]
        area = max(x1 - x0, 0) * max(y1 - y0, 0)
        ix = max(min(x1, cx1) - max(x0, cx0), 0)
        iy = max(min(y1, cy1) - max(y0, cy0), 0)
        covs.append((ix * iy) / area if area > 0 else 0.0)
    return _np.asarray(covs)


def _crop_boxes(label, crop, min_eject_coverage=0.0):
    """Clip normalized boxes to `crop`, renormalize; drop boxes whose
    retained area fraction falls below min_eject_coverage."""
    cx0, cy0, cx1, cy1 = crop
    cov = _coverage(label, crop)
    out = []
    for row, c in zip(label, cov):
        if c <= 0 or c < min_eject_coverage:
            continue
        x0, y0, x1, y1 = row[1:5]
        nx0, ny0 = max(x0, cx0), max(y0, cy0)
        nx1, ny1 = min(x1, cx1), min(y1, cy1)
        if nx1 <= nx0 or ny1 <= ny0:
            continue
        new = _np.array(row, copy=True)
        new[1] = (nx0 - cx0) / (cx1 - cx0)
        new[3] = (nx1 - cx0) / (cx1 - cx0)
        new[2] = (ny0 - cy0) / (cy1 - cy0)
        new[4] = (ny1 - cy0) / (cy1 - cy0)
        out.append(new)
    return _np.asarray(out) if out else _np.zeros((0, label.shape[1]))


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,  # noqa: N802
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Build the standard detection aug list (reference: detection.py:788)."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug

        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(min_object_covered,
                                        min_eject_coverage=min_eject_coverage,
                                        max_attempts=max_attempts))
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(aspect_ratio_range,
                                       (1.0, max(1.0, area_range[1])),
                                       max_attempts, pad_val))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1]),
                                     inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is not None:
        from .image import ColorNormalizeAug

        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53])
        if std is True:
            std = _np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist
