"""Detection augmenters (reference: python/mxnet/image/detection.py).

Labels are (N, 5+) arrays: [class, xmin, ymin, xmax, ymax, ...] with
coordinates normalized to [0, 1]. Augmenters transform image + label
together; the host-side design rationale is in image.py.
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..ndarray.ndarray import NDArray
from .image import (
    Augmenter,
    CastAug,
    ColorJitterAug,
    HueJitterAug,
    LightingAug,
    RandomGrayAug,
    _as_np,
    fixed_crop,
    imresize,
)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
           "DetRandomCropAug", "DetRandomPadAug", "DetForceResizeAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base (reference: detection.py:41)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter (reference: detection.py:72)."""

    def __init__(self, augmenter):
        super().__init__()
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            src = NDArray(_as_np(src)[:, ::-1].copy())
            label = _np.array(label, copy=True)
            xmin = 1.0 - label[:, 3]
            xmax = 1.0 - label[:, 1]
            label[:, 1], label[:, 3] = xmin, xmax
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by object coverage (reference:
    detection.py:118): a crop is accepted only when at least one box keeps
    >= min_object_covered of its area; boxes falling below
    min_eject_coverage are dropped from the label."""

    def __init__(self, min_object_covered=0.5, min_crop_size=0.5,
                 max_crop_size=1.0, min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered)
        self.min_object_covered = min_object_covered
        self.min_crop_size = min_crop_size
        self.max_crop_size = max_crop_size
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        label = _np.asarray(label)
        for _ in range(self.max_attempts):
            scale = _pyrandom.uniform(self.min_crop_size, self.max_crop_size)
            cw, ch = int(w * scale), int(h * scale)
            x0 = _pyrandom.randint(0, w - cw)
            y0 = _pyrandom.randint(0, h - ch)
            crop = (x0 / w, y0 / h, (x0 + cw) / w, (y0 + ch) / h)
            cov = _coverage(label, crop)
            if len(cov) == 0 or cov.max() < self.min_object_covered:
                continue
            new_label = _crop_boxes(label, crop, self.min_eject_coverage)
            if len(new_label):
                out = fixed_crop(NDArray(arr), x0, y0, cw, ch)
                return out, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad to a random larger canvas with random aspect ratio
    (reference: detection.py:472). Per-channel fill values honored."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50, pad_val=(127,)):
        super().__init__()
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _as_np(src)
        h, w = arr.shape[:2]
        nh, nw = h, w
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(max(1.0, self.area_range[0]),
                                     self.area_range[1]) * h * w
            ratio = _pyrandom.uniform(*self.aspect_ratio_range)
            cand_w = int(round((area * ratio) ** 0.5))
            cand_h = int(round((area / ratio) ** 0.5))
            if cand_w >= w and cand_h >= h:
                nh, nw = cand_h, cand_w
                break
        y0 = _pyrandom.randint(0, nh - h) if nh > h else 0
        x0 = _pyrandom.randint(0, nw - w) if nw > w else 0
        fill = _np.asarray(self.pad_val, arr.dtype)
        if fill.size == 1:
            fill = _np.full((arr.shape[2],), fill.ravel()[0], arr.dtype)
        out = _np.broadcast_to(
            fill[:arr.shape[2]], (nh, nw, arr.shape[2])).copy()
        out[y0:y0 + h, x0:x0 + w] = arr
        label = _np.array(label, copy=True)
        label[:, 1] = (label[:, 1] * w + x0) / nw
        label[:, 3] = (label[:, 3] * w + x0) / nw
        label[:, 2] = (label[:, 2] * h + y0) / nh
        label[:, 4] = (label[:, 4] * h + y0) / nh
        return NDArray(out), label


class DetForceResizeAug(DetAugmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1], self.interp), label


def _coverage(label, crop):
    """Fraction of each box's area retained by the crop region."""
    cx0, cy0, cx1, cy1 = crop
    covs = []
    for row in label:
        x0, y0, x1, y1 = row[1:5]
        area = max(x1 - x0, 0) * max(y1 - y0, 0)
        ix = max(min(x1, cx1) - max(x0, cx0), 0)
        iy = max(min(y1, cy1) - max(y0, cy0), 0)
        covs.append((ix * iy) / area if area > 0 else 0.0)
    return _np.asarray(covs)


def _crop_boxes(label, crop, min_eject_coverage=0.0):
    """Clip normalized boxes to `crop`, renormalize; drop boxes whose
    retained area fraction falls below min_eject_coverage."""
    cx0, cy0, cx1, cy1 = crop
    cov = _coverage(label, crop)
    out = []
    for row, c in zip(label, cov):
        if c <= 0 or c < min_eject_coverage:
            continue
        x0, y0, x1, y1 = row[1:5]
        nx0, ny0 = max(x0, cx0), max(y0, cy0)
        nx1, ny1 = min(x1, cx1), min(y1, cy1)
        if nx1 <= nx0 or ny1 <= ny0:
            continue
        new = _np.array(row, copy=True)
        new[1] = (nx0 - cx0) / (cx1 - cx0)
        new[3] = (nx1 - cx0) / (cx1 - cx0)
        new[2] = (ny0 - cy0) / (cy1 - cy0)
        new[4] = (ny1 - cy0) / (cy1 - cy0)
        out.append(new)
    return _np.asarray(out) if out else _np.zeros((0, label.shape[1]))


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,  # noqa: N802
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Build the standard detection aug list (reference: detection.py:788)."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug

        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug(min_object_covered,
                                        min_eject_coverage=min_eject_coverage,
                                        max_attempts=max_attempts))
    if rand_pad > 0:
        auglist.append(DetRandomPadAug(aspect_ratio_range,
                                       (1.0, max(1.0, area_range[1])),
                                       max_attempts, pad_val))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1]),
                                     inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is not None:
        from .image import ColorNormalizeAug

        if mean is True:
            mean = _np.array([123.68, 116.28, 103.53])
        if std is True:
            std = _np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter:
    """Detection data iterator (reference: image/detection.py:625-1008
    ImageDetIter over iter_image_det_recordio.cc).

    Reads a .rec written with detection labels (header label layout:
    ``[header_width, obj_width, <extra header...>, obj0..., obj1...]``,
    obj = ``[class, xmin, ymin, xmax, ymax, ...]`` normalized to [0,1]) or
    a .lst via ``path_imglist``. Labels ride through the Det augmenter
    chain with the image and come out padded to a fixed
    ``(batch, max_objects, obj_width)`` block with ``label_pad_val``
    rows, so the batch shape is static for jit (TPU contract: no dynamic
    shapes reach the device).
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, aug_list=None, imglist=None,
                 dtype="float32", label_pad_width=-1, label_pad_val=-1.0,
                 num_parts=1, part_index=0, seed=0, **kwargs):
        from ..io import DataDesc

        self.batch_size = batch_size
        self._shape = tuple(data_shape)
        self._dtype = _np.dtype(dtype)
        self._pad_val = float(label_pad_val)
        self._shuffle = shuffle
        self._seed = int(seed)
        self._epoch = -1
        self.auglist = (aug_list if aug_list is not None
                        else CreateDetAugmenter(data_shape, **kwargs))

        self._items = []           # (kind, payload) per image
        if path_imgrec:
            from ..recordio import IndexedRecordIO

            self._rec = (IndexedRecordIO(path_imgidx, path_imgrec)
                         if path_imgidx else IndexedRecordIO(path_imgrec))
            self._items = [("rec", int(i))
                           for i in _np.arange(len(self._rec))]
        elif path_imglist or imglist is not None:
            import os

            rows = []
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) < 3:
                            continue
                        rows.append((
                            [float(v) for v in parts[1:-1]],
                            os.path.join(path_root, parts[-1])))
            else:
                for entry in imglist:
                    rows.append(([float(v) for v in entry[:-1]]
                                 if not isinstance(entry[0], (list, tuple))
                                 else list(entry[0]), entry[-1]))
            self._items = [("file", r) for r in rows]
        else:
            raise ValueError("need path_imgrec, path_imglist or imglist")
        if not self._items:
            raise ValueError(
                "ImageDetIter found no records — for .rec sources the "
                ".idx sidecar must exist (pass path_imgidx or write with "
                "MXIndexedRecordIO/im2rec)")

        # label block shape, decided BEFORE sharding — every num_parts
        # worker must build the same provide_label or distributed
        # collectives mismatch. With label_pad_width the contract is
        # explicit and only the first record is probed for obj width;
        # otherwise a full scan is required (reference: ImageDetIter
        # estimates label_shape from the data). For multi-worker jobs on
        # large .rec files, pass label_pad_width to skip the scan.
        max_obj, obj_w = 1, 5
        scan = (self._items[:1] if label_pad_width > 0 else self._items)
        for it in scan:
            lab = self._read_label(it)
            max_obj = max(max_obj, lab.shape[0])
            obj_w = max(obj_w, lab.shape[1])
        if num_parts > 1:
            keep = _np.array_split(_np.arange(len(self._items)),
                                   num_parts)[part_index]
            self._items = [self._items[int(j)] for j in keep]
            if not self._items:
                raise ValueError(
                    f"part {part_index}/{num_parts} of a "
                    "dataset this small is empty")
        if label_pad_width > 0:
            if label_pad_width < max_obj:
                raise ValueError(
                    f"label_pad_width {label_pad_width} < max objects "
                    f"{max_obj} in the dataset")
            max_obj = label_pad_width
        self._data_label_shape = (max_obj, obj_w)  # dataset floor
        self._label_shape = (max_obj, obj_w)

        c, h, w = self._shape
        self.provide_data = [DataDesc("data", (batch_size, c, h, w),
                                      self._dtype)]
        self.provide_label = [DataDesc("label",
                                       (batch_size,) + self._label_shape)]
        self.reset()

    # -- label plumbing -------------------------------------------------
    @staticmethod
    def _parse_label(raw):
        """Flat packed label -> (N, obj_width) array of valid objects
        (reference: ImageDetIter._parse_label)."""
        raw = _np.asarray(raw, _np.float32).ravel()
        if raw.size < 2:
            raise ValueError("det label needs [header_width, obj_width]")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise ValueError(f"object width {obj_width} < 5")
        if (raw.size - header_width) % obj_width:
            raise ValueError(
                f"label length {raw.size} does not match header "
                f"{header_width} + k*{obj_width}")
        out = raw[header_width:].reshape(-1, obj_width)
        return out[out[:, 0] > -0.5]   # class < 0 rows are padding

    def _read_label(self, item):
        kind, payload = item
        if kind == "rec":
            from ..recordio import unpack

            header, _ = unpack(self._rec.read_idx(payload))
            return self._parse_label(header.label)
        return self._parse_label(payload[0])

    def _read_sample(self, item):
        """One record read -> (image HWC, label (N, w)) — image and label
        come from the same unpack, one seek per sample."""
        kind, payload = item
        if kind == "rec":
            from ..recordio import unpack_img

            header, img = unpack_img(self._rec.read_idx(payload))
            label = self._parse_label(header.label)
        else:
            from .image import imread

            img = _as_np(imread(payload[1]))
            label = self._parse_label(payload[0])
        if img.ndim == 2:
            img = img[:, :, None]
        return img, label

    # -- iteration ------------------------------------------------------
    def reset(self):
        self._epoch += 1
        order = _np.arange(len(self._items))
        if self._shuffle:
            _np.random.RandomState(self._seed + self._epoch).shuffle(order)
        self._order = order
        self._cursor = 0

    def __iter__(self):
        return self

    def next(self):
        from .. import numpy as mnp
        from ..io import DataBatch

        if self._cursor >= len(self._order):
            raise StopIteration
        idxs = self._order[self._cursor:self._cursor + self.batch_size]
        pad = self.batch_size - len(idxs)
        if pad:   # wrap to the start (last_batch_handle='pad'); modulo so
            # datasets smaller than one batch still fill every row
            fill = self._order[_np.arange(pad) % len(self._order)]
            idxs = _np.concatenate([idxs, fill])
        self._cursor += self.batch_size

        c, h, w = self._shape
        datas = _np.empty((self.batch_size, c, h, w), self._dtype)
        labels = _np.full((self.batch_size,) + self._label_shape,
                          self._pad_val, _np.float32)
        for j, i in enumerate(idxs):
            item = self._items[int(i)]
            img, label = self._read_sample(item)
            src = NDArray(img)
            for aug in self.auglist:
                src, label = aug(src, label)
            arr = _as_np(src)
            if arr.shape[:2] != (h, w):
                # custom aug lists need not end in a force-resize; the
                # batch block must still be static (labels are
                # normalized, so a pure resize leaves them untouched)
                arr = _as_np(imresize(NDArray(arr), w, h))
            datas[j] = arr.transpose(2, 0, 1).astype(self._dtype)
            if label.shape[0] > self._label_shape[0]:
                raise ValueError(
                    f"record has {label.shape[0]} objects but the label "
                    f"block holds {self._label_shape[0]} — raise "
                    "label_pad_width (boxes must never be silently "
                    "dropped)")
            n = label.shape[0]
            labels[j, :n, :label.shape[1]] = label[:n]
        return DataBatch([mnp.array(datas)], [mnp.array(labels)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    __next__ = next

    # -- reference utility surface --------------------------------------
    def reshape(self, data_shape=None, label_shape=None):
        from ..io import DataDesc

        if data_shape is not None:
            self._shape = tuple(data_shape)
            c, h, w = self._shape
            self.provide_data = [DataDesc(
                "data", (self.batch_size, c, h, w), self._dtype)]
        if label_shape is not None:
            label_shape = tuple(label_shape)
            floor = getattr(self, "_data_label_shape", (1, 5))
            if label_shape[0] < floor[0] or label_shape[1] < floor[1]:
                raise ValueError(
                    f"label_shape {label_shape} smaller than the "
                    f"dataset's {floor} — boxes would be silently "
                    "dropped/truncated")
            self._label_shape = label_shape
            self.provide_label = [DataDesc(
                "label", (self.batch_size,) + self._label_shape)]

    def sync_label_shape(self, it, verbose=False):
        """Synchronize label padding with another ImageDetIter
        (reference: train/val iterators must agree)."""
        if not isinstance(it, ImageDetIter):
            raise TypeError("sync_label_shape needs an ImageDetIter")
        target = (max(self._label_shape[0], it._label_shape[0]),
                  max(self._label_shape[1], it._label_shape[1]))
        if verbose and target != self._label_shape:
            print(f"label shape synced to {target}")
        self.reshape(label_shape=target)
        it.reshape(label_shape=target)
        return it

    def draw_next(self, color=255, thickness=2, waitKey=None,  # noqa: N803,ARG002
                  window_name=None):  # noqa: ARG002
        """Yield images with ground-truth boxes burned in (reference:
        ImageDetIter.draw_next; numpy drawing instead of cv2)."""
        try:
            batch = self.next()
        except StopIteration:
            return
        imgs = _np.asarray(batch.data[0].asnumpy())
        labs = _np.asarray(batch.label[0].asnumpy())
        for img, lab in zip(imgs, labs):
            canvas = img.transpose(1, 2, 0).copy()
            hh, ww = canvas.shape[:2]
            for obj in lab:
                if obj[0] < -0.5:
                    continue
                x1 = int(_np.clip(obj[1] * ww, 0, ww - 1))
                y1 = int(_np.clip(obj[2] * hh, 0, hh - 1))
                x2 = int(_np.clip(obj[3] * ww, 0, ww - 1))
                y2 = int(_np.clip(obj[4] * hh, 0, hh - 1))
                t = thickness
                canvas[y1:y1 + t, x1:x2] = color
                canvas[max(0, y2 - t):y2, x1:x2] = color
                canvas[y1:y2, x1:x1 + t] = color
                canvas[y1:y2, max(0, x2 - t):x2] = color
            yield canvas
