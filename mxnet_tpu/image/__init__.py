"""mx.image — image IO + augmentation (reference: python/mxnet/image/)."""
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
from .image import *  # noqa: F401,F403
