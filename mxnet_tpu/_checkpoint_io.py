"""Checkpoint IO through the native dependency engine.

The reference pushes save/load work through the engine so checkpoint
writes overlap training and conflicting accesses serialize on vars
(SURVEY §5 checkpoint/resume; reference NDArray::Save runs under
WaitToRead + file IO off the compute path). Here: each checkpoint path
owns an engine variable; writes are pushed as IO-property ops that
mutate the path var, so
  * training continues while the .npz serializes on an engine thread,
  * two writes to the same path serialize in order,
  * a load (or `mx.nd.waitall()`) blocks until pending writes to that
    path land, and a failed write's exception is rethrown there
    (deferred-exception semantics, threaded_engine.cc:440).
Falls back to synchronous writes when the native engine is unavailable.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

__all__ = ["async_save_npz", "wait_for_path"]

_path_vars = {}
_pending = {}    # key -> queued-but-unfinished write count
_lock = threading.Lock()


def _key(path):
    # canonical key: save('ck') and load(abspath('ck')) must synchronize
    return os.path.abspath(str(path))


def async_save_npz(path, arrays):
    """Write `arrays` (name -> numpy) to `path` as .npz via the engine.

    Returns immediately; the write runs on an engine IO thread. Call
    wait_for_path(path) (or engine.waitall()) to barrier."""
    from . import engine
    from ._dtype_codec import encode_payload

    path = _key(path)  # bind the directory at save time, not flush time
    arrays = encode_payload(arrays)  # bf16/f8 -> uint view + dtype sidecar

    def write():
        with open(path, "wb") as f:
            _np.savez(f, **arrays)

    eng = engine.native_engine()
    if eng is None or engine.is_naive():
        write()  # synchronous fallback (no var allocated)
        return
    key = _key(path)

    def write_and_count():
        try:
            write()
        finally:
            with _lock:
                _pending[key] -= 1

    # push under the lock so reclamation (wait_for_path) can never observe
    # a var between lookup and push
    with _lock:
        var = _path_vars.get(key)
        if var is None:
            var = eng.new_var()
            _path_vars[key] = var
        _pending[key] = _pending.get(key, 0) + 1
        engine.push(write_and_count, mutable_vars=(var,), io=True)


def wait_for_path(path):
    """Block until pending writes to `path` complete; rethrows a failed
    write's deferred exception (reference: WaitForVar)."""
    from . import engine

    eng = engine.native_engine()
    if eng is None:
        return
    key = _key(path)
    with _lock:
        var = _path_vars.get(key)
    if var is None:
        return
    engine.wait_for_var(var)  # concurrent waiters all block here
    _reap(key, var)


def _reap(key, var):
    """Drop the bookkeeping entry once the path is idle. The native var is
    deliberately NOT delete_var'd: another waiter may still hold the raw
    pointer (deleting here would be a use-after-free); a Var is ~100 bytes
    and is reclaimed at engine shutdown, so the residual cost per distinct
    checkpoint path is negligible against the UAF risk."""
    with _lock:
        if _pending.get(key, 0) == 0 and _path_vars.get(key) is var:
            _path_vars.pop(key, None)
            _pending.pop(key, None)


def reap_idle():
    """Drop bookkeeping for every idle path — called from engine.waitall()
    (global quiescence), so epoch-stamped saves that are never loaded
    don't grow the maps unboundedly."""
    with _lock:
        idle = [k for k, v in _path_vars.items()
                if _pending.get(k, 0) == 0]
        for k in idle:
            _path_vars.pop(k, None)
            _pending.pop(k, None)
