"""Checkpoint IO through the native dependency engine.

The reference pushes save/load work through the engine so checkpoint
writes overlap training and conflicting accesses serialize on vars
(SURVEY §5 checkpoint/resume; reference NDArray::Save runs under
WaitToRead + file IO off the compute path). Here: each checkpoint path
owns an engine variable; writes are pushed as IO-property ops that
mutate the path var, so
  * training continues while the .npz serializes on an engine thread,
  * two writes to the same path serialize in order,
  * a load (or `mx.nd.waitall()`) blocks until pending writes to that
    path land, and a failed write's exception is rethrown there
    (deferred-exception semantics, threaded_engine.cc:440).
Falls back to synchronous writes when the native engine is unavailable.

`async_run` generalizes the same path-serialized IO contract to any
callable — checkpoint/manager.py chains payload-write then manifest+
rename commit ops on one var so the commit can never overtake the write.
Failed ops keep their ORIGINAL exception object (traceback intact);
`wait_for_path`/`flush_all` re-raise it, with the engine's stringly
reconstruction attached as ``__context__``.
"""
from __future__ import annotations

import os
import threading

import numpy as _np

__all__ = ["async_save_npz", "async_run", "wait_for_path", "flush_all",
           "pending_error"]

_path_vars = {}
_pending = {}    # key -> queued-but-unfinished op count
_errors = {}     # key -> first failed op's ORIGINAL exception (tb attached)
_lock = threading.Lock()


def _key(path):
    # canonical key: save('ck') and load(abspath('ck')) must synchronize
    return os.path.abspath(str(path))


def async_run(path, fn):
    """Run `fn()` on an engine IO thread, serialized with every other op
    queued on `path` (same engine var -> same order as queued). Returns
    immediately; `wait_for_path(path)` barriers and rethrows a failed
    op's original exception. Runs synchronously (exceptions raise
    inline) when the native engine is unavailable or naive."""
    from . import engine

    key = _key(path)
    eng = engine.native_engine()
    if eng is None or engine.is_naive():
        fn()  # synchronous fallback (no var allocated)
        return

    def run_and_count():
        try:
            fn()
        except BaseException as e:
            with _lock:
                # keep the FIRST failure per path; a later success does
                # not unrecord it (the op sequence is already broken)
                _errors.setdefault(key, e)
            raise
        finally:
            with _lock:
                _pending[key] -= 1

    # push under the lock so reclamation (wait_for_path) can never observe
    # a var between lookup and push
    with _lock:
        var = _path_vars.get(key)
        if var is None:
            var = eng.new_var()
            _path_vars[key] = var
        _pending[key] = _pending.get(key, 0) + 1
        engine.push(run_and_count, mutable_vars=(var,), io=True)


def async_save_npz(path, arrays):
    """Write `arrays` (name -> numpy) to `path` as .npz via the engine.

    Returns immediately; the write runs on an engine IO thread. Call
    wait_for_path(path) (or engine.waitall()) to barrier."""
    from ._dtype_codec import encode_payload

    path = _key(path)  # bind the directory at save time, not flush time
    arrays = encode_payload(arrays)  # bf16/f8 -> uint view + dtype sidecar

    def write():
        with open(path, "wb") as f:
            _np.savez(f, **arrays)

    async_run(path, write)


def pending_error(path):
    """The first recorded failure for `path`'s op chain (or None). Does
    not consume the record — checkpoint commit ops peek at this to skip
    committing on top of a failed payload write."""
    with _lock:
        return _errors.get(_key(path))


def _take_error(key):
    with _lock:
        return _errors.pop(key, None)


def wait_for_path(path):
    """Block until pending ops on `path` complete; rethrows a failed
    op's exception (reference: WaitForVar) — the ORIGINAL exception
    object, so the IO thread's traceback survives and `except <Type>`
    clauses see the real type, with the engine's reconstructed error
    chained as context."""
    from . import engine

    key = _key(path)
    eng = engine.native_engine()
    if eng is None:
        err = _take_error(key)
        if err is not None:
            raise err
        return
    with _lock:
        var = _path_vars.get(key)
    if var is None:
        err = _take_error(key)
        if err is not None:
            raise err
        return
    try:
        engine.wait_for_var(var)  # concurrent waiters all block here
    except Exception as native_exc:
        err = _take_error(key)
        if err is not None:
            raise err from native_exc
        raise
    err = _take_error(key)
    if err is not None:
        raise err
    _reap(key, var)


def flush_all():
    """Barrier EVERY path with pending ops (the preemption handler's
    pre-exit fence: an emergency snapshot must not exit before earlier
    epoch-stamped saves land). Waits all paths even when one fails;
    re-raises the first failure afterwards."""
    with _lock:
        keys = list(_path_vars)
    first = None
    for k in keys:
        try:
            wait_for_path(k)
        except Exception as e:  # noqa: PERF203 — keep draining the rest
            if first is None:
                first = e
    if first is not None:
        raise first


def _reap(key, var):
    """Drop the bookkeeping entry once the path is idle. The native var is
    deliberately NOT delete_var'd: another waiter may still hold the raw
    pointer (deleting here would be a use-after-free); a Var is ~100 bytes
    and is reclaimed at engine shutdown, so the residual cost per distinct
    checkpoint path is negligible against the UAF risk."""
    with _lock:
        if _pending.get(key, 0) == 0 and _path_vars.get(key) is var:
            _path_vars.pop(key, None)
            _pending.pop(key, None)


def reap_idle():
    """Drop bookkeeping for every idle path — called from engine.waitall()
    (global quiescence), so epoch-stamped saves that are never loaded
    don't grow the maps unboundedly."""
    with _lock:
        idle = [k for k, v in _path_vars.items()
                if _pending.get(k, 0) == 0]
        for k in idle:
            _path_vars.pop(k, None)
            _pending.pop(k, None)
