"""Top-level mx.random module (reference: python/mxnet/random.py —
seed + the sampling namespace). This module IS `mx.random` (bound in
__init__.py), so `import mxnet_tpu.random` and the attribute agree; the
sampling functions are the numpy-frontend implementations."""
from .numpy.random import *  # noqa: F401,F403
from .numpy.random import __all__ as _np_all

__all__ = list(_np_all)
if "seed" not in __all__:
    from ._random import seed  # noqa: F401

    __all__.append("seed")
