"""Thread-local coordination between the pass pipeline and the blocks
it traces.

Deliberately import-light (stdlib only): gluon/block.py consults
`suppressed()` inside every CachedOp body, and the passes package
proper pulls in jax — this module breaks that cycle.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

_tls = threading.local()


def suppressed():
    """True while the pipeline (or compile introspection) is re-tracing
    a captured body for its own purposes.  `cached_fn` checks this so
    pipeline traces don't double-count in jit_trace_total — the
    pipeline fires `ctx.on_build` exactly once per built entry
    instead."""
    return getattr(_tls, "suppress", 0) > 0


@contextmanager
def suppress_trace_bumps():
    _tls.suppress = getattr(_tls, "suppress", 0) + 1
    try:
        yield
    finally:
        _tls.suppress -= 1
