"""Rematerialization pass: segmented ``jax.checkpoint`` over captured
training graphs, with a cost-model-driven ``auto`` policy.

The reference stack exposes per-layer mirroring (memonger); the TPU
papers' framing is a policy chosen from a cost model rather than from
measurement.  This pass splits the captured forward body into ~√N
contiguous equation segments and wraps each in ``jax.checkpoint``, so
the backward pass recomputes one segment at a time instead of keeping
every activation live — the classic O(√N) activation-memory schedule.
A single whole-body checkpoint would be pointless (the backward would
recompute everything at once and peak residency would not move);
segmentation is what bends the curve.

Policies (MXTPU_REMAT_POLICY, or ``RematPass(policy)``):

  none   leave the graph alone (default)
  dots   segments save matmul/conv outputs (jax.checkpoint_policies
         .dots_saveable) — cheap recompute, most of the win
  full   segments save only their boundary values — max memory saving,
         max recompute
  auto   estimate the fwd+bwd peak residency (passes/memory.py liveness
         walk, cross-checked against the diagnostics compile registry)
         for each policy and pick the cheapest one that fits the budget
         (MXTPU_REMAT_BUDGET_MB, else the device's memory_stats
         bytes_limit; with neither, resolves to ``none``).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from .. import env as _env
from ..telemetry import instruments as _telemetry
from . import manager as _manager
from .manager import GraphPass

__all__ = [
    "POLICIES",
    "RematPass",
    "choose_policy",
    "default_segments",
    "remat_budget_bytes",
    "segmented_remat",
]

POLICIES = ("none", "dots", "full")


def default_segments(n_eqns):
    """~√N contiguous segments: the textbook memory/recompute sweet
    spot."""
    return max(2, int(round(math.sqrt(max(n_eqns, 1)))))


def remat_budget_bytes():
    """The HBM budget `auto` fits into, or None (→ no remat)."""
    mb = int(_env.get("MXTPU_REMAT_BUDGET_MB"))
    if mb > 0:
        return mb << 20
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


def _seam_platform(closed, ctx):
    """Platform the rewritten program will actually run on: the devices
    already committed on the captured consts (traced weights), else the
    seam block's materialized parameters, else the process default.
    ``jax.default_backend()`` alone is wrong in a mixed-backend process —
    a CPU-placed program built under a TPU default would keep the CPU-
    hostile barrier, and an accelerator program under a CPU default
    would lose it."""
    platforms = set()

    def collect(arr):
        devs = getattr(arr, "devices", None)
        if callable(devs):
            try:
                platforms.update(d.platform for d in devs())
            except Exception:
                pass

    for c in closed.consts:
        collect(c)
    if not platforms and ctx is not None and ctx.block is not None:
        try:
            for _n, p in getattr(ctx.block, "_cached_param_list", ()):
                collect(p.data()._data)
        except Exception:
            pass
    if len(platforms) == 1:
        return platforms.pop()
    return jax.default_backend()


def segmented_remat(closed, policy, n_segments, ctx=None):
    """Rewrite ``closed`` so its equations run as ``n_segments``
    contiguous ``jax.checkpoint`` segments; returns a new ClosedJaxpr
    computing bitwise-identical outputs."""
    from ..subgraph import _eval_eqn

    jaxpr, consts = closed.jaxpr, list(closed.consts)
    eqns = list(jaxpr.eqns)
    if len(eqns) < 2:
        return closed
    n_segments = max(1, min(int(n_segments), len(eqns)))
    bounds = [len(eqns) * k // n_segments for k in range(n_segments + 1)]
    jax_policy = (None if policy == "full"
                  else jax.checkpoint_policies.dots_saveable)
    # XLA:CPU's thunk runtime mis-assigns layouts around the
    # optimization_barrier jax.checkpoint inserts (DotThunk's dim0-major
    # check rejects the transposed dots in the recompute); CPU has no
    # HBM to protect, so drop the CSE barrier there and keep it on real
    # accelerators where it preserves the rematerialization.
    prevent_cse = _seam_platform(closed, ctx) != "cpu"

    out_needed = {id(v) for v in jaxpr.outvars
                  if not isinstance(v, jcore.Literal)}
    segments = []
    for s in range(n_segments):
        chunk = eqns[bounds[s]:bounds[s + 1]]
        if not chunk:
            continue
        local = {id(v) for eqn in chunk for v in eqn.outvars}
        ins, seen = [], set()
        for eqn in chunk:
            for v in eqn.invars:
                if isinstance(v, jcore.Literal):
                    continue
                if id(v) in local or id(v) in seen:
                    continue
                seen.add(id(v))
                ins.append(v)
        later_use = {id(v) for eqn in eqns[bounds[s + 1]:]
                     for v in eqn.invars
                     if not isinstance(v, jcore.Literal)}
        outs, odone = [], set()
        for eqn in chunk:
            for v in eqn.outvars:
                if id(v) in odone:
                    continue
                if id(v) in later_use or id(v) in out_needed:
                    odone.add(id(v))
                    outs.append(v)
        segments.append((chunk, ins, outs))

    def rematted(*args):
        env = {}
        for v, val in zip(jaxpr.constvars, consts):
            env[id(v)] = val
        for v, val in zip(jaxpr.invars, args):
            env[id(v)] = val

        def read(v):
            if isinstance(v, jcore.Literal):
                return jnp.asarray(v.val)
            return env[id(v)]

        for chunk, ins, outs in segments:
            if not outs:  # dead tail — nothing downstream reads it
                continue

            def seg_fn(*vals, _chunk=chunk, _ins=ins, _outs=outs):
                local_env = {id(v): val for v, val in zip(_ins, vals)}

                def rd(v):
                    if isinstance(v, jcore.Literal):
                        return jnp.asarray(v.val)
                    return local_env[id(v)]

                for eqn in _chunk:
                    out = _eval_eqn(eqn, [rd(v) for v in eqn.invars])
                    if isinstance(out, (list, tuple)):
                        for v, val in zip(eqn.outvars, out):
                            local_env[id(v)] = val
                    else:
                        local_env[id(eqn.outvars[0])] = out
                return tuple(local_env[id(v)] for v in _outs)

            vals = tuple(read(v) for v in ins)
            res = jax.checkpoint(seg_fn, policy=jax_policy,
                                 prevent_cse=prevent_cse)(*vals)
            for v, val in zip(outs, res):
                env[id(v)] = val
        return tuple(read(v) for v in jaxpr.outvars)

    return _manager.retrace_flat(rematted, closed)


def choose_policy(closed, ctx):
    """`auto`: pick the cheapest policy whose estimated fwd+bwd peak
    residency fits the budget.  Estimates come from the liveness walk
    (passes/memory.py); the compile registry's measured peak for this
    seam, when present, floors the `none` estimate so a backend-reported
    number is never ignored."""
    from . import memory as _memory

    budget = remat_budget_bytes()
    if budget is None:
        return "none"

    estimates = {}
    n_seg = default_segments(len(closed.jaxpr.eqns))
    for cand in POLICIES:
        try:
            c = closed if cand == "none" else segmented_remat(
                closed, cand, n_seg, ctx)
            estimates[cand] = _memory.estimate_training_peak_bytes(c)
        except Exception:
            estimates[cand] = None
    try:
        from ..diagnostics.introspect import compile_registry
        entry = compile_registry().get((ctx.label, ctx.variant))
        measured = entry and entry.get("peak_hbm_bytes")
        if measured and estimates.get("none") is not None:
            estimates["none"] = max(estimates["none"], int(measured))
    except Exception:
        pass

    ctx.notes["remat_estimates"] = dict(estimates)
    ctx.notes["remat_budget_bytes"] = budget
    for cand in POLICIES:  # none → dots → full: least recompute first
        est = estimates.get(cand)
        if est is not None and est <= budget:
            return cand
    return "full" if estimates.get("full") is not None else "none"


class RematPass(GraphPass):
    """Wraps training graphs in segmented ``jax.checkpoint``.  Applies
    only to training builds (a predict graph has no backward to save
    memory in)."""

    name = "remat"
    priority = 90  # after precision rewrites: remat the graph AMP made
    kinds = ("block", "whole_step_fwd")

    def __init__(self, policy="auto", segments=None):
        self.policy = str(policy or "auto").lower()
        self.segments = segments

    def applies(self, ctx):
        if ctx.kind not in self.kinds:
            return False
        return ctx.training or ctx.kind == "whole_step_fwd"

    def run(self, closed, ctx):
        policy = self.policy
        if policy in ("auto", "1", "true", "on"):
            policy = choose_policy(closed, ctx)
        if policy not in POLICIES:
            raise ValueError(
                f"MXTPU_REMAT_POLICY={policy!r}: expected one of "
                f"{POLICIES + ('auto',)}")
        _telemetry.record_remat_policy(ctx.label, policy)
        ctx.notes["remat_policy"] = policy
        if policy == "none" or len(closed.jaxpr.eqns) < 2:
            return closed
        n_seg = self.segments or default_segments(len(closed.jaxpr.eqns))
        return segmented_remat(closed, policy, n_seg, ctx)
