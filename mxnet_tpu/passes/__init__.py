"""mxnet_tpu.passes — the NNVM-style graph-pass pipeline.

Owns the seam between trace and compile: every jit the framework builds
for a captured program (CachedOp variants, export, symbol lowering, the
whole-step train program) flows through :func:`apply`, which runs the
resolved passes jaxpr → jaxpr before XLA sees the graph.  Shipped
passes: :class:`AmpPass` (auto mixed precision), :class:`RematPass`
(segmented rematerialization with an `auto` cost-model policy),
:class:`KernelPass` (the bandwidth-kernel audit; docs/kernels.md), and
cross-CachedOp structural dedup (MXTPU_GRAPH_DEDUP).  docs/passes.md
covers the architecture and how to write a custom pass.
"""
from .manager import (  # noqa: F401
    GraphPass,
    PassContext,
    PassManager,
    apply,
    apply_pipeline,
    block_context,
    pipeline_enabled,
    register_named_pass,
    resolve_passes,
    retrace_flat,
    run_passes,
    trace_closed,
    wrap_forward,
)
from .amp_pass import AmpPass  # noqa: F401
from .remat import (  # noqa: F401
    RematPass,
    choose_policy,
    segmented_remat,
)
from .dedup import (  # noqa: F401
    DedupExecutable,
    executable_cache_info,
    reset_executable_cache,
    structural_key,
)
from .kernel_pass import KernelPass  # noqa: F401
from .layout import LayoutPass  # noqa: F401
from . import _state  # noqa: F401
from . import memory  # noqa: F401

register_named_pass("amp", AmpPass)
register_named_pass("remat", RematPass)
register_named_pass("kernels", KernelPass)
# force-named layout (MXTPU_PASSES=layout) rewrites unconditionally;
# MXTPU_LAYOUT owns the auto/off policy via resolve_passes injection
register_named_pass("layout", lambda: LayoutPass("nhwc"))


def _numerics_factory():
    # lazy: observability imports jax-heavy bits; only pay when named
    from ..observability.numerics import NumericsPass

    return NumericsPass()


register_named_pass("numerics", _numerics_factory)


def _sharding_factory():
    # lazy (sharding imports parallel.mesh); a force-named pass carries
    # no plan of its own — it stamps whatever plan the context holds
    from ..sharding.shard_pass import ShardingPass

    return ShardingPass()


register_named_pass("sharding", _sharding_factory)

__all__ = [
    "AmpPass",
    "DedupExecutable",
    "GraphPass",
    "KernelPass",
    "LayoutPass",
    "PassContext",
    "PassManager",
    "RematPass",
    "apply",
    "apply_pipeline",
    "block_context",
    "choose_policy",
    "executable_cache_info",
    "memory",
    "pipeline_enabled",
    "register_named_pass",
    "reset_executable_cache",
    "resolve_passes",
    "retrace_flat",
    "run_passes",
    "segmented_remat",
    "structural_key",
    "trace_closed",
    "wrap_forward",
]
