"""Pass manager: the NNVM-style seam between trace and compile.

The reference stack runs graph passes (AMP's low_precision_pass, memory
planning, fusion) on the NNVM graph a CachedOp captured, *before*
handing it to the executor.  Here the captured graph is a jaxpr and the
executor is XLA, so the seam is the point where the framework would
call ``jax.jit`` on a captured block body.  Every such call site —
`HybridBlock._build_jit`, the subgraph variant, `export()`, symbol
lowering, and the whole-step train program — routes through
:func:`apply` instead, which traces the body once per input signature,
runs the registered passes jaxpr → jaxpr, and compiles the REWRITTEN
program.  docs/passes.md is the user-facing tour.

With no passes resolved (and dedup off), :func:`apply` returns a plain
``jax.jit(fn)`` — bitwise-identical to the pre-pipeline framework, and
what ``MXTPU_PASSES=0`` forces unconditionally.
"""
from __future__ import annotations

import threading
import time

import jax
from jax.api_util import shaped_abstractify

from .. import env as _env
from ..telemetry import instruments as _telemetry
from . import _state

__all__ = [
    "GraphPass",
    "PassContext",
    "PassManager",
    "apply",
    "apply_pipeline",
    "block_context",
    "pipelined_callable",
    "pipeline_enabled",
    "register_named_pass",
    "resolve_passes",
    "retrace_flat",
    "run_passes",
    "trace_closed",
    "wrap_forward",
]

# Seam kinds a pass can opt into (PassContext.kind):
#   block          a CachedOp variant (HybridBlock._build_jit / subgraph)
#   export         the inference function jax_export serializes
#   symbol         SymbolBlock's lowered symbolic graph
#   whole_step     the outer one-dispatch train program (fwd+bwd+update)
#   whole_step_fwd the forward body embedded inside the whole-step
#                  program (where AMP/remat act; the outer program also
#                  holds optimizer state, which passes must not touch)
KINDS = ("block", "export", "symbol", "whole_step", "whole_step_fwd")


class PassContext:
    """Everything a pass may consult about the seam it is rewriting."""

    __slots__ = ("block", "label", "variant", "kind", "training",
                 "donate_argnums", "on_build", "notes", "plan",
                 "in_shardings", "out_shardings")

    def __init__(self, block=None, label="", variant="", kind="block",
                 training=False, donate_argnums=(), on_build=None,
                 plan=None, in_shardings=None, out_shardings=None):
        self.block = block
        self.label = label or (type(block).__name__ if block is not None
                               else "?")
        self.variant = variant
        self.kind = kind
        self.training = bool(training)
        self.donate_argnums = tuple(donate_argnums or ())
        # Fired once per built pipeline entry (new input signature), in
        # place of the side effects the suppressed trace would have had
        # (the block's jit_trace_total bump).
        self.on_build = on_build
        self.notes = {}
        # The ShardingPlan for this seam, or None.  Plan-carrying
        # contexts get a ShardingPass injected in resolve_passes; a
        # None plan (mesh=None) never does, so that path compiles the
        # same program main compiles.  Deliberately per-context, not
        # process-global: two trainers with different meshes coexist.
        self.plan = plan
        # Optional jit placement constraints forwarded verbatim to
        # jax.jit by apply()/apply_pipeline().  None means "let jax
        # infer from operands" — the default everywhere today; the
        # whole-step path places operands with device_put instead
        # (python scalars in its arg list make pytree-prefix shardings
        # fragile), so these are for block/export seams and tests.
        self.in_shardings = in_shardings
        self.out_shardings = out_shardings

    def fire_on_build(self):
        if self.on_build is not None:
            self.on_build()

    def __repr__(self):
        return (f"PassContext({self.label}/{self.variant or '?'} "
                f"kind={self.kind} training={self.training})")


class GraphPass:
    """Base class: a jaxpr → jaxpr rewrite.

    Subclasses set ``name`` (unique within a pipeline), ``priority``
    (lower runs earlier; ties break by name, so ordering is
    deterministic regardless of registration order) and ``kinds`` (the
    seams the pass participates in), and implement :meth:`run`.
    """

    name = "?"
    priority = 50
    kinds = ("block",)

    def applies(self, ctx):
        return ctx.kind in self.kinds

    def run(self, closed_jaxpr, ctx):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r}, priority={self.priority})"


class PassManager:
    """Ordered, name-deduped pass registry — one per HybridBlock
    (``block.pass_pipeline()``), plus free-standing instances in tests.
    Registering a pass with an existing name replaces it."""

    def __init__(self, passes=()):
        self._lock = threading.Lock()
        self._passes = []
        for p in passes:
            self.register(p)

    def register(self, graph_pass):
        with self._lock:
            self._passes = [p for p in self._passes
                            if p.name != graph_pass.name]
            self._passes.append(graph_pass)
        return graph_pass

    def remove(self, name):
        with self._lock:
            before = len(self._passes)
            self._passes = [p for p in self._passes if p.name != name]
            return len(self._passes) != before

    def get(self, name):
        with self._lock:
            for p in self._passes:
                if p.name == name:
                    return p
        return None

    def passes(self):
        """Registered passes in execution order: (priority, name)."""
        with self._lock:
            return sorted(self._passes, key=lambda p: (p.priority, p.name))

    def __len__(self):
        with self._lock:
            return len(self._passes)

    def __iter__(self):
        return iter(self.passes())

    def __repr__(self):
        return f"PassManager({self.passes()!r})"


# MXTPU_PASSES can name passes by string ("amp,remat"); factories
# register here (passes/__init__.py) so env config needs no imports.
_NAMED = {}


def register_named_pass(name, factory):
    _NAMED[name] = factory
    return factory


def pipeline_enabled():
    """False only under the kill switch (MXTPU_PASSES=0/off/false/no):
    every seam then compiles its captured program verbatim, including
    blocks with explicitly registered pipelines."""
    return str(_env.get("MXTPU_PASSES")).strip().lower() not in (
        "0", "off", "false", "no")


def resolve_passes(ctx):
    """The pipeline for one seam build: the block's registered passes,
    any passes force-added by name via MXTPU_PASSES, and the env-driven
    remat policy — filtered by :meth:`GraphPass.applies` and sorted
    (priority, name)."""
    if not pipeline_enabled():
        return []
    passes = []
    pm = getattr(ctx.block, "_pass_manager", None) \
        if ctx.block is not None else None
    if pm is not None:
        passes.extend(pm.passes())
    spec = str(_env.get("MXTPU_PASSES")).strip()
    if spec.lower() not in ("", "auto", "1", "on", "true", "yes"):
        for name in spec.split(","):
            name = name.strip()
            if not name or any(p.name == name for p in passes):
                continue
            factory = _NAMED.get(name)
            if factory is None:
                raise ValueError(
                    f"MXTPU_PASSES names unknown pass {name!r}; "
                    f"registered: {sorted(_NAMED)}")
            passes.append(factory())
    policy = str(_env.get("MXTPU_REMAT_POLICY")).strip().lower()
    if policy not in ("", "none") and not any(p.name == "remat"
                                              for p in passes):
        from .remat import RematPass
        passes.append(RematPass(policy))
    # mode() is the ONE normalization of MXTPU_NUMERICS — TrainStep's
    # step-boundary poll reads the same function, so a value that
    # installs no pass here also triggers no polling there
    from ..observability import numerics as _numerics
    if _numerics.mode() != "off" \
            and not any(p.name == "numerics" for p in passes):
        passes.append(_numerics.NumericsPass())
    # same one-normalization contract as numerics: kernels.dispatch.mode()
    # both injects the audit pass here and gates the sites themselves
    from ..kernels import dispatch as _kdispatch
    if _kdispatch.mode() != "off" \
            and not any(p.name == "kernels" for p in passes):
        from .kernel_pass import KernelPass
        passes.append(KernelPass())
    # and once more for layout: layout.mode() injects the NHWC rewrite
    # here and gates prepare_block at the CachedOp/TrainStep entries —
    # MXTPU_LAYOUT=off touches neither (zero extra traces)
    from . import layout as _layout
    if _layout.mode() != "off" \
            and not any(p.name == "layout" for p in passes):
        passes.append(_layout.LayoutPass())
    # sharding joins only when the context CARRIES a plan (mesh=None →
    # ctx.plan None → never injected, the kill-switch acceptance
    # contract) and MXTPU_SHARDING isn't off — the same mode() Trainer
    # used to resolve that plan in the first place
    if ctx.plan is not None:
        from ..sharding import mode as _sharding_mode
        if _sharding_mode() != "off" \
                and not any(p.name == "sharding" for p in passes):
            from ..sharding.shard_pass import ShardingPass
            passes.append(ShardingPass(ctx.plan))
    passes = [p for p in passes if p.applies(ctx)]
    passes.sort(key=lambda p: (p.priority, p.name))
    return passes


def _dedup_active(ctx):
    # Dedup is scoped to block seams: export needs a real jax.jit for
    # jax_export, and whole-step programs donate buffers (a shared
    # executable must not donate one block's params for another).
    return (ctx.kind == "block" and pipeline_enabled()
            and bool(_env.get("MXTPU_GRAPH_DEDUP")))


def trace_closed(fn, args):
    """``make_jaxpr`` with block trace-side-effects suppressed; returns
    (ClosedJaxpr, out_tree)."""
    with _state.suppress_trace_bumps():
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    _, out_tree = jax.tree_util.tree_flatten(out_shape)
    return closed, out_tree


def run_passes(closed, passes, ctx):
    for p in passes:
        t0 = time.perf_counter()
        closed = p.run(closed, ctx)
        _telemetry.record_pass(p.name, (time.perf_counter() - t0) * 1e3)
    return closed


def retrace_flat(fn_flat, closed):
    """Re-trace a flat-args callable at ``closed``'s input signature.
    The pass contract is jaxpr → jaxpr; interpreter-style rewrites
    (amp_rewrite, segmented remat) produce a callable and round-trip
    back to a ClosedJaxpr through this."""
    sds = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
           for v in closed.jaxpr.invars]
    return jax.make_jaxpr(lambda *xs: tuple(fn_flat(*xs)))(*sds)


def signature(args):
    """(flat leaves, hashable signature) of a pytree of arguments."""
    flat, in_tree = jax.tree_util.tree_flatten(args)
    return flat, (in_tree, tuple(shaped_abstractify(x) for x in flat))


def pipelined_callable(fn, passes, ctx):
    """``fn`` with the pipeline applied at trace time: one cached
    (rewritten ClosedJaxpr, out_tree) per input signature, evaluated
    inline via ``eval_jaxpr``.  Traceable — jit / vjp / export of the
    result see the REWRITTEN program, and re-traces at a known
    signature hit the cache instead of re-running the passes."""
    cache = {}
    lock = threading.Lock()

    def pipelined(*args):
        flat, sig = signature(args)
        entry = cache.get(sig)
        if entry is None:
            with lock:
                entry = cache.get(sig)
                if entry is None:
                    closed, out_tree = trace_closed(fn, args)
                    closed = run_passes(closed, passes, ctx)
                    entry = (closed, out_tree)
                    cache[sig] = entry
                    ctx.fire_on_build()
        closed, out_tree = entry
        outs = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
        return jax.tree_util.tree_unflatten(out_tree, list(outs))

    pipelined._pass_ctx = ctx
    pipelined._pass_list = passes
    return pipelined


def apply(fn, ctx):
    """THE seam: compile ``fn`` through the pass pipeline.

    Resolution order per build:
      no passes, no dedup → plain ``jax.jit(fn)`` (bitwise main);
      dedup on (block seams) → a :class:`~.dedup.DedupExecutable`
      sharing structurally identical programs across blocks;
      otherwise → ``jax.jit`` of the pipelined traceable — a REAL jit
      object, so donation, ``.lower()`` (compile introspection) and
      ``jax_export`` all work unchanged.
    """
    passes = resolve_passes(ctx)
    if _dedup_active(ctx):
        from .dedup import DedupExecutable
        return DedupExecutable(fn, passes, ctx)
    if not passes:
        return jax.jit(fn, donate_argnums=ctx.donate_argnums,
                       **_jit_shardings(ctx))
    return jax.jit(pipelined_callable(fn, passes, ctx),
                   donate_argnums=ctx.donate_argnums,
                   **_jit_shardings(ctx))


def _jit_shardings(ctx):
    """in/out_shardings kwargs for jax.jit — only the ones the context
    actually sets, so the default stays a vanilla jit call (bitwise
    main, and robust to jax versions where the kwarg default differs
    from passing None)."""
    kw = {}
    if ctx.in_shardings is not None:
        kw["in_shardings"] = ctx.in_shardings
    if ctx.out_shardings is not None:
        kw["out_shardings"] = ctx.out_shardings
    return kw


def apply_pipeline(fn, passes, ctx):
    """:func:`apply` with an explicit pass list, bypassing resolution —
    for one-off variant builders (amp.build_amp_variant) and tests.
    Ignores the MXTPU_PASSES kill switch: the caller asked for exactly
    these passes."""
    if not passes:
        return jax.jit(fn, donate_argnums=ctx.donate_argnums,
                       **_jit_shardings(ctx))
    return jax.jit(pipelined_callable(fn, passes, ctx),
                   donate_argnums=ctx.donate_argnums,
                   **_jit_shardings(ctx))


def wrap_forward(fn, ctx):
    """Pipeline for a forward body embedded in a larger program (the
    whole-step train program's loss forward): returns ``fn`` untouched
    when no passes apply, else the pipelined traceable — no jit; the
    enclosing program's trace swallows the rewritten jaxpr inline."""
    passes = resolve_passes(ctx)
    if not passes:
        return fn
    return pipelined_callable(fn, passes, ctx)


def block_context(block, training, kind="block", bump=True):
    """PassContext for a HybridBlock seam.  ``bump`` wires on_build to
    the block's jit_trace_total bump — pipeline builds count exactly
    like direct traces did."""
    on_build = None
    if bump and kind == "block":
        def on_build():
            block._bump_trace(training)
    return PassContext(
        block=block, label=type(block).__name__,
        variant="train" if training else "predict",
        kind=kind, training=training, on_build=on_build)
