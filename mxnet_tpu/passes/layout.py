"""LayoutPass — whole-graph NHWC propagation with transpose elision.

TPUs strongly prefer channels-last tilings (C rides the 128-wide lane
dimension, so convs feed the MXU and BN/elementwise chains vectorize
without relayouts), but the reference default is NCHW and per-layer
``layout=`` flags leave mixed graphs paying transpose pairs at every
conv/norm seam.  This pass makes layout a COMPILER decision, the way
TVM's graph-level layout-transformation pass and the learned-TPU-cost-
model work frame it: walk the captured jaxpr once, rewrite every
``conv_general_dilated`` to NHWC/HWIO dimension numbers, propagate
channels-last through elementwise / BN / reduce / reduce_window ops, and
materialize a transpose ONLY at an unavoidable boundary.

The interpreter is lazy: every jaxpr var maps to a dict of
``{permutation: value}`` and values materialize on demand, so

  * a pre-existing ``transpose`` equation is ABSORBED into the
    permutation key (no op emitted) — transpose·transpose pairs cancel
    for free, and survivors sink to the graph edges (the final outvar
    reads at identity);
  * ``reshape`` / ``broadcast_in_dim`` register permutation-polymorphic
    makers, so a bias broadcast materializes directly in the layout its
    consumer wants instead of broadcasting channels-first and paying a
    transpose.

Weights are re-laid-out PERSISTENTLY and eagerly by
:func:`prepare_block` (called from ``HybridBlock._call_cached`` and
``TrainStep.__call__`` before the first trace): a one-time device-side
OIHW→HWIO transpose recorded on the Parameter as ``_layout_perm``.  The
captured program then sees HWIO weight invars from the start — one
compile, zero per-step weight transposes, and the PR-4/6 donated
whole-step path updates the physical (HWIO) buffers in place.
Checkpoints round-trip the LOGICAL layout (``Parameter.logical_data``),
so NCHW-era snapshots load bitwise and new snapshots stay portable.

Modes (``MXTPU_LAYOUT``, kernels-style kill-switch discipline):

  off   (default) nothing consults this module — captured programs are
        bitwise-identical to main with zero extra traces;
  auto  rewrite only when the passes/memory.py external-bytes model
        predicts a win: skip graphs with no channels-first convs (zero
        retrace), decline regions whose conv activations are under
        MXTPU_LAYOUT_MIN_BYTES, and decline when the bytes of inserted
        boundary transposes rival the predicted conv-side saving;
  nhwc  rewrite whenever a channels-first conv is present.

docs/layout.md is the user-facing tour.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .. import env as _env
from ..telemetry import instruments as _telemetry
from . import manager as _manager
from .manager import GraphPass

__all__ = ["LayoutPass", "mode", "min_bytes", "prepare_block",
           "weight_perm"]

# same normalization table as kernels.dispatch._MODES / numerics.mode():
# the ONE place MXTPU_LAYOUT is interpreted — resolve_passes injection,
# prepare_block, and the pass itself all read mode()
_MODES = {
    "": "off", "0": "off", "off": "off", "false": "off", "no": "off",
    "none": "off",
    "1": "auto", "auto": "auto", "on": "auto", "true": "auto",
    "yes": "auto",
    "nhwc": "nhwc", "force": "nhwc", "always": "nhwc",
}


def mode():
    """Resolved MXTPU_LAYOUT mode: 'off' | 'auto' | 'nhwc'."""
    raw = str(_env.get("MXTPU_LAYOUT")).strip().lower()
    try:
        return _MODES[raw]
    except KeyError:
        raise ValueError(
            f"MXTPU_LAYOUT={raw!r} is not a recognized mode; expected "
            f"off | auto | nhwc") from None


def min_bytes():
    """auto declines graphs whose conv activations total less than this."""
    return int(_env.get("MXTPU_LAYOUT_MIN_BYTES"))


# ---------------------------------------------------------------------------
# persistent weight re-layout
# ---------------------------------------------------------------------------


def weight_perm(nd):
    """The OIHW→HWIO-family permutation for an nd-spatial conv weight
    ((O, I, *k) → (*k, I, O)); 2-D: (2, 3, 1, 0)."""
    return tuple(range(2, 2 + nd)) + (1, 0)


def prepare_block(block, trainer=None):
    """One-time persistent re-layout of every channels-first conv weight
    under ``block`` to HWIO, recorded as ``Parameter._layout_perm``.

    Idempotent and eager: call sites (``HybridBlock._call_cached``,
    ``TrainStep.__call__``) run it BEFORE the first trace, so the
    captured program's weight invars are already channels-last — no
    extra compile, and the donated whole-step writeback updates the
    physical buffers consistently.  A ``trainer`` (when known) gets its
    momentum-class optimizer-state leaves transposed alongside, keeping
    state/weight layouts matched for already-created states.
    """
    if getattr(block, "_layout_prepared", False):
        return
    if mode() == "off":
        return
    complete = True
    for layer in _iter_convs(block):
        if layer._transpose or layer._channels_last:
            continue
        p = layer.weight
        if getattr(p, "_layout_perm", None) is not None:
            continue
        if p._data_map is None:
            # deferred init still pending — retry on the next call
            complete = False
            continue
        _relayout_param(p, layer._ndim)
        if trainer is not None:
            _relayout_states(trainer, p, p._layout_perm)
    if complete:
        object.__setattr__(block, "_layout_prepared", True)


def _iter_convs(block):
    from ..gluon.nn.conv_layers import _Conv

    seen = set()
    stack = [block]
    while stack:
        b = stack.pop()
        if id(b) in seen:
            continue
        seen.add(id(b))
        if isinstance(b, _Conv):
            yield b
        stack.extend(getattr(b, "_children", {}).values())


def _relayout_param(p, nd):
    """Device-side OIHW→HWIO transpose of every data (and grad) copy.
    ``p.shape`` stays LOGICAL; physical layout is ``p._layout_perm``."""
    perm = weight_perm(nd)
    for arr in p._data_map.values():
        arr._data = jnp.transpose(arr._data, perm)
        arr._version += 1
    # grads transpose WITHOUT a version bump: the Trainer's stale-grad
    # tracking compares versions, and a relayout is not a fresh gradient
    for g in (p._grad_map or {}).values():
        g._data = jnp.transpose(g._data, perm)
    p._layout_perm = perm


def _relayout_states(trainer, p, perm):
    """Best-effort: transpose momentum-class optimizer-state leaves
    (shaped like the logical weight) to match the new physical layout."""
    try:
        from ..ndarray.ndarray import NDArray

        states = getattr(trainer, "_states", None)
        params = getattr(trainer, "_params", None)
        if not states or params is None:
            return
        logical = tuple(p._shape or ())
        if len(logical) != len(perm):
            return

        def fix(leaf):
            if isinstance(leaf, NDArray) \
                    and tuple(leaf.shape) == logical:
                leaf._data = jnp.transpose(leaf._data, perm)
            return leaf

        for i, q in enumerate(params):
            if q is p and i < len(states) and states[i] is not None:
                jax.tree_util.tree_map(
                    fix, states[i],
                    is_leaf=lambda x: isinstance(x, NDArray))
    except Exception:
        pass


# ---------------------------------------------------------------------------
# the permutation-keyed lazy interpreter
# ---------------------------------------------------------------------------


def _ident(rank):
    return tuple(range(rank))


def _val_bytes(v):
    try:
        return int(v.size) * _np.dtype(v.dtype).itemsize
    except Exception:
        return 0


class _Stats:
    """One rewrite's accounting — lands in ctx.notes['layout'] and the
    layout_* telemetry counters."""

    __slots__ = ("convs_seen", "convs_rewritten", "convs_already_cl",
                 "bn_propagated", "act_propagated", "eqns_propagated",
                 "transposes_inserted", "inserted_bytes",
                 "transposes_absorbed", "benefit_bytes")

    def __init__(self):
        self.convs_seen = 0
        self.convs_rewritten = 0
        self.convs_already_cl = 0
        self.bn_propagated = 0
        self.act_propagated = 0
        self.eqns_propagated = 0
        self.transposes_inserted = 0
        self.inserted_bytes = 0
        self.transposes_absorbed = 0
        self.benefit_bytes = 0

    @property
    def naive_transposes(self):
        """What a naive PER-OP channels-last rewrite would pay: a
        transpose pair + weight relayout around every conv (3) and a
        pair around every propagated BN / activation (2)."""
        return (3 * self.convs_rewritten
                + 2 * (self.bn_propagated + self.act_propagated))

    @property
    def transposes_elided(self):
        return self.transposes_absorbed + max(
            0, self.naive_transposes - self.transposes_inserted)

    def as_dict(self):
        return {
            "convs_rewritten": self.convs_rewritten,
            "convs_already_cl": self.convs_already_cl,
            "bn_propagated": self.bn_propagated,
            "act_propagated": self.act_propagated,
            "eqns_propagated": self.eqns_propagated,
            "transposes_inserted": self.transposes_inserted,
            "transposes_elided": self.transposes_elided,
            "inserted_bytes": self.inserted_bytes,
            "benefit_bytes": self.benefit_bytes,
        }


# single-output shape-preserving primitives channels-last flows through
# untouched (lax-level operands of one eqn always share a shape; scalars
# pass unchanged)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "rem", "atan2",
    "and", "or", "xor", "not", "neg", "sign", "abs", "exp", "exp2",
    "expm1", "log", "log1p", "sqrt", "rsqrt", "cbrt", "tanh", "logistic",
    "erf", "erfc", "erf_inv", "sin", "cos", "tan", "sinh", "cosh",
    "asin", "acos", "atan", "floor", "ceil", "round", "is_finite",
    "integer_pow", "square", "convert_element_type", "select_n", "clamp",
    "nextafter", "eq", "ne", "lt", "le", "gt", "ge", "stop_gradient",
    "copy",
})
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_prod", "reduce_max", "reduce_min",
    "reduce_and", "reduce_or",
})
_RW_PRIMS = frozenset({
    "reduce_window_sum", "reduce_window_max", "reduce_window_min",
})


def _conv_perms(dn):
    """(lhs, rhs, out) permutations carrying each conv operand from the
    eqn's dimension_numbers to channels-last (NHWC / HWIO / NHWC),
    spatial order preserved — identity triple means the conv already IS
    channels-last.  Generic over rank and over deconv-style IO specs."""
    lhs_perm = (dn.lhs_spec[0],) + tuple(dn.lhs_spec[2:]) + (dn.lhs_spec[1],)
    rhs_perm = tuple(dn.rhs_spec[2:]) + (dn.rhs_spec[1], dn.rhs_spec[0])
    out_perm = (dn.out_spec[0],) + tuple(dn.out_spec[2:]) + (dn.out_spec[1],)
    return lhs_perm, rhs_perm, out_perm


def _closure_objects(fn, depth=0):
    if depth > 6 or not callable(fn):
        return
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        yield v
        if callable(v):
            yield from _closure_objects(v, depth + 1)


def _bn_target(eqn):
    """Recognize the framework's BN-training custom_vjp equation and
    recover its nondiff (eps, axis).  Returns (callable, eps, axis) —
    the exact function to RE-EMIT (never inline: the custom VJP is the
    closed-form backward) — or None.  Identity checks only; anything
    unrecognized stays a barrier."""
    if eqn.primitive.name != "custom_vjp_call_jaxpr":
        return None
    if eqn.params.get("num_consts") or len(eqn.invars) != 4 \
            or len(eqn.outvars) != 3:
        return None
    wf = getattr(eqn.params.get("bwd"), "__self__", None)
    f = getattr(wf, "f", None)
    if f is None:
        return None
    from ..ops import nn as _nn

    target = None
    if f is _nn._bn_train_bwd:
        target = _nn._bn_train
    else:
        try:
            from ..kernels import norm as _knorm
            if f is _knorm._bn_train_bwd:
                target = _knorm.bn_train
        except ImportError:
            pass
    if target is None:
        return None
    # nondiff args ride the WrappedFun's _add_args_ transform as
    # Unhashable wrappers: ((eps, axis) order matches nondiff_argnums)
    for t in getattr(wf, "transforms", ()):
        if getattr(t[0], "__name__", "") != "_add_args_":
            continue
        try:
            vals = tuple(getattr(a, "val", a) for a in t[1][0])
        except Exception:
            return None
        if len(vals) == 2:
            return target, float(vals[0]), int(vals[1])
    return None


def _is_relu(eqn):
    """Exact-identity recognition of jax.nn.relu's custom_jvp equation
    (re-emitting relu keeps its gradient-at-zero semantics; inlining the
    call_jaxpr would not)."""
    if eqn.primitive.name != "custom_jvp_call":
        return False
    if eqn.params.get("num_consts") or len(eqn.invars) != 1 \
            or len(eqn.outvars) != 1:
        return False
    target_jvp = getattr(jax.nn.relu, "jvp", None)
    if target_jvp is None:
        return False
    thunk = eqn.params.get("jvp_jaxpr_thunk")
    return any(getattr(o, "f", None) is target_jvp
               for o in _closure_objects(thunk) or ())


class _Interpreter:
    """Evaluates a jaxpr re-emitting ops channels-last where profitable.

    ``vals[var]`` maps permutation → traced value, where a value stored
    under perm p satisfies ``v == transpose(x_logical, p)``.  ``makers``
    hold permutation-polymorphic constructors (reshape/broadcast) that
    build a requested layout directly.  Reads materialize lazily; a
    transpose is emitted only when no stored perm or maker can satisfy
    the request — that emission is the ONLY place transposes enter the
    rewritten program."""

    def __init__(self, stats):
        self.vals = {}
        self.makers = {}
        self.stats = stats

    # -- env ---------------------------------------------------------------
    def write(self, var, val, perm=None):
        rank = len(getattr(var, "aval", val).shape) \
            if hasattr(var, "aval") else _np.ndim(val)
        perm = _ident(rank) if perm is None else tuple(perm)
        self.vals.setdefault(var, {})[perm] = val

    def stored_perm(self, atom):
        """A non-identity permutation already held for `atom` (the
        channels-last propagation signal), else None."""
        if isinstance(atom, jax.core.Literal):
            return None
        d = self.vals.get(atom)
        if not d:
            return None
        ident = _ident(len(atom.aval.shape))
        for p in d:
            if p != ident:
                return p
        return None

    def read(self, atom, perm=None):
        if isinstance(atom, jax.core.Literal):
            v = atom.val
            if perm is None or _np.ndim(v) == 0 \
                    or tuple(perm) == _ident(_np.ndim(v)):
                return v
            return _np.transpose(v, perm)
        rank = len(atom.aval.shape)
        perm = _ident(rank) if perm is None else tuple(perm)
        d = self.vals.setdefault(atom, {})
        if perm in d:
            return d[perm]
        mk = self.makers.get(atom)
        if mk is not None:
            v = mk(perm)
            if v is not None:
                d[perm] = v
                return v
        ident = _ident(rank)
        if ident in d:
            src_p, src_v = ident, d[ident]
        elif d:
            src_p, src_v = next(iter(d.items()))
        elif mk is not None:
            v = mk(ident)
            if v is None:
                raise RuntimeError(f"layout: cannot materialize {atom}")
            d[ident] = v
            src_p, src_v = ident, v
        else:
            raise RuntimeError(f"layout: unbound var {atom}")
        q = tuple(src_p.index(perm[i]) for i in range(rank))
        if q == ident:
            d[perm] = src_v
            return src_v
        out = lax.transpose(src_v, q)
        self.stats.transposes_inserted += 1
        self.stats.inserted_bytes += _val_bytes(out)
        d[perm] = out
        return out

    # -- fallback ----------------------------------------------------------
    def barrier(self, eqn):
        """Re-bind the equation VERBATIM on identity-layout operands —
        the safe default for everything the pass does not recognize."""
        vals = [self.read(a) for a in eqn.invars]
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        outs = eqn.primitive.bind(*subfuns, *vals, **bind_params)
        if not eqn.primitive.multiple_results:
            outs = [outs]
        for var, v in zip(eqn.outvars, outs):
            self.write(var, v)

    # -- rewrite rules -----------------------------------------------------
    def conv(self, eqn):
        from .memory import _aval_bytes

        self.stats.convs_seen += 1
        dn = eqn.params["dimension_numbers"]
        rank = len(dn.lhs_spec)
        ident = _ident(rank)
        lhs_perm, rhs_perm, out_perm = _conv_perms(dn)
        if lhs_perm == ident and out_perm == ident:
            # data already flows channels-last (NHWC-native layer);
            # re-conjugating just the kernel spec buys nothing
            self.stats.convs_already_cl += 1
            return self.barrier(eqn)
        x = self.read(eqn.invars[0], lhs_perm)
        w = self.read(eqn.invars[1], rhs_perm)
        new_spatial = tuple(range(1, rank - 1))
        params = dict(eqn.params)
        params["dimension_numbers"] = lax.ConvDimensionNumbers(
            lhs_spec=(0, rank - 1) + new_spatial,
            rhs_spec=(rank - 1, rank - 2) + tuple(range(rank - 2)),
            out_spec=(0, rank - 1) + new_spatial)
        out = eqn.primitive.bind(x, w, **params)
        self.write(eqn.outvars[0], out, out_perm)
        self.stats.convs_rewritten += 1
        self.stats.benefit_bytes += 2 * (
            _aval_bytes(eqn.invars[0].aval)
            + _aval_bytes(eqn.outvars[0].aval))

    def bn(self, eqn, target, eps, axis):
        xvar = eqn.invars[0]
        rank = len(xvar.aval.shape)
        axis = axis % rank
        if axis == rank - 1:
            return self.barrier(eqn)  # already channels-last
        p = self.stored_perm(xvar)
        if p is None or p[-1] != axis:
            # send the channel axis last, other dims keeping order
            p = tuple(i for i in range(rank) if i != axis) + (axis,)
        x = self.read(xvar, p)
        gamma = self.read(eqn.invars[1])
        beta = self.read(eqn.invars[2])
        shift = self.read(eqn.invars[3])
        out, mean, var = target(x, gamma, beta, shift,
                                float(eps), int(p.index(axis)))
        self.write(eqn.outvars[0], out, p)
        self.write(eqn.outvars[1], mean)
        self.write(eqn.outvars[2], var)
        self.stats.bn_propagated += 1

    def relu(self, eqn):
        p = self.stored_perm(eqn.invars[0])
        if p is None:
            return self.barrier(eqn)
        out = jax.nn.relu(self.read(eqn.invars[0], p))
        self.write(eqn.outvars[0], out, p)
        self.stats.act_propagated += 1

    def transpose(self, eqn):
        xvar = eqn.invars[0]
        if isinstance(xvar, jax.core.Literal):
            return self.barrier(eqn)
        q = tuple(eqn.params["permutation"])
        d = self.vals.get(xvar)
        mk = self.makers.get(xvar)
        if not d and mk is None:
            return self.barrier(eqn)
        out_var = eqn.outvars[0]
        rank = len(q)
        if d:
            # absorb: out stored under s holds transpose(x, s∘q) with
            # (s∘q)[i] = q[s[i]]; pick s so s∘q is a perm we already hold
            ident = _ident(rank)
            p0, v0 = (ident, d[ident]) if ident in d \
                else next(iter(d.items()))
            s = tuple(q.index(p0[i]) for i in range(rank))
            self.write(out_var, v0, s)
        else:
            def out_maker(s, _mk=mk, _q=q):
                return _mk(tuple(_q[i] for i in s))
            self.makers[out_var] = out_maker
        self.stats.transposes_absorbed += 1

    def reshape(self, eqn):
        xvar = eqn.invars[0]
        if eqn.params.get("dimensions") is not None \
                or isinstance(xvar, jax.core.Literal):
            return self.barrier(eqn)
        new_sizes = tuple(eqn.params["new_sizes"])
        out_rank = len(new_sizes)
        out_nonsing = sum(1 for dim in new_sizes if dim != 1)
        x_shape = tuple(xvar.aval.shape)
        env = self

        def order_ok(p):
            # transpose(x, p) keeps x's row-major element order iff the
            # non-singleton dims keep their relative order under p
            pos = [p.index(i) for i in range(len(x_shape))
                   if x_shape[i] != 1]
            return pos == sorted(pos)

        def maker(s):
            if s != _ident(out_rank) and out_nonsing > 1:
                return None  # read() materializes identity + transpose
            target = tuple(new_sizes[s[i]] for i in range(out_rank))
            src = next((v for p, v in env.vals.get(xvar, {}).items()
                        if order_ok(p)), None)
            if src is None:
                src = env.read(xvar)
            return jnp.reshape(src, target)

        self.makers[eqn.outvars[0]] = maker

    def broadcast(self, eqn):
        xvar = eqn.invars[0]
        shape = tuple(eqn.params["shape"])
        bd = tuple(eqn.params["broadcast_dimensions"])
        out_rank = len(shape)
        env = self

        def maker(s):
            target = tuple(shape[s[i]] for i in range(out_rank))
            inv_s = {dim: i for i, dim in enumerate(s)}
            if isinstance(xvar, jax.core.Literal):
                cands = [(_ident(_np.ndim(xvar.val)), xvar.val)]
            else:
                ident = _ident(len(xvar.aval.shape))
                cands = sorted(env.vals.get(xvar, {}).items(),
                               key=lambda kv: kv[0] != ident)
            for p, v in cands:
                nbd = tuple(inv_s[bd[p[k]]] for k in range(len(p)))
                if all(nbd[j] < nbd[j + 1] for j in range(len(nbd) - 1)):
                    return lax.broadcast_in_dim(v, target, nbd)
            if s == _ident(out_rank):
                return lax.broadcast_in_dim(env.read(xvar), shape, bd)
            return None

        self.makers[eqn.outvars[0]] = maker

    def reduce(self, eqn):
        xvar = eqn.invars[0]
        p = self.stored_perm(xvar)
        if p is None:
            return self.barrier(eqn)
        axes = tuple(eqn.params["axes"])
        new_axes = tuple(sorted(p.index(a) for a in axes))
        kept = [p[k] for k in range(len(p)) if k not in set(new_axes)]
        if kept != sorted(kept):
            # surviving dims would come out permuted — materialize instead
            return self.barrier(eqn)
        v = self.read(xvar, p)
        bp = dict(eqn.params)
        bp["axes"] = new_axes
        subfuns, bind_params = eqn.primitive.get_bind_params(bp)
        out = eqn.primitive.bind(*subfuns, v, **bind_params)
        self.write(eqn.outvars[0], out)
        self.stats.eqns_propagated += 1

    def reduce_window(self, eqn):
        xvar = eqn.invars[0]
        p = self.stored_perm(xvar)
        if p is None:
            return self.barrier(eqn)
        v = self.read(xvar, p)
        bp = dict(eqn.params)
        for k in ("window_dimensions", "window_strides", "base_dilation",
                  "window_dilation", "padding"):
            old = tuple(bp[k])
            bp[k] = tuple(old[p[i]] for i in range(len(p)))
        subfuns, bind_params = eqn.primitive.get_bind_params(bp)
        out = eqn.primitive.bind(*subfuns, v, **bind_params)
        self.write(eqn.outvars[0], out, p)
        self.stats.eqns_propagated += 1

    def opt_barrier(self, eqn):
        perms, vals = [], []
        for a in eqn.invars:
            p = self.stored_perm(a)
            perms.append(p)
            vals.append(self.read(a, p))
        outs = eqn.primitive.bind(*vals)
        for var, p, v in zip(eqn.outvars, perms, outs):
            self.write(var, v, p)

    def elementwise(self, eqn):
        p = None
        rank = 0
        for a in eqn.invars:
            sh = _np.shape(a.val) if isinstance(a, jax.core.Literal) \
                else a.aval.shape
            if len(sh) == 0:
                continue
            if rank and len(sh) != rank:
                return self.barrier(eqn)  # unexpected mixed ranks
            rank = len(sh)
            if p is None:
                p = self.stored_perm(a)
        if p is None or len(p) != rank:
            return self.barrier(eqn)
        vals = []
        for a in eqn.invars:
            sh = _np.shape(a.val) if isinstance(a, jax.core.Literal) \
                else a.aval.shape
            vals.append(self.read(a, p if len(sh) else None))
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        out = eqn.primitive.bind(*subfuns, *vals, **bind_params)
        self.write(eqn.outvars[0], out, p)
        self.stats.eqns_propagated += 1

    # -- driver ------------------------------------------------------------
    def run(self, closed, args):
        jaxpr = closed.jaxpr
        for var, val in zip(jaxpr.constvars, closed.consts):
            self.write(var, val)
        for var, val in zip(jaxpr.invars, args):
            self.write(var, val)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name == "conv_general_dilated":
                self.conv(eqn)
            elif name == "transpose":
                self.transpose(eqn)
            elif name == "reshape":
                self.reshape(eqn)
            elif name == "broadcast_in_dim":
                self.broadcast(eqn)
            elif name in _REDUCE_PRIMS:
                self.reduce(eqn)
            elif name in _RW_PRIMS:
                self.reduce_window(eqn)
            elif name == "optimization_barrier":
                self.opt_barrier(eqn)
            elif name == "custom_vjp_call_jaxpr":
                bn = _bn_target(eqn)
                if bn is not None:
                    self.bn(eqn, *bn)
                else:
                    self.barrier(eqn)
            elif name == "custom_jvp_call" and _is_relu(eqn):
                self.relu(eqn)
            elif name in _ELEMENTWISE and len(eqn.outvars) == 1:
                self.elementwise(eqn)
            else:
                self.barrier(eqn)
        # outvars read at identity: surviving transposes sink to the edges
        return [self.read(v) for v in jaxpr.outvars]


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def _scan_convs(jaxpr):
    """(channels_first_convs, total_convs, activation_bytes) of the
    top-level conv equations — the zero-cost pre-gate."""
    from .memory import _aval_bytes

    cf = total = act_bytes = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name != "conv_general_dilated":
            continue
        total += 1
        dn = eqn.params["dimension_numbers"]
        ident = _ident(len(dn.lhs_spec))
        lhs_perm, _, out_perm = _conv_perms(dn)
        if lhs_perm == ident and out_perm == ident:
            continue  # data already channels-last; kernel spec is moot
        cf += 1
        act_bytes += (_aval_bytes(eqn.invars[0].aval)
                      + _aval_bytes(eqn.outvars[0].aval))
    return cf, total, act_bytes


class LayoutPass(GraphPass):
    """Whole-graph channels-last rewrite (module docstring has the full
    story).  Priority 20: after AmpPass(10) fixed dtypes (the byte-model
    scoring must see them) and before KernelPass(40) audits the program
    XLA will actually compile.  Never fails a build — any internal error
    returns the program unchanged with the error in ctx.notes."""

    name = "layout"
    priority = 20
    kinds = ("block", "export", "whole_step", "whole_step_fwd")

    def __init__(self, mode=None):
        # a forced mode serves the MXTPU_PASSES=layout named-pass path;
        # None defers to MXTPU_LAYOUT at run time
        self._forced = mode

    def run(self, closed, ctx):
        try:
            return self._run(closed, ctx)
        except Exception as exc:
            ctx.notes["layout"] = {"error": repr(exc)}
            return closed

    def _run(self, closed, ctx):
        m = self._forced if self._forced is not None else mode()
        note = {"mode": m, "kind": ctx.kind}
        ctx.notes["layout"] = note
        if m == "off":
            note["decision"] = "off"
            return closed
        cf, total, act_bytes = _scan_convs(closed.jaxpr)
        note["convs_seen"] = total
        note["convs_channels_first"] = cf
        if cf == 0:
            # nothing to do: no retrace, no interpreter — the common
            # steady-state (weights pre-laid-out, convs already NHWC)
            note["decision"] = "no_cf_convs"
            return closed
        if ctx.kind == "whole_step":
            # the loss forward was already rewritten at its own
            # whole_step_fwd seam; convs surviving HERE are AD-generated
            # gradient convs whose layouts derive from the rewritten
            # forward — re-conjugating them would fight XLA's own
            # transpose folding, so the outer seam only audits
            note["decision"] = "audit_only"
            return closed
        if m == "auto" and act_bytes < min_bytes():
            note["decision"] = "too_small"
            note["conv_activation_bytes"] = act_bytes
            return closed
        stats = _Stats()

        def rewritten(*flat):
            return tuple(_Interpreter(stats).run(closed, flat))

        new_closed = _manager.retrace_flat(rewritten, closed)
        note.update(stats.as_dict())
        if m == "auto" and stats.benefit_bytes <= 2 * stats.inserted_bytes:
            # boundary transposes rival the predicted conv-side win
            note["decision"] = "declined_no_savings"
            return closed
        note["decision"] = "rewritten"
        _telemetry.record_layout_rewrite(
            stats.convs_rewritten, stats.transposes_inserted,
            stats.transposes_elided)
        return new_closed
