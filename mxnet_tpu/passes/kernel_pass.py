"""KernelPass — the pass-pipeline face of the bandwidth kernels.

The kernels themselves are chosen at TRACE time by their call sites
(ops/nn.py, optimizer/optimizer.py consulting kernels/dispatch.py):
rewriting a finished jaxpr can't recover a custom-VJP's nondiff
arguments, and site dispatch is what keeps ``MXTPU_KERNELS=off``
bitwise-exact.  What the pipeline CAN do — and this pass does — is
audit the captured program after the sites have spoken:

* census the ``pallas_call`` equations that actually landed in the
  graph (how many sites adopted a kernel);
* run the promoted byte model (:func:`passes.memory.estimate_region_bytes`)
  over the program and report the residual top external-byte regions —
  the regions a FUTURE kernel should target next;
* publish both in ``ctx.notes["kernels"]`` so seam owners, tests and
  `tools/fusion_audit.py --report` read one consistent account.

Priority 40 places the audit after AmpPass(10) has rewritten dtypes —
the byte model must see the dtypes XLA will see — and before
RematPass(90) duplicates region interiors, which would double-count
bytes that never hit HBM twice.  The pass never edits the jaxpr; it is
injected by :func:`manager.resolve_passes` whenever MXTPU_KERNELS is
not off.
"""
from __future__ import annotations

from .manager import GraphPass

__all__ = ["KernelPass", "audit_jaxpr"]

# report at most this many residual regions per seam — notes ride in
# every pipeline entry and postmortem bundle, keep them bounded
_TOP_REGIONS = 8


def audit_jaxpr(closed):
    """The KernelPass audit of one captured program: pallas_call census
    plus the byte model's residual hot regions."""
    from . import memory as _memory

    n_pallas = 0

    def _walk(jaxpr):
        nonlocal n_pallas
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                n_pallas += 1
            for v in eqn.params.values():
                sub = getattr(v, "jaxpr", None)
                if sub is not None:
                    _walk(sub)

    _walk(closed.jaxpr)
    regions = _memory.estimate_region_bytes(closed)
    top = [{"external_bytes": r["external_bytes"],
            "eqns": r["eqns"],
            "prims": dict(sorted(r["prims"].items(),
                                 key=lambda kv: -kv[1])[:6])}
           for r in regions[:_TOP_REGIONS]]
    return {
        "pallas_calls": n_pallas,
        "regions": len(regions),
        "external_bytes_total": sum(r["external_bytes"] for r in regions),
        "top_regions": top,
    }


class KernelPass(GraphPass):
    """Audit-only pass: reports kernel adoption and residual HBM-bound
    regions for the seam being built.  See module docstring."""

    name = "kernels"
    priority = 40
    kinds = ("block", "whole_step_fwd", "whole_step")

    def run(self, closed_jaxpr, ctx):
        try:
            ctx.notes["kernels"] = audit_jaxpr(closed_jaxpr)
        except Exception as exc:  # audit must never fail a build
            ctx.notes["kernels"] = {"error": repr(exc)}
        return closed_jaxpr
