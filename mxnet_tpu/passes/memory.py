"""Peak-residency estimation: a liveness walk over a captured jaxpr.

The reference stack's NNVM memory planner assigns storage by walking
the graph in topological order and freeing buffers at their last use;
the peak of that walk is the plan's residency requirement.  This module
runs the same walk over a jaxpr (recursing into pjit/remat2/custom-call
sub-jaxprs) and reports the peak live bytes — a backend-independent
estimate the remat `auto` policy and the diagnostics compile registry
use.  XLA's own `memory_analysis().temp_size_in_bytes` is not usable
for this on CPU: it reports the SUM of temp allocations, not a
liveness-packed peak, so rematerialization never changes it there.

The estimate is an upper-bound-ish approximation (no buffer aliasing,
no fusion eliding intermediates), but it moves the right way: wrapping
segments in ``jax.checkpoint`` drops forward activations from the
backward program's live set, and the walk sees exactly that.
"""
from __future__ import annotations

import itertools

import numpy as np

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

__all__ = [
    "estimate_peak_bytes",
    "estimate_training_peak_bytes",
    "estimate_region_bytes",
    "norm_region_bytes",
    "optimizer_region_bytes",
]

# Call-like primitives whose sub-jaxpr binds the eqn's operands 1:1 —
# safe to inline into the walk.  Loop/branch primitives (scan, while,
# cond) slice or select their operands, so they stay opaque: their
# outputs are counted, their bodies are not expanded.
_INLINE_PRIMS = ("pjit", "remat2", "closed_call", "core_call",
                 "custom_jvp_call", "custom_vjp_call",
                 "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")


def _aval_bytes(aval):
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic dim
            n *= 1
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG key arrays) — itemsize if exposed
        itemsize = getattr(dtype, "itemsize", 4)
    return n * itemsize


def _sub_jaxpr(eqn):
    """(inner Jaxpr, inner consts) when the eqn is an inlineable call,
    else None."""
    if eqn.primitive.name not in _INLINE_PRIMS:
        return None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        if hasattr(sub, "jaxpr"):  # ClosedJaxpr
            inner, consts = sub.jaxpr, list(sub.consts)
        else:
            inner, consts = sub, []
        if len(inner.invars) == len(eqn.invars):
            return inner, consts
    return None


def estimate_peak_bytes(closed):
    """Peak live bytes of one program: walk eqns in order, allocate
    outputs, free each value after its last use.  Program inputs,
    consts and outputs stay resident for the whole walk (they are real
    buffers XLA holds)."""
    jaxpr = closed.jaxpr
    counter = itertools.count()
    token_bytes = {}
    steps = []  # (in_tokens, out_tokens) per flattened eqn

    def new_token(aval):
        t = next(counter)
        token_bytes[t] = _aval_bytes(aval)
        return t

    def walk(j, in_tokens, const_tokens):
        env = {}
        for v, t in zip(j.constvars, const_tokens):
            env[id(v)] = t
        for v, t in zip(j.invars, in_tokens):
            env[id(v)] = t

        def read(v):
            if isinstance(v, jcore.Literal):
                return None
            return env.get(id(v))

        for eqn in j.eqns:
            ins = [read(v) for v in eqn.invars]
            sub = _sub_jaxpr(eqn)
            if sub is not None:
                inner, consts = sub
                const_ts = [new_token(jax.api_util.shaped_abstractify(c))
                            for c in consts]
                inner_outs = walk(inner, ins, const_ts)
                for v, t in zip(eqn.outvars, inner_outs):
                    if t is None:  # inner returned a literal
                        t = new_token(v.aval)
                        steps.append(((), (t,)))
                    env[id(v)] = t
            else:
                outs = []
                for v in eqn.outvars:
                    t = new_token(v.aval)
                    env[id(v)] = t
                    outs.append(t)
                steps.append((tuple(t for t in ins if t is not None),
                              tuple(outs)))
        return [read(v) for v in j.outvars]

    in_ts = [new_token(v.aval) for v in jaxpr.invars]
    const_ts = [new_token(v.aval) for v in jaxpr.constvars]
    out_ts = walk(jaxpr, in_ts, const_ts)

    last_use = {}
    for i, (ins, _) in enumerate(steps):
        for t in ins:
            last_use[t] = i
    pinned = set(in_ts) | set(const_ts)
    pinned.update(t for t in out_ts if t is not None)

    current = set(in_ts) | set(const_ts)
    cur = sum(token_bytes[t] for t in current)
    peak = cur
    for i, (ins, outs) in enumerate(steps):
        for t in outs:
            if t not in current:
                current.add(t)
                cur += token_bytes[t]
        peak = max(peak, cur)
        for t in set(ins) | set(outs):
            # free at last use; dead values (never read) free immediately
            if (t in current and t not in pinned
                    and last_use.get(t, -1) <= i):
                current.remove(t)
                cur -= token_bytes[t]
    return int(peak)


def estimate_training_peak_bytes(closed):
    """Peak live bytes of the fwd+bwd program derived from a forward
    jaxpr: grad of the summed float outputs w.r.t. every float input —
    the program whose residency rematerialization actually changes.
    Falls back to the forward-only estimate when the program has no
    float outputs or inputs to differentiate."""
    jaxpr = closed.jaxpr

    def _is_float(aval):
        dtype = getattr(aval, "dtype", None)
        return dtype is not None and jnp.issubdtype(dtype, jnp.floating)

    argnums = tuple(i for i, v in enumerate(jaxpr.invars)
                    if _is_float(v.aval))
    has_float_out = any(_is_float(v.aval) for v in jaxpr.outvars)
    if not argnums or not has_float_out:
        return estimate_peak_bytes(closed)

    def scalar_loss(*flat):
        outs = jax.core.eval_jaxpr(jaxpr, closed.consts, *flat)
        total = jnp.zeros((), jnp.float32)
        for o in outs:
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype,
                                                      jnp.floating):
                total = total + jnp.sum(o.astype(jnp.float32))
        return total

    sds = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
           for v in jaxpr.invars]
    grad_closed = jax.make_jaxpr(
        jax.grad(scalar_loss, argnums=argnums))(*sds)
    return estimate_peak_bytes(grad_closed)


# ---------------------------------------------------------------------------
# per-region external-bytes model (promoted from tools/fusion_audit.py)
# ---------------------------------------------------------------------------
#
# tools/fusion_audit.py runs this segmentation over the lowered StableHLO
# text of a whole train step (union-find of fusable ops; external bytes =
# cross-region SSA edges).  The KernelPass `auto` decision needs the SAME
# model at the jaxpr level — before lowering, per call site — so the
# segmentation is promoted here, on top of the liveness walk's flattening
# (_sub_jaxpr / _aval_bytes).
#
# Calibration: the r5 audit's empirical finding is that XLA on TPU treats
# REDUCTIONS and large WIDENING CONVERTS as fusion roots — their producers
# fuse in, their consumers start a new kernel, so the value at the boundary
# round-trips through HBM.  That is exactly what made the BN-stats f32
# population the worst region of the step.  The model below encodes it:
#
#   * anchor prims (conv/dot/gather/...) are their own region;
#   * reduce prims and >=`widen_threshold`-byte widening converts are
#     fusion ROOTS: they merge upstream, and everything downstream of
#     their output belongs to a later region (tracked by a per-value
#     "root generation" — a step only merges with producers of its own
#     generation);
#   * everything else elementwise-ish merges freely within a generation;
#   * a region's external bytes = bytes of values crossing its boundary
#     (inputs produced outside + outputs consumed outside), the HBM
#     traffic a perfectly-fused XLA schedule still pays.

_ANCHOR_PRIMS = frozenset((
    "conv_general_dilated", "dot_general", "reduce_window_sum",
    "reduce_window_max", "reduce_window_min", "scatter", "scatter-add",
    "scatter_add", "gather", "sort", "dynamic_slice", "dynamic_update_slice",
    "iota", "rng_bit_generator", "random_bits", "fft", "custom_call",
    "pallas_call", "while", "scan", "cond",
))

_REDUCE_PRIMS = frozenset((
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "reduce_precision",
    "cumsum", "cumlogsumexp", "cummax", "cummin", "cumprod",
))


def _flatten_steps(closed):
    """Flatten a ClosedJaxpr (inlining _INLINE_PRIMS sub-jaxprs, the same
    walk estimate_peak_bytes does) into a step list for the region model:
    (prim_name, in_tokens, out_tokens).  Returns (steps, token_bytes,
    input_tokens, output_tokens, token_dtype_size)."""
    jaxpr = closed.jaxpr
    counter = itertools.count()
    token_bytes = {}
    token_itemsize = {}
    steps = []

    def new_token(aval):
        t = next(counter)
        token_bytes[t] = _aval_bytes(aval)
        try:
            token_itemsize[t] = np.dtype(
                getattr(aval, "dtype", np.float32)).itemsize
        except TypeError:
            token_itemsize[t] = 4
        return t

    def walk(j, in_tokens, const_tokens):
        env = {}
        for v, t in zip(j.constvars, const_tokens):
            env[id(v)] = t
        for v, t in zip(j.invars, in_tokens):
            env[id(v)] = t

        def read(v):
            if isinstance(v, jcore.Literal):
                return None
            return env.get(id(v))

        for eqn in j.eqns:
            ins = [read(v) for v in eqn.invars]
            sub = _sub_jaxpr(eqn)
            if sub is not None:
                inner, consts = sub
                const_ts = [new_token(jax.api_util.shaped_abstractify(c))
                            for c in consts]
                inner_outs = walk(inner, ins, const_ts)
                for v, t in zip(eqn.outvars, inner_outs):
                    if t is None:
                        t = new_token(v.aval)
                        steps.append(("literal", (), (t,)))
                    env[id(v)] = t
            else:
                outs = []
                for v in eqn.outvars:
                    t = new_token(v.aval)
                    env[id(v)] = t
                    outs.append(t)
                steps.append((eqn.primitive.name,
                              tuple(t for t in ins if t is not None),
                              tuple(outs)))
        return [read(v) for v in j.outvars]

    in_ts = [new_token(v.aval) for v in jaxpr.invars]
    const_ts = [new_token(v.aval) for v in jaxpr.constvars]
    out_ts = walk(jaxpr, in_ts, const_ts)
    boundary_in = set(in_ts) | set(const_ts)
    boundary_out = set(t for t in out_ts if t is not None)
    return steps, token_bytes, token_itemsize, boundary_in, boundary_out


def estimate_region_bytes(closed, widen_threshold=1 << 20):
    """Segment one captured jaxpr into XLA-fusion regions and charge each
    region its external HBM bytes.  Returns regions sorted by external
    bytes, descending:

        [{"eqns": int, "external_bytes": int, "input_bytes": int,
          "output_bytes": int, "prims": {name: count}}, ...]

    `widen_threshold`: widening converts producing at least this many
    bytes are treated as fusion roots (the audit's empirical
    f32-materialization boundary); smaller ones fuse like elementwise.
    """
    steps, token_bytes, token_itemsize, boundary_in, boundary_out = \
        _flatten_steps(closed)

    producer = {}
    consumers = {}
    for i, (_, ins, outs) in enumerate(steps):
        for t in outs:
            producer[t] = i
        for t in ins:
            consumers.setdefault(t, []).append(i)

    def kind_of(i):
        prim, ins, outs = steps[i]
        if prim in _ANCHOR_PRIMS:
            return "anchor"
        if prim in _REDUCE_PRIMS or prim.startswith("reduce_"):
            return "root"
        if prim == "convert_element_type" and ins and outs:
            if (token_itemsize[outs[0]] > token_itemsize[ins[0]]
                    and token_bytes[outs[0]] >= widen_threshold):
                return "root"
        return "fuse"

    kinds = [kind_of(i) for i in range(len(steps))]

    # root generation per value: consumers of a root output live one
    # generation later, so they can never merge back across the boundary
    gen = {t: 0 for t in boundary_in}
    step_gen = [0] * len(steps)
    for i, (_, ins, outs) in enumerate(steps):
        g = max((gen.get(t, 0) for t in ins), default=0)
        step_gen[i] = g
        out_g = g + 1 if kinds[i] == "root" else g
        for t in outs:
            gen[t] = out_g

    parent = list(range(len(steps)))

    def find(i):
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for i, (_, ins, _) in enumerate(steps):
        if kinds[i] == "anchor":
            continue
        for t in ins:
            j = producer.get(t)
            if j is None or kinds[j] == "anchor":
                continue
            # merge with same-generation producers only: a root merges
            # upstream (its inputs share its generation), while steps
            # downstream of a root output carry a later generation and
            # stay in their own region
            if step_gen[j] == step_gen[i] and kinds[j] != "root":
                union(i, j)
            elif kinds[j] == "root" and kinds[i] == "root" \
                    and step_gen[j] == step_gen[i]:
                union(i, j)

    regions = {}
    for i in range(len(steps)):
        if kinds[i] == "anchor":
            continue
        regions.setdefault(find(i), []).append(i)

    out = []
    for members in regions.values():
        mset = set(members)
        in_bytes = out_bytes = 0
        seen_in, seen_out = set(), set()
        prims = {}
        for i in members:
            prim, ins, outs = steps[i]
            prims[prim] = prims.get(prim, 0) + 1
            for t in ins:
                if t in seen_in:
                    continue
                j = producer.get(t)
                if j is None or j not in mset:
                    seen_in.add(t)
                    in_bytes += token_bytes[t]
            for t in outs:
                if t in seen_out:
                    continue
                used_outside = t in boundary_out or any(
                    c not in mset for c in consumers.get(t, ()))
                if used_outside:
                    seen_out.add(t)
                    out_bytes += token_bytes[t]
        out.append({
            "eqns": len(members),
            "external_bytes": in_bytes + out_bytes,
            "input_bytes": in_bytes,
            "output_bytes": out_bytes,
            "prims": dict(sorted(prims.items(), key=lambda kv: -kv[1])),
        })
    out.sort(key=lambda r: -r["external_bytes"])
    return out


# -- analytic per-site models (what the `auto` dispatch decision reads) -----
#
# The jaxpr segmentation above is the honest accounting over a whole
# captured program (the KernelPass report, the >=30% acceptance test);
# at a single call site the region shapes are known in closed form, so
# the dispatch decision uses these O(1) per-channel-ignoring formulas.
# Both express the same model: reduce/widen roots break the XLA program
# into passes that round-trip the population through HBM; the Pallas
# kernel's floor is one (or two, for two-phase stats) reads of the
# operands plus one write of each output.

def _itemsize(dtype):
    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        return 4


def norm_region_bytes(shape, x_dtype, ew_dtype):
    """(xla_bytes, kernel_bytes) for ONE BatchNorm training call site —
    forward and backward regions combined (a site either uses the kernel
    pair or neither: the residual layout must match).

    XLA (per the root model): fwd reads x, round-trips the centered
    population xf across the sum/sum² reduce boundary, writes out; bwd
    reads x and dy, round-trips xhat and the cast dy across the
    dbeta/dgamma reduce boundary, writes dx.  Kernel: fwd reads x twice
    (two-phase stats) and writes out; bwd reads x and dy twice and
    writes dx.  Per-channel vectors are noise and ignored."""
    n = 1
    for d in shape:
        n *= int(d)
    bx = _itemsize(x_dtype)
    be = _itemsize(ew_dtype)
    xla_fwd = n * bx + 2 * n * be + n * bx
    xla_bwd = 2 * n * bx + 4 * n * be + n * bx
    k_fwd = 2 * n * bx + n * bx
    k_bwd = 2 * (2 * n * bx) + n * bx
    return xla_fwd + xla_bwd, k_fwd + k_bwd


def optimizer_region_bytes(w_size, w_dtype, n_state, mp):
    """(xla_bytes, kernel_bytes) for ONE parameter's fused-update chain.

    The floor both paths pay: read grad, read+write each state leaf,
    read+write the master/weight, write the low-precision weight copy
    (mp).  XLA additionally round-trips the widened f32 grad across the
    mp cast boundary (the audit's optimizer-chain region); without mp
    there is no widening root, the chain is one region, and the model
    predicts zero savings — `auto` declines, which is correct: XLA
    already fuses the pure-f32 chain perfectly."""
    n = int(w_size)
    bw = _itemsize(w_dtype)
    if mp:
        floor = (n * bw            # read low-precision grad
                 + 2 * n * 4       # master read+write
                 + n_state * 2 * n * 4  # state leaves read+write (f32)
                 + n * bw)         # write low-precision weight copy
        xla = floor + 2 * n * 4    # g32 round-trip at the cast root
        return xla, floor
    floor = (n * bw + 2 * n * bw + n_state * 2 * n * bw)
    return floor, floor
