"""Peak-residency estimation: a liveness walk over a captured jaxpr.

The reference stack's NNVM memory planner assigns storage by walking
the graph in topological order and freeing buffers at their last use;
the peak of that walk is the plan's residency requirement.  This module
runs the same walk over a jaxpr (recursing into pjit/remat2/custom-call
sub-jaxprs) and reports the peak live bytes — a backend-independent
estimate the remat `auto` policy and the diagnostics compile registry
use.  XLA's own `memory_analysis().temp_size_in_bytes` is not usable
for this on CPU: it reports the SUM of temp allocations, not a
liveness-packed peak, so rematerialization never changes it there.

The estimate is an upper-bound-ish approximation (no buffer aliasing,
no fusion eliding intermediates), but it moves the right way: wrapping
segments in ``jax.checkpoint`` drops forward activations from the
backward program's live set, and the walk sees exactly that.
"""
from __future__ import annotations

import itertools

import numpy as np

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

__all__ = [
    "estimate_peak_bytes",
    "estimate_training_peak_bytes",
]

# Call-like primitives whose sub-jaxpr binds the eqn's operands 1:1 —
# safe to inline into the walk.  Loop/branch primitives (scan, while,
# cond) slice or select their operands, so they stay opaque: their
# outputs are counted, their bodies are not expanded.
_INLINE_PRIMS = ("pjit", "remat2", "closed_call", "core_call",
                 "custom_jvp_call", "custom_vjp_call",
                 "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")


def _aval_bytes(aval):
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # symbolic dim
            n *= 1
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG key arrays) — itemsize if exposed
        itemsize = getattr(dtype, "itemsize", 4)
    return n * itemsize


def _sub_jaxpr(eqn):
    """(inner Jaxpr, inner consts) when the eqn is an inlineable call,
    else None."""
    if eqn.primitive.name not in _INLINE_PRIMS:
        return None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        if hasattr(sub, "jaxpr"):  # ClosedJaxpr
            inner, consts = sub.jaxpr, list(sub.consts)
        else:
            inner, consts = sub, []
        if len(inner.invars) == len(eqn.invars):
            return inner, consts
    return None


def estimate_peak_bytes(closed):
    """Peak live bytes of one program: walk eqns in order, allocate
    outputs, free each value after its last use.  Program inputs,
    consts and outputs stay resident for the whole walk (they are real
    buffers XLA holds)."""
    jaxpr = closed.jaxpr
    counter = itertools.count()
    token_bytes = {}
    steps = []  # (in_tokens, out_tokens) per flattened eqn

    def new_token(aval):
        t = next(counter)
        token_bytes[t] = _aval_bytes(aval)
        return t

    def walk(j, in_tokens, const_tokens):
        env = {}
        for v, t in zip(j.constvars, const_tokens):
            env[id(v)] = t
        for v, t in zip(j.invars, in_tokens):
            env[id(v)] = t

        def read(v):
            if isinstance(v, jcore.Literal):
                return None
            return env.get(id(v))

        for eqn in j.eqns:
            ins = [read(v) for v in eqn.invars]
            sub = _sub_jaxpr(eqn)
            if sub is not None:
                inner, consts = sub
                const_ts = [new_token(jax.api_util.shaped_abstractify(c))
                            for c in consts]
                inner_outs = walk(inner, ins, const_ts)
                for v, t in zip(eqn.outvars, inner_outs):
                    if t is None:  # inner returned a literal
                        t = new_token(v.aval)
                        steps.append(((), (t,)))
                    env[id(v)] = t
            else:
                outs = []
                for v in eqn.outvars:
                    t = new_token(v.aval)
                    env[id(v)] = t
                    outs.append(t)
                steps.append((tuple(t for t in ins if t is not None),
                              tuple(outs)))
        return [read(v) for v in j.outvars]

    in_ts = [new_token(v.aval) for v in jaxpr.invars]
    const_ts = [new_token(v.aval) for v in jaxpr.constvars]
    out_ts = walk(jaxpr, in_ts, const_ts)

    last_use = {}
    for i, (ins, _) in enumerate(steps):
        for t in ins:
            last_use[t] = i
    pinned = set(in_ts) | set(const_ts)
    pinned.update(t for t in out_ts if t is not None)

    current = set(in_ts) | set(const_ts)
    cur = sum(token_bytes[t] for t in current)
    peak = cur
    for i, (ins, outs) in enumerate(steps):
        for t in outs:
            if t not in current:
                current.add(t)
                cur += token_bytes[t]
        peak = max(peak, cur)
        for t in set(ins) | set(outs):
            # free at last use; dead values (never read) free immediately
            if (t in current and t not in pinned
                    and last_use.get(t, -1) <= i):
                current.remove(t)
                cur -= token_bytes[t]
    return int(peak)


def estimate_training_peak_bytes(closed):
    """Peak live bytes of the fwd+bwd program derived from a forward
    jaxpr: grad of the summed float outputs w.r.t. every float input —
    the program whose residency rematerialization actually changes.
    Falls back to the forward-only estimate when the program has no
    float outputs or inputs to differentiate."""
    jaxpr = closed.jaxpr

    def _is_float(aval):
        dtype = getattr(aval, "dtype", None)
        return dtype is not None and jnp.issubdtype(dtype, jnp.floating)

    argnums = tuple(i for i, v in enumerate(jaxpr.invars)
                    if _is_float(v.aval))
    has_float_out = any(_is_float(v.aval) for v in jaxpr.outvars)
    if not argnums or not has_float_out:
        return estimate_peak_bytes(closed)

    def scalar_loss(*flat):
        outs = jax.core.eval_jaxpr(jaxpr, closed.consts, *flat)
        total = jnp.zeros((), jnp.float32)
        for o in outs:
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype,
                                                      jnp.floating):
                total = total + jnp.sum(o.astype(jnp.float32))
        return total

    sds = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
           for v in jaxpr.invars]
    grad_closed = jax.make_jaxpr(
        jax.grad(scalar_loss, argnums=argnums))(*sds)
    return estimate_peak_bytes(grad_closed)
