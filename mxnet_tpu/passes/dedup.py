"""Cross-CachedOp dedup: structurally identical captured programs share
ONE compiled executable.

Multi-head models and serving `ModelRegistry` replicas trace the same
graph once per block today; XLA compiles each copy.  With
``MXTPU_GRAPH_DEDUP=1`` every block-seam build canonicalizes its
(pass-rewritten) jaxpr — de Bruijn variable numbering, shapes/dtypes,
the equation graph, recursively through nested jaxprs — and looks the
key up in a process-wide executable cache.  Constants enter the shared
executable as runtime ARGUMENTS, so two blocks whose programs differ
only in weight/const values still share.  A hit skips the trace bump
(the `jit_trace_total` zero-retrace proof) and counts in
``graph_dedup_hits_total``.

Programs that cannot be canonicalized safely (effects, huge embedded
constants, identity-hashed callables in eqn params) simply do not
share — correctness first; the build falls back to a private
executable.
"""
from __future__ import annotations

import threading

import numpy as np

import jax
from jax.extend import core as jcore

from ..telemetry import instruments as _telemetry
from . import manager as _manager

__all__ = [
    "DedupExecutable",
    "executable_cache_info",
    "reset_executable_cache",
    "structural_key",
]

_CACHE_LOCK = threading.Lock()
_EXEC_CACHE = {}
_STATS = {"hits": 0, "misses": 0, "unhashable": 0}

# Embedded constants larger than this make the key unhashable (and the
# program un-shared) rather than hashing megabytes of weights per build.
_MAX_CONST_BYTES = 1 << 20


class _Unhashable(Exception):
    pass


def _aval_key(aval):
    return (tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")),
            bool(getattr(aval, "weak_type", False)))


def _canon(obj):
    """Canonicalize one eqn param (or nested const) into a hashable,
    value-comparable token."""
    if obj is None or isinstance(obj, (bool, int, float, complex, str,
                                       bytes)):
        return obj
    if isinstance(obj, jcore.Jaxpr):
        return ("jaxpr", _jaxpr_key(obj))
    if hasattr(obj, "jaxpr") and hasattr(obj, "consts"):  # ClosedJaxpr
        # nested consts are BAKED into the shared program, so their
        # values (not just avals) must participate in the key
        return ("closed", tuple(_canon(c) for c in obj.consts),
                _jaxpr_key(obj.jaxpr))
    if isinstance(obj, np.dtype):
        return ("dtype", str(obj))
    if hasattr(obj, "__array__") and hasattr(obj, "dtype") \
            and hasattr(obj, "shape"):
        arr = np.asarray(obj)
        if arr.nbytes > _MAX_CONST_BYTES:
            raise _Unhashable
        return ("nd", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(obj, (tuple, list)):
        return ("seq", tuple(_canon(x) for x in obj))
    if isinstance(obj, dict):
        return ("map", tuple((str(k), _canon(v)) for k, v in
                             sorted(obj.items(), key=lambda kv: str(kv[0]))))
    if isinstance(obj, (set, frozenset)):
        return ("set", tuple(sorted(repr(x) for x in obj)))
    try:
        hash(obj)
    except TypeError:
        raise _Unhashable from None
    # identity-hashed objects (callables, thunks) are still CORRECT key
    # components — equal only to themselves — they just never match
    # across blocks, so such programs don't dedup
    return ("obj", type(obj).__module__, type(obj).__qualname__, obj)


# custom-derivative calls carry their rule callables/thunks as params.
# The raw objects hash by identity (unique per trace), so keying on them
# would poison every program containing a custom op — but they CANNOT
# simply be dropped either: the rules decide what jax.vjp through the
# shared executable computes, and two blocks with identical primal
# structure but different custom gradients (make_loss's constant-grad
# bwd vs stop_gradient) must not share one executable.  Each rule param
# is therefore reduced to a STABLE, semantics-bearing token: jaxpr
# thunks are forced (all-zeros symbolic-zero pattern — deterministic,
# trace-time-only cost) and keyed by the traced rule jaxpr; wrapped
# rule callables are keyed by the identity of their underlying user
# function, which IS shared across traces of the same library op.  A
# rule that can't be tokenized makes the program unhashable, so it
# falls back to a private executable — correctness first.
_RULE_JAXPR_THUNKS = frozenset((
    "jvp_jaxpr_thunk", "jvp_jaxpr_fun", "fwd_jaxpr_thunk",
))
_RULE_FUN_PARAMS = frozenset(("fwd", "bwd", "jvp"))
_RULE_DERIVED_PARAMS = frozenset(("out_trees",))  # fixed by the fwd jaxpr
_CUSTOM_CALL_PRIMS = frozenset((
    "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
))


def _rule_fun_token(obj):
    """Stable token for a wrapped rule callable: the underlying user
    function (``WrappedFun.f``), equal-by-identity across traces of the
    same op."""
    target = getattr(obj, "__self__", obj)  # bound call_wrapped → WrappedFun
    f = getattr(target, "f", None) or (obj if callable(obj) else None)
    if f is None:
        raise _Unhashable
    return ("rulefn", f)


# forcing is top-level only: a rule jaxpr often contains the op itself
# (jax.nn.relu's jvp recomputes relu), so forcing nested thunks would
# recurse forever.  Inside a forced rule, nested custom calls are keyed
# by their primal jaxpr + stable fun tokens, which first-order
# differentiation through the shared executable never looks past.
_RULE_DEPTH = threading.local()


def _rule_jaxpr_token(eqn, thunk):
    """Force a rule-jaxpr thunk with the no-symbolic-zeros pattern and
    key the traced rule itself."""
    if getattr(_RULE_DEPTH, "d", 0):
        return ("rulejaxpr", "nested")
    n = len(eqn.invars) - int(eqn.params.get("num_consts") or 0)
    _RULE_DEPTH.d = 1
    try:
        forced = thunk(*([False] * n))
        return ("rulejaxpr", _canon(forced))
    except _Unhashable:
        raise
    except Exception:
        raise _Unhashable from None
    finally:
        _RULE_DEPTH.d = 0


def _pallas_key(params):
    """Structural token for one ``pallas_call``: the traced kernel body
    plus the launch geometry that selects a Mosaic program.  Anything we
    can't reduce to structure raises ``_Unhashable`` — the program then
    takes a private executable, never a wrong shared one.

    Kernel bodies mutate their refs, so they carry jax state effects —
    internal to the pallas_call, invisible to the surrounding program.
    They are canonicalized WITH their effect structure (two bodies match
    only if their read/write effects match positionally) instead of
    tripping the top-level no-effects rule."""
    params = dict(params)
    prev = _EFFECT_TOLERANT[0]
    _EFFECT_TOLERANT[0] = True
    try:
        kernel = ("kernel", _canon(params.pop("jaxpr")))
    finally:
        _EFFECT_TOLERANT[0] = prev
    gm = params.pop("grid_mapping", None)
    geo = ()
    if gm is not None:
        blocks = []
        for bm in getattr(gm, "block_mappings", ()):
            blocks.append((
                tuple(getattr(bm, "block_shape", ())),
                _canon(getattr(bm, "index_map_jaxpr", None)),
            ))
        geo = (tuple(getattr(gm, "grid", ())), tuple(blocks))
    rest = {}
    for k, v in params.items():
        try:
            rest[k] = _canon(v)
        except _Unhashable:
            # compiler params / cost estimates that resist tokenizing
            # are keyed by repr when stable; an address-bearing repr is
            # identity, not structure — poison the key instead
            r = repr(v)
            if "0x" in r:
                raise
            rest[k] = ("repr", r)
    return ("pallas", kernel, geo, _canon(rest))


def _eqn_params_key(eqn):
    params = dict(eqn.params)
    if eqn.primitive.name in _CUSTOM_CALL_PRIMS:
        rules = []
        for k in sorted(params):
            if k in _RULE_DERIVED_PARAMS:
                params.pop(k)
            elif k in _RULE_JAXPR_THUNKS:
                rules.append((k, _rule_jaxpr_token(eqn, params.pop(k))))
            elif k in _RULE_FUN_PARAMS:
                rules.append((k, _rule_fun_token(params.pop(k))))
        return ("custom", _canon(params), tuple(rules))
    if eqn.primitive.name == "pallas_call":
        # a Pallas kernel IS a structural feature: two programs share an
        # executable only when kernel body + grid + block maps agree
        try:
            return _pallas_key(params)
        except _Unhashable:
            raise
        except Exception:
            raise _Unhashable from None
    return _canon(params)


# canonicalizing a Pallas kernel body (see _pallas_key): its internal
# ref state effects become part of the key instead of poisoning it
_EFFECT_TOLERANT = [False]


def _effects_key(effects):
    toks = []
    for e in effects:
        r = repr(e)
        if "0x" in r:        # address-bearing repr: identity, not structure
            raise _Unhashable
        toks.append(r)
    return tuple(sorted(toks))


def _jaxpr_key(jaxpr):
    effects = getattr(jaxpr, "effects", None)
    eff_tok = ()
    if effects:
        if not _EFFECT_TOLERANT[0]:
            raise _Unhashable  # effectful programs never share executables
        eff_tok = _effects_key(effects)
    ids = {}

    def vid(v):
        token = ids.get(id(v))
        if token is None:
            token = ids[id(v)] = len(ids)
        return token

    def atom(v):
        if isinstance(v, jcore.Literal):
            return ("lit", _canon(v.val))
        return ("var", vid(v), _aval_key(v.aval))

    parts = [
        ("effects", eff_tok),
        ("const", tuple((vid(v), _aval_key(v.aval))
                        for v in jaxpr.constvars)),
        ("in", tuple((vid(v), _aval_key(v.aval)) for v in jaxpr.invars)),
    ]
    for eqn in jaxpr.eqns:
        parts.append((eqn.primitive.name,
                      tuple(atom(v) for v in eqn.invars),
                      tuple((vid(v), _aval_key(v.aval))
                            for v in eqn.outvars),
                      _eqn_params_key(eqn)))
    parts.append(("out", tuple(atom(v) for v in jaxpr.outvars)))
    return tuple(parts)


def structural_key(closed):
    """Canonical key of a ClosedJaxpr modulo var names and TOP-LEVEL
    const values (consts become runtime args of the shared executable,
    so only their avals matter).  None ⇒ not safely shareable."""
    try:
        return ("prog",
                tuple(_aval_key(jax.api_util.shaped_abstractify(c))
                      for c in closed.consts),
                _jaxpr_key(closed.jaxpr))
    except _Unhashable:
        return None


class _SharedExec:
    """One compiled executable serving every structurally identical
    program: jit of ``run(consts, *flat)`` over the FIRST matching
    jaxpr (all matches are structurally equal, so evaluating that one
    with each caller's consts/args is exact)."""

    __slots__ = ("jitted",)

    def __init__(self, closed):
        jaxpr = closed.jaxpr

        def run_shared(consts, *flat):
            return jax.core.eval_jaxpr(jaxpr, consts, *flat)

        self.jitted = jax.jit(run_shared)


class _Entry:
    __slots__ = ("shared", "consts", "out_tree", "hit")

    def __init__(self, shared, consts, out_tree, hit):
        self.shared = shared
        self.consts = consts
        self.out_tree = out_tree
        self.hit = hit


class DedupExecutable:
    """The block-seam executable under MXTPU_GRAPH_DEDUP=1: callable
    like a jitted function (with ``.lower()`` for compile
    introspection), backed by the process-wide shared-executable
    cache."""

    def __init__(self, fn, passes, ctx):
        self._fn = fn
        self._passes = passes
        self._ctx = ctx
        self._entries = {}
        self._lock = threading.Lock()

    def _entry(self, args):
        flat, sig = _manager.signature(args)
        entry = self._entries.get(sig)
        if entry is None:
            with self._lock:
                entry = self._entries.get(sig)
                if entry is None:
                    entry = self._build(args)
                    self._entries[sig] = entry
        return entry, flat

    def _build(self, args):
        ctx = self._ctx
        closed, out_tree = _manager.trace_closed(self._fn, args)
        closed = _manager.run_passes(closed, self._passes, ctx)
        key = structural_key(closed)
        hit = False
        if key is None:
            with _CACHE_LOCK:
                _STATS["unhashable"] += 1
            shared = _SharedExec(closed)  # private, unshared
        else:
            with _CACHE_LOCK:
                shared = _EXEC_CACHE.get(key)
                hit = shared is not None
                if not hit:
                    shared = _EXEC_CACHE[key] = _SharedExec(closed)
                _STATS["hits" if hit else "misses"] += 1
        if hit:
            _telemetry.record_dedup_hit(ctx.label)
        else:
            # one real build = one trace bump, exactly like a direct jit
            ctx.fire_on_build()
        return _Entry(shared, tuple(closed.consts), out_tree, hit)

    def __call__(self, *args):
        entry, flat = self._entry(args)
        outs = entry.shared.jitted(list(entry.consts), *flat)
        return jax.tree_util.tree_unflatten(entry.out_tree, list(outs))

    def lower(self, *args):
        entry, flat = self._entry(args)
        return entry.shared.jitted.lower(list(entry.consts), *flat)


def executable_cache_info():
    """{entries, hits, misses, unhashable} of the process-wide shared
    executable cache (tools/diagnose.py --passes)."""
    with _CACHE_LOCK:
        return {"entries": len(_EXEC_CACHE), **_STATS}


def reset_executable_cache():
    with _CACHE_LOCK:
        _EXEC_CACHE.clear()
        for k in _STATS:
            _STATS[k] = 0
