"""Cross-CachedOp dedup: structurally identical captured programs share
ONE compiled executable.

Multi-head models and serving `ModelRegistry` replicas trace the same
graph once per block today; XLA compiles each copy.  With
``MXTPU_GRAPH_DEDUP=1`` every block-seam build canonicalizes its
(pass-rewritten) jaxpr — de Bruijn variable numbering, shapes/dtypes,
the equation graph, recursively through nested jaxprs — and looks the
key up in a process-wide executable cache.  Constants enter the shared
executable as runtime ARGUMENTS, so two blocks whose programs differ
only in weight/const values still share.  A hit skips the trace bump
(the `jit_trace_total` zero-retrace proof) and counts in
``graph_dedup_hits_total``.

Programs that cannot be canonicalized safely (effects, huge embedded
constants, identity-hashed callables in eqn params) simply do not
share — correctness first; the build falls back to a private
executable.
"""
from __future__ import annotations

import threading

import numpy as np

import jax
from jax.extend import core as jcore

from ..telemetry import instruments as _telemetry
from . import manager as _manager

__all__ = [
    "DedupExecutable",
    "executable_cache_info",
    "reset_executable_cache",
    "structural_key",
]

_CACHE_LOCK = threading.Lock()
_EXEC_CACHE = {}
_STATS = {"hits": 0, "misses": 0, "unhashable": 0}

# Embedded constants larger than this make the key unhashable (and the
# program un-shared) rather than hashing megabytes of weights per build.
_MAX_CONST_BYTES = 1 << 20


class _Unhashable(Exception):
    pass


def _aval_key(aval):
    return (tuple(getattr(aval, "shape", ())),
            str(getattr(aval, "dtype", "?")),
            bool(getattr(aval, "weak_type", False)))


def _canon(obj):
    """Canonicalize one eqn param (or nested const) into a hashable,
    value-comparable token."""
    if obj is None or isinstance(obj, (bool, int, float, complex, str,
                                       bytes)):
        return obj
    if isinstance(obj, jcore.Jaxpr):
        return ("jaxpr", _jaxpr_key(obj))
    if hasattr(obj, "jaxpr") and hasattr(obj, "consts"):  # ClosedJaxpr
        # nested consts are BAKED into the shared program, so their
        # values (not just avals) must participate in the key
        return ("closed", tuple(_canon(c) for c in obj.consts),
                _jaxpr_key(obj.jaxpr))
    if isinstance(obj, np.dtype):
        return ("dtype", str(obj))
    if hasattr(obj, "__array__") and hasattr(obj, "dtype") \
            and hasattr(obj, "shape"):
        arr = np.asarray(obj)
        if arr.nbytes > _MAX_CONST_BYTES:
            raise _Unhashable
        return ("nd", arr.shape, str(arr.dtype), arr.tobytes())
    if isinstance(obj, (tuple, list)):
        return ("seq", tuple(_canon(x) for x in obj))
    if isinstance(obj, dict):
        return ("map", tuple((str(k), _canon(v)) for k, v in
                             sorted(obj.items(), key=lambda kv: str(kv[0]))))
    if isinstance(obj, (set, frozenset)):
        return ("set", tuple(sorted(repr(x) for x in obj)))
    try:
        hash(obj)
    except TypeError:
        raise _Unhashable from None
    # identity-hashed objects (callables, thunks) are still CORRECT key
    # components — equal only to themselves — they just never match
    # across blocks, so such programs don't dedup
    return ("obj", type(obj).__module__, type(obj).__qualname__, obj)


# custom-derivative calls carry memoized rule thunks that hash by
# identity and would never match across traces.  The primal body
# (call_jaxpr / fun_jaxpr, which IS part of the key) fully determines
# what the shared executable computes, and two traces of the same
# library function (e.g. jax.nn.relu) carry equivalent rules — so the
# thunks are dropped from the key rather than poisoning every program
# that contains a relu.
_RULE_THUNK_PARAMS = frozenset((
    "jvp_jaxpr_thunk", "jvp_jaxpr_fun", "fwd_jaxpr_thunk",
    "fwd", "bwd", "jvp", "out_trees",
))
_CUSTOM_CALL_PRIMS = frozenset((
    "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
))


def _eqn_params_key(eqn):
    params = dict(eqn.params)
    if eqn.primitive.name in _CUSTOM_CALL_PRIMS:
        for k in _RULE_THUNK_PARAMS:
            params.pop(k, None)
    return _canon(params)


def _jaxpr_key(jaxpr):
    if getattr(jaxpr, "effects", None):
        raise _Unhashable  # effectful programs never share executables
    ids = {}

    def vid(v):
        token = ids.get(id(v))
        if token is None:
            token = ids[id(v)] = len(ids)
        return token

    def atom(v):
        if isinstance(v, jcore.Literal):
            return ("lit", _canon(v.val))
        return ("var", vid(v), _aval_key(v.aval))

    parts = [
        ("const", tuple((vid(v), _aval_key(v.aval))
                        for v in jaxpr.constvars)),
        ("in", tuple((vid(v), _aval_key(v.aval)) for v in jaxpr.invars)),
    ]
    for eqn in jaxpr.eqns:
        parts.append((eqn.primitive.name,
                      tuple(atom(v) for v in eqn.invars),
                      tuple((vid(v), _aval_key(v.aval))
                            for v in eqn.outvars),
                      _eqn_params_key(eqn)))
    parts.append(("out", tuple(atom(v) for v in jaxpr.outvars)))
    return tuple(parts)


def structural_key(closed):
    """Canonical key of a ClosedJaxpr modulo var names and TOP-LEVEL
    const values (consts become runtime args of the shared executable,
    so only their avals matter).  None ⇒ not safely shareable."""
    try:
        return ("prog",
                tuple(_aval_key(jax.api_util.shaped_abstractify(c))
                      for c in closed.consts),
                _jaxpr_key(closed.jaxpr))
    except _Unhashable:
        return None


class _SharedExec:
    """One compiled executable serving every structurally identical
    program: jit of ``run(consts, *flat)`` over the FIRST matching
    jaxpr (all matches are structurally equal, so evaluating that one
    with each caller's consts/args is exact)."""

    __slots__ = ("jitted",)

    def __init__(self, closed):
        jaxpr = closed.jaxpr

        def run_shared(consts, *flat):
            return jax.core.eval_jaxpr(jaxpr, consts, *flat)

        self.jitted = jax.jit(run_shared)


class _Entry:
    __slots__ = ("shared", "consts", "out_tree", "hit")

    def __init__(self, shared, consts, out_tree, hit):
        self.shared = shared
        self.consts = consts
        self.out_tree = out_tree
        self.hit = hit


class DedupExecutable:
    """The block-seam executable under MXTPU_GRAPH_DEDUP=1: callable
    like a jitted function (with ``.lower()`` for compile
    introspection), backed by the process-wide shared-executable
    cache."""

    def __init__(self, fn, passes, ctx):
        self._fn = fn
        self._passes = passes
        self._ctx = ctx
        self._entries = {}
        self._lock = threading.Lock()

    def _entry(self, args):
        flat, sig = _manager.signature(args)
        entry = self._entries.get(sig)
        if entry is None:
            with self._lock:
                entry = self._entries.get(sig)
                if entry is None:
                    entry = self._build(args)
                    self._entries[sig] = entry
        return entry, flat

    def _build(self, args):
        ctx = self._ctx
        closed, out_tree = _manager.trace_closed(self._fn, args)
        closed = _manager.run_passes(closed, self._passes, ctx)
        key = structural_key(closed)
        hit = False
        if key is None:
            with _CACHE_LOCK:
                _STATS["unhashable"] += 1
            shared = _SharedExec(closed)  # private, unshared
        else:
            with _CACHE_LOCK:
                shared = _EXEC_CACHE.get(key)
                hit = shared is not None
                if not hit:
                    shared = _EXEC_CACHE[key] = _SharedExec(closed)
                _STATS["hits" if hit else "misses"] += 1
        if hit:
            _telemetry.record_dedup_hit(ctx.label)
        else:
            # one real build = one trace bump, exactly like a direct jit
            ctx.fire_on_build()
        return _Entry(shared, tuple(closed.consts), out_tree, hit)

    def __call__(self, *args):
        entry, flat = self._entry(args)
        outs = entry.shared.jitted(list(entry.consts), *flat)
        return jax.tree_util.tree_unflatten(entry.out_tree, list(outs))

    def lower(self, *args):
        entry, flat = self._entry(args)
        return entry.shared.jitted.lower(list(entry.consts), *flat)


def executable_cache_info():
    """{entries, hits, misses, unhashable} of the process-wide shared
    executable cache (tools/diagnose.py --passes)."""
    with _CACHE_LOCK:
        return {"entries": len(_EXEC_CACHE), **_STATS}


def reset_executable_cache():
    with _CACHE_LOCK:
        _EXEC_CACHE.clear()
        for k in _STATS:
            _STATS[k] = 0
