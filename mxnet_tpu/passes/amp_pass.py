"""AMP as a graph pass: the reference `low_precision_pass.cc` ported
onto the pipeline.

The actual dtype rewrite lives in `amp/graph_pass.amp_rewrite` (the
jaxpr interpreter enforcing the LP16/FP32/widest cast lists); this pass
adapts it to the jaxpr → jaxpr contract so auto-cast composes with the
other passes and with every seam — block variants, export, symbol
lowering, and the whole-step train program's forward body.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import manager as _manager
from .manager import GraphPass

__all__ = ["AmpPass"]


class AmpPass(GraphPass):
    """Rewrite matmul/conv to the target low precision, pin the
    numerically sensitive ops to fp32, cast outputs back (see
    amp/graph_pass.py for the op lists).  Per-build AmpStats land on
    ``ctx.block._amp_stats`` (when a block owns the seam), on
    ``ctx.notes['amp_stats']``, and accumulate into ``stats`` when one
    is passed (legacy build_amp_variant contract)."""

    name = "amp"
    priority = 10  # precision first; remat checkpoints the cast graph
    kinds = ("block", "export", "symbol", "whole_step_fwd")

    def __init__(self, target_dtype=None, stats=None):
        self.target_dtype = (jnp.bfloat16 if target_dtype is None
                             else target_dtype)
        self.stats_sink = stats

    def run(self, closed, ctx):
        from ..amp.graph_pass import AmpStats, amp_rewrite

        stats = AmpStats()
        rewritten = amp_rewrite(closed, self.target_dtype, stats)
        new_closed = _manager.retrace_flat(rewritten, closed)
        if self.stats_sink is not None:
            self.stats_sink.lp16_ops += stats.lp16_ops
            self.stats_sink.fp32_pinned_ops += stats.fp32_pinned_ops
        if ctx.block is not None:
            object.__setattr__(ctx.block, "_amp_stats", stats)
        ctx.notes["amp_stats"] = stats
        return new_closed
