"""DLPack interop (reference: python/mxnet/dlpack.py). Zero-copy
exchange with other frameworks through the jax.Array DLPack protocol."""
from .numpy_extension import (  # noqa: F401
    from_dlpack,
    to_dlpack_for_read,
    to_dlpack_for_write,
)

__all__ = ["from_dlpack", "to_dlpack_for_read", "to_dlpack_for_write"]
