"""Python CustomOp API (reference: python/mxnet/operator.py:434,487,710 —
CustomOp/CustomOpProp/register and the C-side async CustomOperator worker,
src/operator/custom/custom-inl.h:51).

TPU re-design: a custom op is an eager Python callable whose forward/backward
run on NDArrays (device arrays under the hood) and whose autograd integration
rides the tape's Function node — no separate worker queue is needed because
JAX dispatch is already async. Ops registered here are invokable as
`mx.nd.Custom(*data, op_type=name)` exactly like the reference.
"""
from __future__ import annotations

import collections as _collections

from . import autograd as ag
from .ndarray.ndarray import NDArray

__all__ = ["CustomOp", "CustomOpProp", "register", "Custom",
           "get_all_registered", "get_all_registered_operators",
           "get_all_registered_operators_grouped", "get_operator_arguments",
           "OperatorArguments"]

_REGISTRY = {}


class CustomOp:
    """Base class for custom operators (reference: operator.py:434)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write `src` into `dst` honoring the grad_req
        (reference: operator.py:463)."""
        if req in ("null", None):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise ValueError(f"unknown req {req!r}")


class CustomOpProp:
    """Operator properties: names, shapes, types, factory
    (reference: operator.py:487)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def infer_storage_type(self, in_stype):
        return (in_stype, ["default"] * len(self.list_outputs()),
                ["default"] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Class decorator registering a CustomOpProp under `reg_name`
    (reference: operator.py:710)."""

    def _do(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _do


def get_all_registered():
    return sorted(_REGISTRY)


class _CustomFunction(ag.Function):
    """Bridges CustomOp.forward/backward onto the autograd tape."""

    def __init__(self, op, prop, n_out):
        super().__init__()
        self._op = op
        self._prop = prop
        self._n_out = n_out

    def forward(self, *inputs):
        from . import numpy as mxnp

        in_shapes = [list(i.shape) for i in inputs]
        ret = self._prop.infer_shape(in_shapes)
        out_shapes = ret[1]          # (in, out[, aux]) — aux optional,
        #                              matching the reference's 2-or-3 form
        in_types = [i.dtype for i in inputs]
        rett = self._prop.infer_type(in_types)
        out_types = rett[1]
        outs = [mxnp.zeros(tuple(s), dtype=t)
                for s, t in zip(out_shapes, out_types)]
        self._op.forward(is_train=ag.is_training(),
                         req=["write"] * len(outs),
                         in_data=list(inputs), out_data=outs, aux=[])
        self._inputs = list(inputs)
        self._outputs = outs
        return tuple(outs) if len(outs) > 1 else outs[0]

    def backward(self, *output_grads):
        from . import numpy as mxnp

        in_grads = [mxnp.zeros(i.shape, dtype=i.dtype) for i in self._inputs]
        self._op.backward(req=["write"] * len(in_grads),
                          out_grad=list(output_grads),
                          in_data=self._inputs, out_data=self._outputs,
                          in_grad=in_grads, aux=[])
        return tuple(in_grads) if len(in_grads) > 1 else in_grads[0]


def Custom(*inputs, op_type=None, **kwargs):  # noqa: N802
    """Invoke a registered custom op: `mx.nd.Custom(x, op_type='my_op')`."""
    if op_type is None:
        raise ValueError("Custom requires op_type=")
    prop_cls = _REGISTRY.get(op_type)
    if prop_cls is None:
        raise KeyError(f"custom op {op_type!r} not registered "
                       f"(have: {get_all_registered()})")
    import inspect

    sig = inspect.signature(prop_cls.__init__)
    has_var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                     for p in sig.parameters.values())
    if not has_var_kw:
        unknown = [k for k in kwargs if k not in sig.parameters]
        if unknown:
            raise TypeError(
                f"custom op {op_type!r} got unexpected parameter(s) "
                f"{unknown}; {prop_cls.__name__}.__init__ accepts "
                f"{[p for p in sig.parameters if p != 'self']}")
    prop = prop_cls(**kwargs)
    nd_inputs = [i for i in inputs if isinstance(i, NDArray)]
    in_shapes = [list(i.shape) for i in nd_inputs]
    in_types = [i.dtype for i in nd_inputs]
    dev = nd_inputs[0].device if nd_inputs else None
    op = prop.create_operator(dev, in_shapes, in_types)
    fn = _CustomFunction(op, prop, len(prop.list_outputs()))
    return fn(*nd_inputs)


# ---- operator introspection (reference: operator.py:1129-1201 — the
# MXListAllOpNames / NNGetOpHandle C-API walk; here the op registry IS the
# python-side table, so introspection reads it directly) -------------------

def get_all_registered_operators():
    """All registered operator names (reference: operator.py:1129)."""
    from .ops.registry import _OPS

    return sorted(_OPS)


def get_all_registered_operators_grouped():
    """Operator names grouped by implementation: alias spellings that
    resolve to the same callable are listed together (reference:
    operator.py:1146 groups by the op handle)."""
    from .ops.registry import _OPS

    groups = {}
    for name, fn in _OPS.items():
        groups.setdefault(id(fn), []).append(name)
    out = {}
    for names in groups.values():
        names.sort()
        out[names[0]] = names
    return out


OperatorArguments = _collections.namedtuple(
    "OperatorArguments", ["narg", "names", "types"])
OperatorArguments.__doc__ = ("Arity + argument names/types of an operator "
                             "(reference: operator.py:1164).")


def get_operator_arguments(op_name):
    """Fetch an operator's argument names and annotated types from its
    python signature (reference: operator.py:1175 reads the same data
    from the C op registry)."""
    import inspect

    from .ops.registry import _OPS

    fn = _OPS.get(op_name)
    if fn is None:
        raise ValueError(f"operator {op_name!r} is not registered")
    sig = inspect.signature(fn)
    names, types = [], []
    for pname, p in sig.parameters.items():
        if p.kind is inspect.Parameter.VAR_KEYWORD:
            continue
        if p.kind is inspect.Parameter.VAR_POSITIONAL:
            # variadic-input ops (add_n, khatri_rao, ...) report one
            # list-typed slot, like the reference's "NDArray-or-Symbol[]"
            names.append(pname)
            types.append("NDArray-or-Symbol[]")
            continue
        names.append(pname)
        if p.annotation is not inspect.Parameter.empty:
            types.append(str(p.annotation))
        elif p.default is None or p.default is inspect.Parameter.empty:
            # None-default optional tensors/attrs carry no type info;
            # the tensor-slot fallback is the faithful description
            types.append("NDArray-or-Symbol")
        else:
            types.append(type(p.default).__name__)
    return OperatorArguments(len(names), names, types)
