"""Base utilities: dtype normalization, registries, errors.

TPU-native re-design of the reference's `python/mxnet/base.py` +
`include/mxnet/base.h` roles (dtype/ctx plumbing, registry helpers). No C ABI is
needed here: the "FFI" of this framework is the JAX/XLA python binding itself.
"""
from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError",
    "DeferredInitializationError",
    "normalize_dtype",
    "dtype_name",
    "registry",
    "string_types",
    "numeric_types",
    "integer_types",
]


class MXNetError(RuntimeError):
    """Framework-level error (parity with the reference's MXNetError)."""


class DeferredInitializationError(MXNetError):
    """Raised when a deferred-init Parameter's data is accessed before shape is known.

    Reference: python/mxnet/gluon/parameter.py (DeferredInitializationError).
    """


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# Canonical dtype names supported on TPU. fp64 is emulated/slow on TPU but kept
# for CPU-mesh testing parity.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "long": "int64",
    "bool": "bool_",
    "boolean": "bool_",
}


def normalize_dtype(dtype):
    """Return a numpy-compatible dtype object (ml_dtypes covers bfloat16).

    Accepts strings, numpy dtypes, python types, jax dtypes; None passes through.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import ml_dtypes

            return _np.dtype(ml_dtypes.bfloat16)
        if dtype == "bool_":
            return _np.dtype(_np.bool_)
        return _np.dtype(dtype)
    if dtype is bool:
        return _np.dtype(_np.bool_)
    return _np.dtype(dtype)


def dtype_name(dtype):
    """Canonical string name of a dtype."""
    d = normalize_dtype(dtype)
    return d.name if d is not None else None


class _Registry:
    """Name -> object registry with alias support.

    Mirrors the reference's `mxnet.registry` (python/mxnet/registry.py) which in
    turn mirrors dmlc registry behavior: case-insensitive lookup, re-register
    warns and overrides.
    """

    def __init__(self, kind):
        self._kind = kind
        self._reg = {}

    def register(self, obj, name=None):
        key = (name or getattr(obj, "__name__", None) or str(obj)).lower()
        self._reg[key] = obj
        return obj

    def get(self, name):
        key = name.lower()
        if key not in self._reg:
            raise KeyError(
                f"{self._kind} '{name}' is not registered. "
                f"Known: {sorted(self._reg)}"
            )
        return self._reg[key]

    def find(self, name):
        return self._reg.get(name.lower())

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def list(self):
        return sorted(self._reg)


def registry(kind):
    return _Registry(kind)
