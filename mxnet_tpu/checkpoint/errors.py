"""Typed checkpoint errors.

Callers branch on these: NotFound means "cold start, begin at step 0";
Corrupt means "this checkpoint is damaged" — restore() treats the two
very differently (a corrupt *latest* falls back to the previous
committed step; an explicitly requested step does not silently
substitute another).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["CheckpointError", "CheckpointNotFound", "CheckpointCorrupt",
           "PlanMismatch"]


class CheckpointError(MXNetError):
    """Base for checkpoint subsystem failures."""


class CheckpointNotFound(CheckpointError):
    """No committed checkpoint exists (at the requested step, or at all)."""


class CheckpointCorrupt(CheckpointError):
    """A committed checkpoint failed validation (missing files, manifest
    mismatch, or per-array checksum failure)."""


class PlanMismatch(CheckpointError):
    """The checkpoint's recorded ShardingPlan and the restoring trainer's
    plan disagree on world size (mesh device count). Restoring across
    world sizes is a topology migration, not a resume — pass
    ``allow_reshard=True`` to restore() (or use ``mxnet_tpu.elastic.
    reshard`` / ``tools/ckpt.py reshard``) to opt in explicitly
    (docs/elasticity.md)."""

    def __init__(self, msg, saved_plan=None, target_plan=None):
        super().__init__(msg)
        self.saved_plan = saved_plan      # manifest dict (or None)
        self.target_plan = target_plan    # manifest dict (or None)
