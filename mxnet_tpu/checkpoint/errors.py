"""Typed checkpoint errors.

Callers branch on these: NotFound means "cold start, begin at step 0";
Corrupt means "this checkpoint is damaged" — restore() treats the two
very differently (a corrupt *latest* falls back to the previous
committed step; an explicitly requested step does not silently
substitute another).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["CheckpointError", "CheckpointNotFound", "CheckpointCorrupt"]


class CheckpointError(MXNetError):
    """Base for checkpoint subsystem failures."""


class CheckpointNotFound(CheckpointError):
    """No committed checkpoint exists (at the requested step, or at all)."""


class CheckpointCorrupt(CheckpointError):
    """A committed checkpoint failed validation (missing files, manifest
    mismatch, or per-array checksum failure)."""
