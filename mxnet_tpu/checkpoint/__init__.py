"""Fault-tolerant checkpointing: atomic async snapshots, retention,
preemption handling, and exact training resume (docs/checkpointing.md).

    mgr = mx.checkpoint.CheckpointManager("ckpts", trainer, keep_last=5)
    mx.checkpoint.install_preemption_handler(mgr)
    for step in range(...):
        ...
        if step % 100 == 0:
            mgr.save(step, user_state={"epoch": epoch, "batch": batch})
    # after a crash / preemption:
    result = mgr.restore()          # latest committed, checksum-verified
    start = result.step + 1
"""
from __future__ import annotations

from .errors import (CheckpointCorrupt, CheckpointError,
                     CheckpointNotFound, PlanMismatch)
from .manager import CheckpointManager, RestoreResult, verify_checkpoint
from .preemption import PreemptionHandler, install_preemption_handler

__all__ = [
    "CheckpointManager", "RestoreResult", "verify_checkpoint",
    "PreemptionHandler", "install_preemption_handler",
    "CheckpointError", "CheckpointCorrupt", "CheckpointNotFound",
    "PlanMismatch",
]
