"""Preemption-to-checkpoint: signal -> emergency snapshot -> clean exit.

TPU pods are preemptible; schedulers announce eviction with SIGTERM (or
SIGUSR1 under some launchers) and grant a grace window. The handler
turns that notice into a SYNCHRONOUS snapshot (async would race the
kill), fences every still-pending earlier save with
`_checkpoint_io.flush_all()`, then exits with a configurable code —
zero by default so supervisors see a clean, resumable shutdown rather
than a crash loop. If the emergency snapshot itself FAILS the process
exits 1 regardless of the configured code: the state was not saved, and
reporting it as resumable would be a lie.

Signal handlers must be installed from the main thread (CPython rule)
and the handler body itself runs on the main thread, which is exactly
where the collective barrier of a distributed save is legal.
"""
from __future__ import annotations

import signal
import sys
import threading

__all__ = ["PreemptionHandler", "install_preemption_handler"]


def _parse_signals(spec):
    out = []
    for name in str(spec).split(","):
        name = name.strip().upper()
        if not name:
            continue
        if not name.startswith("SIG"):
            name = "SIG" + name
        sig = getattr(signal, name, None)
        if sig is None:
            raise ValueError(f"unknown signal {name!r}")
        out.append(sig)
    return out


class PreemptionHandler:
    """Installs signal handlers that snapshot through `manager` and exit.

    Use as a context manager or call install()/uninstall() explicitly;
    uninstall restores the previous handlers. `preempted` flips True
    before the snapshot starts, so polling loops can also drain
    gracefully when `exit=False`.
    """

    def __init__(self, manager, signals=None, exit_code=None, exit=True,
                 user_state_fn=None):
        from .. import env as _env

        self.manager = manager
        if signals is None:
            signals = _parse_signals(_env.get("MXTPU_CKPT_PREEMPT_SIGNALS"))
        elif isinstance(signals, str):
            signals = _parse_signals(signals)
        self.signals = list(signals)
        self.exit_code = _env.get("MXTPU_CKPT_PREEMPT_EXIT_CODE") \
            if exit_code is None else int(exit_code)
        self.exit = bool(exit)
        self.user_state_fn = user_state_fn
        self.preempted = False
        self._prev = {}
        self._installed = False
        self._once = threading.Lock()   # double-delivery guard

    def install(self):
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # non-main thread / teardown
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def _on_signal(self, signum, frame):  # noqa: ARG002
        if not self._once.acquire(blocking=False):
            return  # second delivery while the snapshot runs: ignore
        self.preempted = True
        try:
            from ..observability import flight as _flight

            _flight.record("preempt", signal=int(signum))
        except Exception:
            pass
        saved = False
        try:
            from .. import _checkpoint_io
            from ..diagnostics import spans as _spans

            user_state = self.user_state_fn() if self.user_state_fn \
                else None
            with _spans.span("ckpt.preempt", cat="checkpoint"):
                self.manager.save(sync=True, reason="preempt",
                                  user_state=user_state)
                _checkpoint_io.flush_all()  # earlier async saves too
            saved = True
        except BaseException:
            # a FAILED emergency snapshot must not masquerade as a clean,
            # resumable shutdown: the supervisor would believe the latest
            # state was saved when it was not
            if not self.exit:
                self._once.release()  # stay armed for a retry
                raise
            import traceback

            traceback.print_exc(file=sys.stderr)
            print("mxnet_tpu.checkpoint: emergency preemption snapshot "
                  "FAILED; exiting 1 (latest state NOT saved)",
                  file=sys.stderr)
        try:
            # the black box rides out with the eviction — synchronous,
            # like the snapshot: async would race the kill
            from ..observability import postmortem as _postmortem

            _postmortem.dump(reason="preempt", sync=True,
                             extra={"snapshot_saved": saved})
        except Exception:
            pass
        if self.exit:
            sys.exit(self.exit_code if saved else 1)
        self._once.release()  # stay armed for a later re-delivery


def install_preemption_handler(manager, **kwargs):
    """Convenience: build + install, returning the handler (for
    `uninstall()` or `preempted` polling)."""
    return PreemptionHandler(manager, **kwargs).install()
