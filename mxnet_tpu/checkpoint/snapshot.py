"""Capture / apply complete training state as flat numpy arrays + meta.

The snapshot is the serialization-agnostic middle layer: `capture` walks
a Trainer and returns (arrays, meta) where `arrays` is a flat
name->numpy dict (npz-ready, dtype-codec friendly) and `meta` is a
JSON-able dict that records how to reassemble it. `apply` is the exact
inverse. manager.py owns files, atomicity, and retention; this module
owns *what* training state means:

  * Block parameters (primary device copy; `set_data` re-fans-out to
    every device copy on restore, honoring each param's declared dtype),
  * optimizer per-param state trees — legacy and fused paths share
    `Trainer._states` (possibly (master_fp32, inner) multi-precision
    tuples), flattened leaf-by-leaf with a structure spec in meta,
  * optimizer bookkeeping (`num_update`, per-param update counts `t`
    that drive Adam bias correction and LR schedules — dropping these
    would silently restart schedules, breaking bitwise resume),
  * stale-grad tracking: `Trainer._grad_versions` stores process-local
    buffer versions, meaningless in a new process; we persist *which*
    param indices were stale and re-mark them against the restored
    process's grad versions on apply,
  * the global RNG key and loss-scale, and an opaque user-state blob
    (dataloader cursor etc.) that rides along in meta.
"""
from __future__ import annotations

import numpy as np

from .errors import CheckpointError

__all__ = ["capture", "apply"]

# bump when the (arrays, meta) layout changes incompatibly
SNAPSHOT_VERSION = 1


def _state_spec(state, prefix, out, transform=None):
    """Flatten one optimizer-state tree: leaves (NDArray) land in `out`
    under generated keys; returns a JSON-able spec mirroring the
    structure — None | "key-string" | [child specs]. `transform`, when
    given, maps each leaf's host array before it is stored (the layout
    path de-permutes physically re-laid-out momentum back to the
    logical shape so checkpoints stay layout-agnostic)."""
    from ..ndarray.ndarray import NDArray

    if state is None:
        return None
    if isinstance(state, NDArray):
        arr = state.asnumpy()
        if transform is not None:
            arr = transform(arr)
        out[prefix] = arr
        return prefix
    if isinstance(state, (tuple, list)):
        return [_state_spec(s, f"{prefix}.{j}", out, transform)
                for j, s in enumerate(state)]
    raise CheckpointError(
        f"unserializable optimizer state at {prefix}: {type(state)}")


def _state_from_spec(spec, arrays, transform=None):
    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray

    if spec is None:
        return None
    if isinstance(spec, str):
        if spec not in arrays:
            raise CheckpointError(f"missing optimizer state array {spec!r}")
        arr = arrays[spec]
        if transform is not None:
            arr = transform(arr)
        return NDArray(jnp.asarray(arr))
    return tuple(_state_from_spec(s, arrays, transform) for s in spec)


def _save_transform(p):
    """Physical→logical de-permutation for one param's state leaves, or
    None when the param was never re-laid-out (passes/layout.py)."""
    perm = getattr(p, "_layout_perm", None)
    if perm is None:
        return None
    logical = tuple(p._shape)
    phys = tuple(logical[i] for i in perm)
    if phys == logical:
        return None
    inv = tuple(int(i) for i in np.argsort(perm))

    def t(arr):
        return np.transpose(arr, inv) if tuple(arr.shape) == phys else arr

    return t


def _load_transform(p):
    """Logical→physical permutation applied on restore, matching the
    trainer's CURRENT layout (which may differ from save time — the
    checkpoint itself is always logical)."""
    perm = getattr(p, "_layout_perm", None)
    if perm is None:
        return None
    logical = tuple(p._shape)
    if tuple(logical[i] for i in perm) == logical:
        return None

    def t(arr):
        return (np.transpose(arr, perm)
                if tuple(arr.shape) == logical else arr)

    return t


def _stale_indices(trainer):
    """Param indices whose grad buffer is STALE (untouched since their
    last update) — Trainer.update's `_grad_versions.get(i) == g._version`
    test, persisted as indices since raw versions don't survive a
    process boundary."""
    stale = []
    for i, p in enumerate(trainer._params):
        if p.grad_req == "null" or p._data_map is None:
            continue
        grads = p.list_grad()
        if grads and trainer._grad_versions.get(i) == grads[0]._version:
            stale.append(i)
    return stale


def capture(trainer, user_state=None):
    """Snapshot `trainer`'s complete training state.

    Returns (arrays, meta). Arrays are host numpy copies taken NOW —
    after this returns, training may mutate params freely while the
    manager writes the copies out asynchronously.
    """
    arrays = {}
    param_names, param_dtypes, param_shapes = [], [], []
    layout_perms = []
    for i, p in enumerate(trainer._params):
        p._check_initialized()
        # logical (declared) shape regardless of any persistent NHWC
        # re-layout, so checkpoints are portable across MXTPU_LAYOUT
        arrays[f"param/{i}"] = p.logical_data().asnumpy()
        param_names.append(p.name)
        param_dtypes.append(str(np.dtype(p.dtype)) if p.dtype else None)
        param_shapes.append(list(arrays[f"param/{i}"].shape))
        perm = getattr(p, "_layout_perm", None)
        layout_perms.append(list(perm) if perm is not None else None)
    state_specs = [_state_spec(s, f"opt/{i}", arrays,
                               _save_transform(trainer._params[i]))
                   for i, s in enumerate(trainer._states)]
    meta = {
        "snapshot_version": SNAPSHOT_VERSION,
        "num_params": len(trainer._params),
        "param_names": param_names,
        "param_dtypes": param_dtypes,
        "param_shapes": param_shapes,
        "state_specs": state_specs,
        "states_created": list(trainer._states_created),
        "optimizer": trainer._optimizer.bookkeeping_state(),
        "stale_indices": _stale_indices(trainer),
        # observability only: which params were physically re-laid-out
        # at save time (arrays are ALWAYS logical — apply re-permutes
        # to whatever the restoring trainer's layout is)
        "layout_perms": layout_perms,
        # the ShardingPlan this run trained under (docs/sharding.md):
        # arrays above are host numpy — asnumpy() gathers every shard —
        # so the payload itself is placement-free; the record is for
        # provenance (verify_checkpoint) and tooling.  apply() re-places
        # onto the RESTORING trainer's plan, so replicated↔dp↔dp×tp
        # moves are just save + restore.
        "sharding_plan": (trainer.sharding_plan.to_manifest()
                          if getattr(trainer, "sharding_plan", None)
                          is not None else None),
        "scale": trainer._scale,
        "user_state": user_state,
    }
    from .. import _random

    if _random._rng.key is not None:
        arrays["rng/key"] = np.asarray(_random._rng.key)
        meta["rng_key_dtype"] = str(np.asarray(_random._rng.key).dtype)
    return arrays, meta


def apply(trainer, arrays, meta):
    """Load a snapshot into `trainer` (inverse of `capture`).

    Validates param count / name / dtype against the payload and raises
    CheckpointError on mismatch BEFORE touching any state, so a failed
    restore never leaves the trainer half-loaded.
    """
    import jax.numpy as jnp

    n = meta.get("num_params")
    if n != len(trainer._params):
        raise CheckpointError(
            f"checkpoint holds {n} params but trainer has "
            f"{len(trainer._params)} — wrong model or wrong checkpoint")
    names = meta.get("param_names") or []
    dtypes = meta.get("param_dtypes") or []
    for i, p in enumerate(trainer._params):
        if i < len(names) and names[i] != p.name:
            raise CheckpointError(
                f"param {i} name mismatch: checkpoint has {names[i]!r}, "
                f"trainer has {p.name!r}")
        want = dtypes[i] if i < len(dtypes) else None
        have = str(np.dtype(p.dtype)) if p.dtype else None
        if want is not None and have is not None and want != have:
            raise CheckpointError(
                f"param {i} ({p.name}) dtype mismatch: checkpoint has "
                f"{want}, trainer declares {have}")
        if f"param/{i}" not in arrays:
            raise CheckpointError(f"missing array param/{i} ({p.name})")

    for i, p in enumerate(trainer._params):
        p.set_data(arrays[f"param/{i}"])  # fans out to every device copy
    specs = meta.get("state_specs") or [None] * len(trainer._params)
    trainer._states = [
        _state_from_spec(s, arrays, _load_transform(trainer._params[i]))
        for i, s in enumerate(specs)]
    trainer._states_created = list(
        meta.get("states_created") or [s is not None for s in specs])
    opt_meta = meta.get("optimizer")
    if opt_meta:
        trainer._optimizer.load_bookkeeping_state(opt_meta)
    trainer._scale = float(meta.get("scale", 1.0))
    # re-mark stale grads against THIS process's buffer versions
    trainer._grad_versions = {}
    for i in meta.get("stale_indices") or []:
        p = trainer._params[i]
        if p.grad_req != "null" and p._data_map is not None:
            grads = p.list_grad()
            if grads:
                trainer._grad_versions[i] = grads[0]._version
    # re-place restored arrays onto the RESTORING trainer's plan (which
    # may differ from the save-time plan recorded in meta): set_data /
    # the state rebuild above landed everything at default placement,
    # so a dp=4 checkpoint loads into a replicated run — and vice versa
    # — by re-running plan application here
    plan = getattr(trainer, "_sharding_plan", None)
    if plan is not None:
        trainer._plan_applied = False
        trainer._maybe_apply_plan()
        if trainer._plan_applied:
            from ..optimizer.optimizer import place_state_like

            for i, p in enumerate(trainer._params):
                if trainer._states_created[i]:
                    place_state_like(trainer._states[i], p.data(),
                                     plan=plan,
                                     name=trainer._param_names[i])
    if "rng/key" in arrays:
        from .. import _random

        key = jnp.asarray(arrays["rng/key"])
        want = meta.get("rng_key_dtype")
        if want:
            key = key.astype(want)
        _random._rng.key = key
