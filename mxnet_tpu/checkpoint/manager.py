"""Atomic, async, retained checkpoints over the engine IO path.

Commit protocol (the tentpole guarantee): a checkpoint becomes visible
ONLY via a directory rename —

    .tmp-step-XXXXXXXX/            (invisible to restore)
        arrays.npz                 write + flush + fsync
        MANIFEST.json              write + fsync   (per-array crc32s)
        <dirfsync>
    os.replace(tmp, step-XXXXXXXX) atomic on POSIX
    <parent dirfsync>

so a SIGKILL at ANY point leaves either the previous committed
checkpoint intact (tmp dirs are ignored and reaped) or the new one
fully present with a checksummed manifest. Both write and commit are
pushed through `_checkpoint_io.async_run` on ONE engine var keyed by
the final directory, so the commit can never overtake (or run despite)
a failed payload write, training overlaps the serialization, and
`flush()`/`restore()`/`flush_all()` barrier on exactly the right var.

Distributed (kvstore='tpu_dist'): EVERY rank — writer or not — runs the
identical three-fence sequence (post-mkdir, pre-commit, post-commit);
barrier() is a collective, so a rank skipping any fence would deadlock
the rest. `replicated` mode has rank 0 write while the other ranks meet
the fences with no-op write/commit; `sharded` mode has each rank persist
`shard-NNNNN.npz` + its fragment manifest into the shared tmp dir
BEFORE the pre-commit fence, so rank 0's merge into the final
MANIFEST.json never reads a missing or partial fragment. Multi-worker
saves are forced synchronous — the barrier is a collective and must run
on the main thread, not an engine IO thread.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib

import numpy as np

from .. import _checkpoint_io
from .._dtype_codec import decode_npz, encode_payload
from ..diagnostics import spans as _spans
from ..telemetry import instruments as _telemetry
from . import snapshot as _snapshot
from .errors import (CheckpointCorrupt, CheckpointError,
                     CheckpointNotFound, PlanMismatch)

__all__ = ["CheckpointManager", "RestoreResult", "verify_checkpoint"]

MANIFEST_NAME = "MANIFEST.json"
FORMAT_VERSION = 1
_STEP_FMT = "step-{:08d}"
_TMP_FMT = ".tmp-step-{:08d}"

# test seam: called with the payload path on the IO thread right before
# the npz write starts — lets tests hold a write open (to SIGKILL the
# process mid-write, or to prove save() returns while the write runs)
_WRITE_BEGIN_HOOK = None


def _crc(a):
    """crc32 of an array's raw bytes. Bit-equal whether computed on the
    true exotic dtype or its npz uint view, so capture-time and
    verify-time checksums agree."""
    return zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF


def _fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _step_of(name):
    if name.startswith("step-"):
        try:
            return int(name[5:])
        except ValueError:
            return None
    return None


class RestoreResult:
    """What restore() hands back: the resumed step, the user-state blob
    saved alongside (dataloader cursor etc.), and the raw manifest."""

    def __init__(self, step, user_state, manifest):
        self.step = step
        self.user_state = user_state
        self.manifest = manifest

    def __repr__(self):
        return f"RestoreResult(step={self.step})"


class CheckpointManager:
    """Snapshot/restore complete training state with atomic commits,
    retention, and async writes (docs/checkpointing.md)."""

    def __init__(self, directory, trainer=None, *, keep_last=None,
                 keep_every_n_steps=None, mode=None, kvstore=None,
                 verify=None, async_save=None, user_meta=None):
        from .. import env as _env

        self.directory = os.path.abspath(str(directory))
        self._trainer = trainer
        self._kv = kvstore if kvstore is not None else (
            getattr(trainer, "_kvstore", None) if trainer is not None
            else None)
        self.keep_last = _env.get("MXTPU_CKPT_KEEP_LAST") \
            if keep_last is None else int(keep_last)
        self.keep_every_n_steps = _env.get("MXTPU_CKPT_KEEP_EVERY_N") \
            if keep_every_n_steps is None else int(keep_every_n_steps)
        self.mode = (_env.get("MXTPU_CKPT_MODE") if mode is None
                     else mode).lower()
        if self.mode not in ("replicated", "sharded"):
            raise ValueError(
                f"mode must be 'replicated' or 'sharded', got {self.mode!r}")
        self.verify = _env.get("MXTPU_CKPT_VERIFY") \
            if verify is None else bool(verify)
        self.async_save = _env.get("MXTPU_CKPT_ASYNC") \
            if async_save is None else bool(async_save)
        self.user_meta = user_meta
        self._lock = threading.Lock()   # serializes retention vs. scans
        self._pending = []              # final dirs with in-flight ops
        os.makedirs(self.directory, exist_ok=True)
        if self._rank == 0:
            self._clean_stale_tmp()

    # -- topology ----------------------------------------------------------
    @property
    def _rank(self):
        return getattr(self._kv, "rank", 0) if self._kv is not None else 0

    @property
    def _world(self):
        return getattr(self._kv, "num_workers", 1) \
            if self._kv is not None else 1

    def _barrier(self):
        if self._kv is not None and self._world > 1:
            self._kv.barrier()

    def bind(self, trainer):
        """Attach (or swap) the trainer this manager snapshots."""
        self._trainer = trainer
        if self._kv is None:
            self._kv = getattr(trainer, "_kvstore", None)
        return self

    # -- discovery ---------------------------------------------------------
    def steps(self):
        """Committed checkpoint steps, ascending. A step dir without a
        manifest (impossible via the commit protocol, but a truncated
        copy could produce one) is not 'committed'."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            s = _step_of(n)
            if s is not None and os.path.isfile(
                    os.path.join(self.directory, n, MANIFEST_NAME)):
                out.append(s)
        return sorted(out)

    def latest_step(self):
        """Newest committed step, or None when the directory is empty."""
        steps = self.steps()
        return steps[-1] if steps else None

    def step_dir(self, step):
        return os.path.join(self.directory, _STEP_FMT.format(step))

    def _clean_stale_tmp(self):
        """Reap .tmp-* leftovers from a previous process killed mid-write
        (they are by definition uncommitted — never loadable)."""
        for n in os.listdir(self.directory):
            if n.startswith(".tmp-"):
                shutil.rmtree(os.path.join(self.directory, n),
                              ignore_errors=True)

    # -- save --------------------------------------------------------------
    def save(self, step=None, user_state=None, sync=None, reason="periodic"):
        """Snapshot now; write/commit asynchronously (unless `sync`).

        Captures host copies of all state before returning, so training
        may continue mutating params immediately — the engine IO thread
        serializes and commits in the background. Returns the step.

        `user_state` must be JSON-serializable; it comes back verbatim
        from `restore()` (dataloader epoch/batch cursor, etc.).
        """
        if self._trainer is None:
            raise CheckpointError(
                "CheckpointManager has no trainer bound — pass one at "
                "construction or call bind(trainer)")
        if step is None:
            step = _spans.current_step()
        step = int(step)
        t0 = time.perf_counter()
        with _spans.span("ckpt.capture", cat="checkpoint"):
            arrays, meta = _snapshot.capture(self._trainer,
                                             user_state=user_state)
        world, rank = self._world, self._rank
        sync = (not self.async_save) if sync is None else bool(sync)
        if world > 1:
            sync = True  # commit barrier is a collective: main thread only
        final = self.step_dir(step)
        tmp = os.path.join(self.directory, _TMP_FMT.format(step))
        # which ranks write a payload file into tmp (non-writers still run
        # the exact same barrier sequence below — barrier() is a collective,
        # so EVERY rank must meet EVERY fence or the writers deadlock)
        writer = self.mode == "sharded" or rank == 0
        if world > 1:
            # multi-worker saves are always sync, so no queued async op can
            # still be writing into tmp — main-thread reset is safe here
            if rank == 0:
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp)
            self._barrier()  # writers must not race rank 0's mkdir

        entries = {}      # manifest "arrays" section (this rank's share)
        my_arrays = {}
        if self.mode == "sharded":
            fname = f"shard-{rank:05d}.npz"
            names = sorted(arrays)
            my_names = [n for i, n in enumerate(names) if i % world == rank]
        else:
            fname = "arrays.npz"
            my_names = sorted(arrays) if writer else []
        for n in my_names:
            a = np.asarray(arrays[n])
            my_arrays[n] = a
            entries[n] = {"file": fname, "shape": list(a.shape),
                          "dtype": str(a.dtype), "crc32": _crc(a),
                          "nbytes": int(a.nbytes)}
        nbytes = sum(e["nbytes"] for e in entries.values())
        payload_path = os.path.join(tmp, fname)
        manifest = {
            "format_version": FORMAT_VERSION,
            "library_version": _library_version(),
            "step": step,
            "time": time.time(),
            "world_size": world,
            "mode": self.mode,
            "reason": reason,
            "user_meta": self.user_meta,
            "meta": meta,
            "arrays": entries,
        }

        def write_op():
            if world == 1:
                # tmp reset runs on the serialized IO chain, so a queued
                # async write for a re-save of the same step can never have
                # its directory pulled out from under it
                shutil.rmtree(tmp, ignore_errors=True)
                os.makedirs(tmp)
            if not writer:
                return
            hook = _WRITE_BEGIN_HOOK
            if hook is not None:
                hook(payload_path)
            payload = encode_payload(my_arrays)
            with open(payload_path, "wb") as f:
                np.savez(f, **payload)
                f.flush()
                os.fsync(f.fileno())
            if self.mode == "sharded" and world > 1:
                # the fragment manifest must be durable BEFORE the
                # pre-commit barrier — rank 0's merge reads every fragment
                _write_json(
                    os.path.join(tmp, f"MANIFEST.shard-{rank:05d}.json"),
                    manifest)

        def commit_op():
            if _checkpoint_io.pending_error(final) is not None:
                # payload write failed: never commit on top of it — but a
                # failed save must still show up in metrics
                if writer:
                    _telemetry.record_ckpt_save(
                        self.mode, (time.perf_counter() - t0) * 1e3,
                        nbytes, "error")
                return
            if rank == 0:
                self._commit(tmp, final, manifest, world)
            if writer:
                _telemetry.record_ckpt_save(
                    self.mode, (time.perf_counter() - t0) * 1e3, nbytes,
                    "ok")

        if sync and world > 1:
            # ops run inline: the fences are collectives and must
            # interleave with the writes on the main thread. Every rank —
            # writer or not — executes this identical barrier sequence.
            write_op()
            self._barrier()  # payloads + fragment manifests all on disk
            commit_op()
            _checkpoint_io.wait_for_path(final)  # surface fallback errors
            self._barrier()  # nobody proceeds before the rename landed
        elif sync:
            # push through the path var so this save serializes with any
            # still-pending async save of the same step, then barrier
            _checkpoint_io.async_run(final, write_op)
            _checkpoint_io.async_run(final, commit_op)
            _checkpoint_io.wait_for_path(final)
        else:
            _checkpoint_io.async_run(final, write_op)
            _checkpoint_io.async_run(final, commit_op)
            with self._lock:
                if final not in self._pending:
                    self._pending.append(final)
        return step

    def _commit(self, tmp, final, manifest, world):
        """Manifest + fsync + rename. Runs on the IO thread (async) or
        inline (sync); in multi-worker mode only rank 0 gets here. Sharded
        fragment manifests are already on disk (each rank's write_op wrote
        its own before the pre-commit barrier) — merge them here."""
        if self.mode == "sharded" and world > 1:
            merged = dict(manifest)
            merged["arrays"] = {}
            for r in range(world):
                fp = os.path.join(tmp, f"MANIFEST.shard-{r:05d}.json")
                with open(fp, encoding="utf-8") as f:
                    merged["arrays"].update(json.load(f)["arrays"])
            manifest = merged
        _write_json(os.path.join(tmp, MANIFEST_NAME), manifest)
        _fsync_dir(tmp)
        if os.path.isdir(final):
            # re-saving an existing step replaces it (os.replace cannot
            # overwrite a non-empty dir)
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_dir(self.directory)
        with self._lock:
            self._apply_retention()

    def _apply_retention(self):
        if self.keep_last <= 0:
            return
        steps = self.steps()
        drop = steps[:-self.keep_last] if len(steps) > self.keep_last else []
        for s in drop:
            if self.keep_every_n_steps > 0 and \
                    s % self.keep_every_n_steps == 0:
                continue  # milestone: retained forever
            shutil.rmtree(self.step_dir(s), ignore_errors=True)

    def flush(self):
        """Barrier every save issued by THIS manager; re-raises the first
        write/commit failure (original traceback intact)."""
        with self._lock:
            pending, self._pending = self._pending, []
        first = None
        for p in pending:
            try:
                _checkpoint_io.wait_for_path(p)
            except Exception as e:  # noqa: PERF203 — drain all, raise first
                if first is None:
                    first = e
        if first is not None:
            raise first

    # -- restore -----------------------------------------------------------
    def restore(self, step=None, trainer=None, allow_reshard=False):
        """Load a committed checkpoint into the trainer.

        step=None walks committed steps newest-first, skipping corrupt
        ones with a warning (telemetry `ckpt_restore_total{outcome=
        "corrupt"}`); raises CheckpointNotFound when none load. An
        explicit `step` raises CheckpointNotFound if absent and
        CheckpointCorrupt if damaged — never silently substitutes
        another step. Returns a RestoreResult.

        When both the checkpoint and the trainer carry a ShardingPlan
        and their world sizes (mesh device counts) differ, restore is a
        topology migration and raises PlanMismatch unless
        `allow_reshard=True` opts in (elastic.resharded_restore is the
        documented front door; docs/elasticity.md). Same-world plan
        changes re-place silently, as ever — arrays are host-gathered.
        """
        trainer = trainer or self._trainer
        if trainer is None:
            raise CheckpointError("restore() needs a trainer "
                                  "(bind one or pass trainer=)")
        self.flush()
        self._barrier()  # an in-flight rank-0 commit must land first
        if step is not None:
            step = int(step)
            if not os.path.isfile(os.path.join(self.step_dir(step),
                                               MANIFEST_NAME)):
                _telemetry.record_ckpt_restore("not_found")
                raise CheckpointNotFound(
                    f"no committed checkpoint for step {step} "
                    f"in {self.directory}")
            return self._load(step, trainer, allow_reshard)
        candidates = self.steps()
        if not candidates:
            _telemetry.record_ckpt_restore("not_found")
            raise CheckpointNotFound(
                f"no committed checkpoint in {self.directory}")
        last_err = None
        for s in reversed(candidates):
            try:
                return self._load(s, trainer, allow_reshard)
            except CheckpointCorrupt as e:  # noqa: PERF203
                import warnings

                warnings.warn(
                    f"checkpoint step {s} is corrupt ({e}); "
                    f"falling back to an earlier one", stacklevel=2)
                last_err = e
        _telemetry.record_ckpt_restore("not_found")
        raise CheckpointNotFound(
            f"all {len(candidates)} checkpoints in {self.directory} "
            f"are corrupt") from last_err

    def _check_plan(self, manifest, trainer, allow_reshard, d):
        """The PlanMismatch gate: returns the compatibility report when
        the restore crosses plans (None for exact resumes). Only a
        plan-to-plan world-size change is gated — restoring onto a
        plan-less trainer (host-gathered arrays land replicated) or
        from a plan-less checkpoint (first placement) stays silent."""
        saved = (manifest.get("meta") or {}).get("sharding_plan")
        plan = getattr(trainer, "sharding_plan", None)
        if saved is None and plan is None:
            return None
        from ..elastic import reshard as _reshard

        compat = _reshard.plan_compatibility(saved, plan)
        if compat["verdict"] == "exact":
            return None
        if compat["verdict"] == "reshard" and saved is not None \
                and plan is not None and not allow_reshard:
            _telemetry.record_ckpt_restore("plan_mismatch")
            raise PlanMismatch(
                f"{d}: checkpoint was saved under a "
                f"{compat['saved_world']}-device plan "
                f"({compat['saved_axes']}) but the trainer's plan spans "
                f"{compat['target_world']} devices "
                f"({compat['target_axes']}) — a topology migration. "
                f"Pass allow_reshard=True (or use "
                f"elastic.resharded_restore / tools/ckpt.py reshard) "
                f"to opt in (docs/elasticity.md)",
                saved_plan=saved, target_plan=plan.to_manifest())
        return compat

    def _load(self, step, trainer, allow_reshard=False):
        d = self.step_dir(step)
        try:
            arrays, manifest = _read_checkpoint(d, verify=self.verify)
        except CheckpointError:
            _telemetry.record_ckpt_restore("corrupt")
            raise
        compat = self._check_plan(manifest, trainer, allow_reshard, d)
        t0 = time.perf_counter()
        with _spans.span("ckpt.restore", cat="checkpoint"):
            try:
                _snapshot.apply(trainer, arrays, manifest["meta"])
            except CheckpointError:
                _telemetry.record_ckpt_restore("error")
                raise
        if compat is not None:
            # a cross-plan restore IS the reshard (apply re-placed every
            # array under the target plan): time it and leave the
            # migration in the flight record
            _telemetry.record_reshard(
                (time.perf_counter() - t0) * 1e3,
                saved_world=compat["saved_world"],
                target_world=compat["target_world"], site="restore")
        _telemetry.record_ckpt_restore("ok")
        return RestoreResult(step, manifest["meta"].get("user_state"),
                             manifest)


def _library_version():
    from .. import __version__

    return __version__


def _write_json(path, obj):
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())


def _read_checkpoint(d, verify=True):
    """Load + validate one committed checkpoint dir. Returns
    (arrays, manifest); raises CheckpointCorrupt on any damage."""
    mpath = os.path.join(d, MANIFEST_NAME)
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
    except FileNotFoundError:
        raise CheckpointCorrupt(f"{d}: missing {MANIFEST_NAME}") from None
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointCorrupt(f"{d}: unreadable manifest: {e}") from e
    if manifest.get("format_version") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            f"{d}: unsupported format_version "
            f"{manifest.get('format_version')!r}")
    entries = manifest.get("arrays")
    if not isinstance(entries, dict):
        raise CheckpointCorrupt(f"{d}: manifest has no arrays section")
    arrays = {}
    for fname in sorted({e["file"] for e in entries.values()}):
        fp = os.path.join(d, fname)
        try:
            with np.load(fp) as npz:
                arrays.update(decode_npz(npz))
        except FileNotFoundError:
            raise CheckpointCorrupt(f"{d}: missing payload {fname}") \
                from None
        except Exception as e:
            raise CheckpointCorrupt(
                f"{d}: unreadable payload {fname}: {e}") from e
    for name, e in entries.items():
        if name not in arrays:
            raise CheckpointCorrupt(
                f"{d}: manifest lists {name!r} but {e['file']} lacks it")
        a = arrays[name]
        if list(a.shape) != list(e["shape"]) or str(a.dtype) != e["dtype"]:
            raise CheckpointCorrupt(
                f"{d}: {name!r} is {a.dtype}{list(a.shape)}, manifest "
                f"says {e['dtype']}{e['shape']}")
        if verify and _crc(a) != e["crc32"]:
            raise CheckpointCorrupt(
                f"{d}: checksum mismatch on {name!r} "
                f"(bit-rot or truncated write)")
    extra = set(arrays) - set(entries)
    if extra:
        raise CheckpointCorrupt(
            f"{d}: payload holds arrays absent from manifest: "
            f"{sorted(extra)[:4]}")
    return arrays, manifest


def verify_checkpoint(directory, step=None):
    """Offline integrity report for tools/ckpt.py: checks manifest,
    payload presence, shapes/dtypes, and per-array crc32 WITHOUT needing
    a trainer. Returns a JSON-able report dict (never raises for
    validation failures — they land in report['errors'])."""
    directory = os.path.abspath(str(directory))
    mgr_steps = []
    try:
        for n in os.listdir(directory):
            s = _step_of(n)
            if s is not None and os.path.isfile(
                    os.path.join(directory, n, MANIFEST_NAME)):
                mgr_steps.append(s)
    except FileNotFoundError:
        return {"directory": directory, "step": step, "ok": False,
                "found": False, "errors": ["directory does not exist"]}
    mgr_steps.sort()
    if step is None:
        if not mgr_steps:
            return {"directory": directory, "step": None, "ok": False,
                    "found": False,
                    "errors": ["no committed checkpoints"]}
        step = mgr_steps[-1]
    step = int(step)
    d = os.path.join(directory, _STEP_FMT.format(step))
    if not os.path.isfile(os.path.join(d, MANIFEST_NAME)):
        return {"directory": directory, "step": step, "ok": False,
                "found": False,
                "errors": [f"no committed checkpoint for step {step}"]}
    report = {"directory": directory, "step": step, "found": True,
              "errors": []}
    try:
        arrays, manifest = _read_checkpoint(d, verify=True)
    except CheckpointCorrupt as e:
        report["ok"] = False
        report["errors"].append(str(e))
        return report
    report["ok"] = True
    report["arrays"] = len(arrays)
    report["nbytes"] = sum(int(e["nbytes"])
                           for e in manifest["arrays"].values())
    report["world_size"] = manifest.get("world_size")
    report["mode"] = manifest.get("mode")
    report["library_version"] = manifest.get("library_version")
    report["manifest_step"] = manifest.get("step")
    # the plan the run trained under (None = unsharded); restore onto
    # ANY plan is legal — arrays are host-gathered — so this is
    # provenance, not a constraint
    report["sharding_plan"] = (manifest.get("meta") or {}).get(
        "sharding_plan")
    if manifest.get("step") != step:
        report["ok"] = False
        report["errors"].append(
            f"manifest step {manifest.get('step')} != dir step {step}")
    return report
