"""Minimal ONNX protobuf wire-format encoder/decoder (no onnx dependency).

The environment has no `onnx` package, so the exporter emits the protobuf
wire format directly (field numbers from the stable onnx.proto schema) and
the decoder here doubles as the structural checker the reference got from
onnx.checker. Wire format: tag = (field_num << 3) | wire_type; wire types:
0 = varint, 1 = fixed64, 2 = length-delimited, 5 = fixed32.
"""
from __future__ import annotations

import struct

import numpy as _np

# TensorProto.DataType
DTYPE = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}
DTYPE_REV = {v: k for k, v in DTYPE.items()}

# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


def _varint(n):
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wt):
    return _varint((field << 3) | wt)


def f_int(field, v):
    return _tag(field, 0) + _varint(int(v))


def f_bytes(field, b):
    return _tag(field, 2) + _varint(len(b)) + bytes(b)


def f_str(field, s):
    return f_bytes(field, s.encode())


f_msg = f_bytes


def f_float(field, v):
    return _tag(field, 5) + struct.pack("<f", v)


def f_rep_int(field, vals):
    return b"".join(f_int(field, v) for v in vals)


# --- ONNX message builders -------------------------------------------------

def tensor(name, arr):
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = _np.asarray(arr)
    dt = DTYPE[str(arr.dtype)]
    body = f_rep_int(1, arr.shape)
    body += f_int(2, dt)
    body += f_str(8, name)
    body += f_bytes(9, arr.astype(arr.dtype, order="C").tobytes())
    return body


def value_info(name, shape, elem_type=1):
    """ValueInfoProto: name=1, type=2{tensor_type=1{elem_type=1, shape=2}}."""
    dims = b"".join(
        f_msg(1, f_str(2, d) if isinstance(d, str) else f_int(1, d))
        for d in shape)
    ttype = f_int(1, elem_type) + f_msg(2, dims)
    return f_str(1, name) + f_msg(2, f_msg(1, ttype))


def attr(name, value):
    """AttributeProto with type tagging."""
    body = f_str(1, name)
    if isinstance(value, bool):
        body += f_int(3, int(value)) + f_int(20, ATTR_INT)
    elif isinstance(value, int):
        body += f_int(3, value) + f_int(20, ATTR_INT)
    elif isinstance(value, float):
        body += f_float(2, value) + f_int(20, ATTR_FLOAT)
    elif isinstance(value, str):
        body += f_bytes(4, value.encode()) + f_int(20, ATTR_STRING)
    elif isinstance(value, bytes):
        body += f_bytes(4, value) + f_int(20, ATTR_STRING)
    elif isinstance(value, _np.ndarray):
        body += f_msg(5, tensor(name + "_t", value)) + f_int(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            body += b"".join(f_float(7, v) for v in value)
            body += f_int(20, ATTR_FLOATS)
        elif value and isinstance(value[0], str):
            body += b"".join(f_bytes(9, v.encode()) for v in value)
            body += f_int(20, ATTR_STRINGS)
        else:
            body += b"".join(f_int(8, int(v)) for v in value)
            body += f_int(20, ATTR_INTS)
    else:
        raise TypeError(f"attr {name}: unsupported {type(value)}")
    return body


def node(op_type, inputs, outputs, name="", attrs=None):
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    body = b"".join(f_str(1, i) for i in inputs)
    body += b"".join(f_str(2, o) for o in outputs)
    if name:
        body += f_str(3, name)
    body += f_str(4, op_type)
    for k, v in (attrs or {}).items():
        body += f_msg(5, attr(k, v))
    return body


def graph(nodes, name, initializers, inputs, outputs):
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    body = b"".join(f_msg(1, n) for n in nodes)
    body += f_str(2, name)
    body += b"".join(f_msg(5, t) for t in initializers)
    body += b"".join(f_msg(11, v) for v in inputs)
    body += b"".join(f_msg(12, v) for v in outputs)
    return body


def model(graph_bytes, opset=11, producer="mxnet_tpu"):
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8."""
    body = f_int(1, 7)  # IR version 7 pairs with opset 11
    body += f_str(2, producer)
    body += f_msg(7, graph_bytes)
    body += f_msg(8, f_str(1, "") + f_int(2, opset))
    return body


# --- decoder (structural checker) ------------------------------------------

def _read_varint(buf, pos):
    out = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(buf):
    """Yield (field_num, wire_type, value) over a message buffer."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _read_varint(buf, pos)
            if v >= 1 << 63:  # two's-complement int64 (e.g. axis=-1)
                v -= 1 << 64
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wt == 1:
            v = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise ValueError(f"bad wire type {wt}")
        yield field, wt, v


def parse_tensor(buf):
    out = {"dims": [], "name": None, "data_type": None, "raw": None}
    for field, _, v in _fields(buf):
        if field == 1:
            out["dims"].append(v)
        elif field == 2:
            out["data_type"] = v
        elif field == 8:
            out["name"] = v.decode()
        elif field == 9:
            out["raw"] = v
    if out["raw"] is not None and out["data_type"] in DTYPE_REV:
        out["array"] = _np.frombuffer(
            out["raw"], DTYPE_REV[out["data_type"]]).reshape(out["dims"])
    return out


def parse_node(buf):
    out = {"input": [], "output": [], "op_type": None, "name": "",
           "attrs": {}}
    for field, _, v in _fields(buf):
        if field == 1:
            out["input"].append(v.decode())
        elif field == 2:
            out["output"].append(v.decode())
        elif field == 3:
            out["name"] = v.decode()
        elif field == 4:
            out["op_type"] = v.decode()
        elif field == 5:
            a = _parse_attr(v)
            out["attrs"][a[0]] = a[1]
    return out


def _parse_attr(buf):
    name, val, ints, floats, strings = None, None, [], [], []
    for field, wt, v in _fields(buf):
        if field == 1:
            name = v.decode()
        elif field == 2:
            val = v
        elif field == 3:
            val = v
        elif field == 4:
            val = v.decode() if isinstance(v, (bytes, bytearray)) else v
        elif field == 5:
            val = parse_tensor(v)
        elif field == 7:
            floats.append(v)
        elif field == 8:
            ints.append(v)
        elif field == 9:
            strings.append(v.decode())
    if ints:
        val = ints
    elif floats:
        val = floats
    elif strings:
        val = strings
    return name, val


def parse_graph(buf):
    out = {"nodes": [], "name": None, "initializers": [], "inputs": [],
           "outputs": []}
    for field, _, v in _fields(buf):
        if field == 1:
            out["nodes"].append(parse_node(v))
        elif field == 2:
            out["name"] = v.decode()
        elif field == 5:
            out["initializers"].append(parse_tensor(v))
        elif field == 11:
            out["inputs"].append(_parse_vi(v))
        elif field == 12:
            out["outputs"].append(_parse_vi(v))
    return out


def _parse_vi(buf):
    out = {"name": None, "shape": None, "elem_type": None}
    for field, _, v in _fields(buf):
        if field == 1:
            out["name"] = v.decode()
        elif field == 2:
            for f2, _, tt in _fields(v):
                if f2 == 1:  # tensor_type
                    for f3, _, x in _fields(tt):
                        if f3 == 1:
                            out["elem_type"] = x
                        elif f3 == 2:
                            dims = []
                            for f4, _, d in _fields(x):
                                if f4 == 1:
                                    for f5, _, dv in _fields(d):
                                        if f5 == 1:
                                            dims.append(dv)
                                        elif f5 == 2:
                                            dims.append(dv.decode())
                            out["shape"] = dims
    return out


def parse_model(buf):
    out = {"ir_version": None, "producer": None, "graph": None, "opset": None}
    for field, _, v in _fields(buf):
        if field == 1:
            out["ir_version"] = v
        elif field == 2:
            out["producer"] = v.decode()
        elif field == 7:
            out["graph"] = parse_graph(v)
        elif field == 8:
            for f2, _, x in _fields(v):
                if f2 == 2:
                    out["opset"] = x
    return out


def check_model(buf):
    """Structural sanity (the onnx.checker stand-in): every node input must
    be a graph input, initializer, or earlier node output."""
    m = parse_model(buf)
    g = m["graph"]
    known = {vi["name"] for vi in g["inputs"]}
    known |= {t["name"] for t in g["initializers"]}
    for n in g["nodes"]:
        for i in n["input"]:
            if i and i not in known:
                raise ValueError(
                    f"node {n['name']}({n['op_type']}): undefined input {i!r}")
        known |= set(n["output"])
    for o in g["outputs"]:
        if o["name"] not in known:
            raise ValueError(f"graph output {o['name']!r} undefined")
    return m
