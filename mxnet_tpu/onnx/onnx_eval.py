"""Minimal ONNX graph evaluator over the dependency-free wire decoder.

The image ships neither `onnx` nor `onnxruntime`, so the numeric
round-trip verification the reference ran through onnxruntime
(reference: tests/python-pytest/onnx/test_operators.py) runs here against
this evaluator instead: export -> parse_model -> evaluate(jnp) -> compare
with the original symbol's outputs. Covers exactly the op set
mx2onnx.py emits (opset 11 semantics); unknown ops raise.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _np
from jax import lax

from . import _proto as P

__all__ = ["evaluate", "run_model"]


def _pool(x, kernel, strides, pads, kind, count_include_pad=False):
    nd = len(kernel)
    window = (1, 1) + tuple(kernel)
    strides_ = (1, 1) + tuple(strides)
    # ONNX pads: [b1..bn, e1..en]
    pad_cfg = [(0, 0), (0, 0)] + [(int(pads[i]), int(pads[i + nd]))
                                  for i in range(nd)]
    if kind == "max":
        init = -jnp.inf
        out = lax.reduce_window(x, init, lax.max, window, strides_, pad_cfg)
        return out
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_, pad_cfg)
    if count_include_pad:
        return s / _np.prod(kernel)
    ones = jnp.ones_like(x)
    cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides_, pad_cfg)
    return s / cnt


def _conv(x, w, b, attrs):
    group = int(attrs.get("group", 1))
    nd = w.ndim - 2
    strides = tuple(attrs.get("strides", [1] * nd))
    dil = tuple(attrs.get("dilations", [1] * nd))
    pads = attrs.get("pads", [0] * (2 * nd))
    pad_cfg = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW") if nd == 2
                                    else ("NCH", "OIH", "NCH"))
    out = lax.conv_general_dilated(x, w, strides, pad_cfg,
                                   rhs_dilation=dil, dimension_numbers=dn,
                                   feature_group_count=group)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * nd)
    return out


def _conv_transpose(x, w, b, attrs):
    # ONNX ConvTranspose weight layout: (Cin, Cout/group, kH, kW)
    group = int(attrs.get("group", 1))
    nd = w.ndim - 2
    strides = tuple(attrs.get("strides", [1] * nd))
    pads = attrs.get("pads", [0] * (2 * nd))
    if group != 1:
        raise NotImplementedError("grouped ConvTranspose")
    # equivalent direct form: dilate the input by stride, convolve with the
    # spatially-flipped kernel transposed to OIHW, pad by k-1-p
    wt = jnp.swapaxes(w, 0, 1)            # (Cout, Cin, ...)
    wt = jnp.flip(wt, axis=tuple(range(2, 2 + nd)))
    k = w.shape[2:]
    pad_cfg = [(k[i] - 1 - int(pads[i]), k[i] - 1 - int(pads[i + nd]))
               for i in range(nd)]
    dn = lax.conv_dimension_numbers(x.shape, wt.shape,
                                    ("NCHW", "OIHW", "NCHW") if nd == 2
                                    else ("NCH", "OIH", "NCH"))
    out = lax.conv_general_dilated(x, wt, (1,) * nd, pad_cfg,
                                   lhs_dilation=strides,
                                   dimension_numbers=dn)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * nd)
    return out


def _softmax_block(x, axis):
    """Opset-11 semantics: flatten [axis:] and softmax over the block."""
    axis = axis % x.ndim
    shp = x.shape
    flat = x.reshape(shp[:axis] + (-1,))
    out = jax.nn.softmax(flat, axis=-1)
    return out.reshape(shp)


def _lrn(x, attrs):
    size = int(attrs["size"])
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    bias = float(attrs.get("bias", 1.0))
    half = (size - 1) // 2
    sq = x * x
    pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    acc = jnp.pad(sq, pad)
    window = sum(acc[:, i:i + x.shape[1]] for i in range(size))
    return x / (bias + alpha / size * window) ** beta


def _topk(x, k, attrs):
    axis = int(attrs.get("axis", -1))
    largest = int(attrs.get("largest", 1))
    k = int(k)
    if largest:
        idx = jnp.argsort(-x, axis=axis)
    else:
        idx = jnp.argsort(x, axis=axis)
    idx = lax.slice_in_dim(idx, 0, k, axis=axis % x.ndim)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    return vals, idx.astype(jnp.int64)


def _slice_op(data, starts, ends, axes=None, steps=None):
    starts = _np.asarray(starts).tolist()
    ends = _np.asarray(ends).tolist()
    axes = (_np.asarray(axes).tolist() if axes is not None
            else list(range(len(starts))))
    steps = (_np.asarray(steps).tolist() if steps is not None
             else [1] * len(starts))
    sl = [slice(None)] * data.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        n = data.shape[ax]
        if sp < 0:
            st = min(st, n - 1) if st >= 0 else st + n
            en = None if en <= -(2 ** 31) + n else (en if en >= 0
                                                   else en + n)
            sl[ax] = slice(st, en, sp)
        else:
            sl[ax] = slice(st, min(en, n) if en >= 0 else en, sp)
    return data[tuple(sl)]


def _reshape(data, shape):
    shape = [int(v) for v in _np.asarray(shape).tolist()]
    out = []
    for i, d in enumerate(shape):
        out.append(data.shape[i] if d == 0 else d)
    return data.reshape(out)


def _gemm(a, b, c, attrs):
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    if int(attrs.get("transA", 0)):
        a = a.T
    if int(attrs.get("transB", 0)):
        b = b.T
    y = alpha * (a @ b)
    if c is not None:
        y = y + beta * c
    return y


def _onehot(indices, depth, values, attrs):
    axis = int(attrs.get("axis", -1))
    depth = int(_np.asarray(depth).reshape(()))
    off, on = _np.asarray(values).tolist()
    oh = jax.nn.one_hot(jnp.asarray(indices).astype(jnp.int32), depth,
                        axis=axis)
    return oh * (on - off) + off


def _pad_op(data, attrs, pads=None, value=None):
    pads = attrs.get("pads", pads)
    pads = _np.asarray(pads).tolist()
    nd = data.ndim
    cfg = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
    mode = attrs.get("mode", "constant")
    if isinstance(mode, bytes):
        mode = mode.decode()
    cval = float(attrs.get("value", 0.0) if value is None
                 else _np.asarray(value).reshape(()))
    if mode == "constant":
        return jnp.pad(data, cfg, constant_values=cval)
    return jnp.pad(data, cfg, mode={"reflect": "reflect",
                                    "edge": "edge"}[mode])


def _depth_space(x, attrs, to_depth):
    bs = int(attrs["blocksize"])
    n, c, h, w = x.shape
    if to_depth:
        x = x.reshape(n, c, h // bs, bs, w // bs, bs)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * bs * bs, h // bs, w // bs)
    x = x.reshape(n, bs, bs, c // (bs * bs), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (bs * bs), h * bs, w * bs)


def _axes(attrs, default=None):
    ax = attrs.get("axes", default)
    if ax is None:
        return None
    return tuple(int(a) for a in (ax if isinstance(ax, (list, tuple))
                                  else [ax]))


def _reduce(fn):
    def run(x, attrs):
        ax = _axes(attrs)
        keep = bool(attrs.get("keepdims", 1))
        return fn(x, axis=ax, keepdims=keep)

    return run


_ELEM = {
    "Add": jnp.add, "Sub": jnp.subtract, "Mul": jnp.multiply,
    "Div": jnp.divide, "Pow": jnp.power, "Max": jnp.maximum,
    "Min": jnp.minimum, "And": jnp.logical_and, "Or": jnp.logical_or,
    "Xor": jnp.logical_xor,
}
_UNARY = {
    "Neg": jnp.negative, "Exp": jnp.exp, "Log": jnp.log, "Sqrt": jnp.sqrt,
    "Tanh": jnp.tanh, "Abs": jnp.abs, "Sigmoid": jax.nn.sigmoid,
    "Relu": jax.nn.relu, "Erf": jax.scipy.special.erf, "Floor": jnp.floor,
    "Reciprocal": lambda x: 1.0 / x, "Not": jnp.logical_not,
    "Identity": lambda x: x, "Softplus": jax.nn.softplus,
}
_REDUCE = {
    "ReduceSum": _reduce(jnp.sum), "ReduceMean": _reduce(jnp.mean),
    "ReduceMax": _reduce(jnp.max), "ReduceMin": _reduce(jnp.min),
    "ReduceProd": _reduce(jnp.prod),
    "ReduceLogSumExp": _reduce(
        lambda x, axis, keepdims: jax.scipy.special.logsumexp(
            x, axis=axis, keepdims=keepdims)),
}


def _max_roi_pool(x, rois, attrs):
    """MaxRoiPool: rois (R, 5) = [batch_idx, x1, y1, x2, y2] (matches the
    mx ROIPooling layout)."""
    ph, pw = (int(v) for v in attrs["pooled_shape"])
    scale = float(attrs.get("spatial_scale", 1.0))
    x = _np.asarray(x)
    out = []
    for roi in _np.asarray(rois):
        b = int(roi[0])
        x1, y1, x2, y2 = [int(round(float(v) * scale)) for v in roi[1:]]
        h = max(y2 - y1 + 1, 1)
        w = max(x2 - x1 + 1, 1)
        # 0 (not -inf) for empty bins — matches mx ROIPooling's behavior
        # for boxes falling outside the feature map
        pooled = _np.zeros((x.shape[1], ph, pw), x.dtype)
        for i in range(ph):
            hs = y1 + (i * h) // ph
            he = y1 + max(-((-(i + 1) * h) // ph), (i * h) // ph + 1)
            for j in range(pw):
                ws = x1 + (j * w) // pw
                we = x1 + max(-((-(j + 1) * w) // pw),
                              (j * w) // pw + 1)
                hs_c = min(max(hs, 0), x.shape[2])
                he_c = min(max(he, 0), x.shape[2])
                ws_c = min(max(ws, 0), x.shape[3])
                we_c = min(max(we, 0), x.shape[3])
                if he_c > hs_c and we_c > ws_c:
                    pooled[:, i, j] = x[b, :, hs_c:he_c,
                                        ws_c:we_c].max((1, 2))
        out.append(pooled)
    return jnp.asarray(_np.stack(out))


def _resize(x, sizes, attrs):
    """Resize, linear + align_corners (the form the BilinearResize2D
    converter emits)."""
    mode = attrs.get("mode", "nearest")
    tr = attrs.get("coordinate_transformation_mode", "half_pixel")
    x = _np.asarray(x)
    oh, ow = (int(sizes[-2]), int(sizes[-1]))
    h, w = x.shape[-2], x.shape[-1]
    if mode != "linear" or tr != "align_corners":
        raise NotImplementedError(
            f"Resize mode={mode}/{tr} (only linear+align_corners)")

    def coords(out_n, in_n):
        if out_n == 1 or in_n == 1:
            return _np.zeros(out_n)
        return _np.arange(out_n) * ((in_n - 1) / (out_n - 1))

    ys, xs = coords(oh, h), coords(ow, w)
    y0 = _np.clip(_np.floor(ys).astype(int), 0, h - 1)
    x0 = _np.clip(_np.floor(xs).astype(int), 0, w - 1)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]

    def g(yy, xx):
        return x[..., yy, :][..., :, xx]

    out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
           + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
    return jnp.asarray(out.astype(x.dtype))


def _rnn_eval(op, ins, attrs):
    """LSTM/GRU/RNN per the ONNX spec: gate order LSTM [i,o,f,c],
    GRU [z,r,h] (linear_before_reset honored), X (T,N,I),
    W (D,G*H,I), R (D,G*H,H), B (D,2*G*H). Outputs
    (Y (T,D,N,H), Y_h (D,N,H)[, Y_c])."""
    x = _np.asarray(ins[0], _np.float64)
    W = _np.asarray(ins[1], _np.float64)
    R = _np.asarray(ins[2], _np.float64)
    T, N, _I = x.shape
    D, GH, _ = W.shape
    H = int(attrs["hidden_size"])
    G = GH // H
    B = (_np.asarray(ins[3], _np.float64) if len(ins) > 3
         and ins[3] is not None else _np.zeros((D, 2 * G * H)))
    h0 = (_np.asarray(ins[5], _np.float64) if len(ins) > 5
          and ins[5] is not None else _np.zeros((D, N, H)))
    c0 = (_np.asarray(ins[6], _np.float64) if len(ins) > 6
          and ins[6] is not None else _np.zeros((D, N, H)))
    acts = attrs.get("activations")
    sig = lambda v: 1.0 / (1.0 + _np.exp(-v))  # noqa: E731
    Y = _np.zeros((T, D, N, H))
    Yh = _np.zeros((D, N, H))
    Yc = _np.zeros((D, N, H))
    lbr = int(attrs.get("linear_before_reset", 0))
    for d in range(D):
        Wb, Rb = B[d, :G * H], B[d, G * H:]
        h, c = h0[d], c0[d]
        order = range(T) if d == 0 else range(T - 1, -1, -1)
        for t in order:
            gx = x[t] @ W[d].T + Wb
            if op == "LSTM":
                gates = (gx + h @ R[d].T + Rb).reshape(N, 4, H)
                i, o, f = sig(gates[:, 0]), sig(gates[:, 1]), \
                    sig(gates[:, 2])
                c = f * c + i * _np.tanh(gates[:, 3])
                h = o * _np.tanh(c)
            elif op == "GRU":
                xz, xr, xh = (gx.reshape(N, 3, H)[:, k] for k in range(3))
                gh = (h @ R[d].T + Rb).reshape(N, 3, H)
                z = sig(xz + gh[:, 0])
                r = sig(xr + gh[:, 1])
                if lbr:
                    hcand = _np.tanh(xh + r * gh[:, 2])
                else:
                    Rh = R[d][2 * H:3 * H]
                    hcand = _np.tanh(xh + (r * h) @ Rh.T
                                     + Rb[2 * H:3 * H])
                h = (1 - z) * hcand + z * h
            else:  # RNN
                act = (acts[d] if acts else "Tanh")
                fact = _np.tanh if act == "Tanh" else (
                    lambda v: _np.maximum(v, 0.0))
                h = fact(gx + h @ R[d].T + Rb)
            Y[t, d] = h
        Yh[d], Yc[d] = h, c
    outs = (jnp.asarray(Y.astype(_np.float32)),
            jnp.asarray(Yh.astype(_np.float32)))
    if op == "LSTM":
        outs = outs + (jnp.asarray(Yc.astype(_np.float32)),)
    return outs


def _eval_node(op, ins, attrs):
    """ins: list of jnp arrays (None for absent optional inputs).
    Returns a tuple of outputs."""
    a = attrs
    if op == "Sum":                       # variadic elementwise sum
        out = ins[0]
        for x in ins[1:]:
            out = out + x
        return (out,)
    if op == "ReduceSum" and len(ins) > 1 and ins[1] is not None:
        # opset-13 form: axes arrive as an input tensor
        ax = tuple(int(v) for v in _np.asarray(ins[1]).tolist())
        return (jnp.sum(ins[0], axis=ax or None,
                        keepdims=bool(a.get("keepdims", 1))),)
    if op in _ELEM:
        return (_ELEM[op](ins[0], ins[1]),)
    if op in _UNARY:
        return (_UNARY[op](ins[0]),)
    if op in _REDUCE:
        return (_REDUCE[op](ins[0], a),)
    if op == "MatMul":
        return (jnp.matmul(ins[0], ins[1]),)
    if op == "Gemm":
        return (_gemm(ins[0], ins[1], ins[2] if len(ins) > 2 else None, a),)
    if op == "Conv":
        return (_conv(ins[0], ins[1],
                      ins[2] if len(ins) > 2 else None, a),)
    if op == "ConvTranspose":
        return (_conv_transpose(ins[0], ins[1],
                                ins[2] if len(ins) > 2 else None, a),)
    if op in ("MaxPool", "AveragePool"):
        kernel = a["kernel_shape"]
        nd = len(kernel)
        return (_pool(ins[0], kernel, a.get("strides", [1] * nd),
                      a.get("pads", [0] * 2 * nd),
                      "max" if op == "MaxPool" else "avg",
                      bool(a.get("count_include_pad", 0))),)
    if op == "GlobalAveragePool":
        return (jnp.mean(ins[0], axis=tuple(range(2, ins[0].ndim)),
                         keepdims=True),)
    if op == "GlobalMaxPool":
        return (jnp.max(ins[0], axis=tuple(range(2, ins[0].ndim)),
                        keepdims=True),)
    if op == "BatchNormalization":
        x, scale, b, mean, var = ins[:5]
        eps = float(a.get("epsilon", 1e-5))
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mean.reshape(shape))
                / jnp.sqrt(var.reshape(shape) + eps)
                * scale.reshape(shape) + b.reshape(shape),)
    if op == "InstanceNormalization":
        x, scale, b = ins
        eps = float(a.get("epsilon", 1e-5))
        ax = tuple(range(2, x.ndim))
        mu = x.mean(ax, keepdims=True)
        var = x.var(ax, keepdims=True)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mu) / jnp.sqrt(var + eps) * scale.reshape(shape)
                + b.reshape(shape),)
    if op == "LRN":
        return (_lrn(ins[0], a),)
    if op == "Softmax":
        return (_softmax_block(ins[0], int(a.get("axis", 1))),)
    if op == "LogSoftmax":
        return (jnp.log(_softmax_block(ins[0], int(a.get("axis", 1)))
                        + 1e-38),)
    if op == "LeakyRelu":
        al = float(a.get("alpha", 0.01))
        return (jnp.where(ins[0] > 0, ins[0], al * ins[0]),)
    if op == "Elu":
        al = float(a.get("alpha", 1.0))
        return (jnp.where(ins[0] > 0, ins[0],
                          al * (jnp.exp(ins[0]) - 1.0)),)
    if op == "PRelu":
        return (jnp.where(ins[0] > 0, ins[0], ins[1] * ins[0]),)
    if op == "HardSigmoid":
        al = float(a.get("alpha", 0.2))
        be = float(a.get("beta", 0.5))
        return (jnp.clip(al * ins[0] + be, 0.0, 1.0),)
    if op == "Clip":
        lo = ins[1] if len(ins) > 1 and ins[1] is not None else -jnp.inf
        hi = ins[2] if len(ins) > 2 and ins[2] is not None else jnp.inf
        return (jnp.clip(ins[0], lo, hi),)
    if op == "Where":
        return (jnp.where(ins[0].astype(bool), ins[1], ins[2]),)
    if op == "Equal":
        return (jnp.equal(ins[0], ins[1]),)
    if op == "Greater":
        return (jnp.greater(ins[0], ins[1]),)
    if op == "Less":
        return (jnp.less(ins[0], ins[1]),)
    if op == "Mod":
        if int(a.get("fmod", 0)):
            return (jnp.fmod(ins[0], ins[1]),)
        return (jnp.mod(ins[0], ins[1]),)
    if op == "Cast":
        return (ins[0].astype(P.DTYPE_REV[int(a["to"])]),)
    if op == "Concat":
        return (jnp.concatenate([i for i in ins], axis=int(a["axis"])),)
    if op == "Split":
        ax = int(a.get("axis", 0))
        sizes = a.get("split")
        if sizes is None and len(ins) > 1 and ins[1] is not None:
            sizes = _np.asarray(ins[1]).tolist()   # opset-13 input form
        if sizes:
            cuts = _np.cumsum(sizes)[:-1].tolist()
            return tuple(jnp.split(ins[0], cuts, axis=ax))
        return tuple(jnp.split(ins[0], 2, axis=ax))
    if op == "Transpose":
        perm = a.get("perm")
        return (jnp.transpose(ins[0], perm),)
    if op == "Reshape":
        return (_reshape(ins[0], ins[1]),)
    if op == "Flatten":
        ax = int(a.get("axis", 1))
        return (ins[0].reshape((int(_np.prod(ins[0].shape[:ax]) or 1),
                                -1)),)
    if op == "Squeeze":
        ax = _axes(a)
        if ax is None and len(ins) > 1 and ins[1] is not None:
            ax = tuple(int(v) for v in _np.asarray(ins[1]).tolist())
        return (jnp.squeeze(ins[0], axis=ax),)
    if op == "Unsqueeze":
        ax = _axes(a)
        if ax is None and len(ins) > 1 and ins[1] is not None:
            ax = tuple(int(v) for v in _np.asarray(ins[1]).tolist())
        out = ins[0]
        for x in sorted(ax):
            out = jnp.expand_dims(out, x)
        return (out,)
    if op == "Expand":
        shape = [int(v) for v in _np.asarray(ins[1]).tolist()]
        return (jnp.broadcast_to(
            ins[0], _np.broadcast_shapes(tuple(ins[0].shape),
                                         tuple(shape))),)
    if op == "Tile":
        return (jnp.tile(ins[0],
                         [int(v) for v in _np.asarray(ins[1]).tolist()]),)
    if op == "Shape":
        return (jnp.asarray(ins[0].shape, jnp.int64),)
    if op == "Slice":
        return (_slice_op(ins[0], ins[1], ins[2],
                          ins[3] if len(ins) > 3 else None,
                          ins[4] if len(ins) > 4 else None),)
    if op == "Gather":
        ax = int(a.get("axis", 0))
        return (jnp.take(ins[0], ins[1].astype(jnp.int32), axis=ax),)
    if op == "GatherElements":
        ax = int(a.get("axis", 0))
        return (jnp.take_along_axis(ins[0], ins[1].astype(jnp.int32),
                                    axis=ax),)
    if op == "OneHot":
        return (_onehot(ins[0], ins[1], ins[2], a),)
    if op == "TopK":
        return _topk(ins[0], _np.asarray(ins[1]).reshape(()), a)
    if op == "ArgMax":
        ax = int(a.get("axis", 0))
        keep = bool(a.get("keepdims", 1))
        out = jnp.argmax(ins[0], axis=ax)
        return (jnp.expand_dims(out, ax).astype(jnp.int64) if keep
                else out.astype(jnp.int64),)
    if op == "ArgMin":
        ax = int(a.get("axis", 0))
        keep = bool(a.get("keepdims", 1))
        out = jnp.argmin(ins[0], axis=ax)
        return (jnp.expand_dims(out, ax).astype(jnp.int64) if keep
                else out.astype(jnp.int64),)
    if op == "Pad":
        return (_pad_op(ins[0], a,
                        pads=_np.asarray(ins[1]).tolist()
                        if len(ins) > 1 else None,
                        value=ins[2] if len(ins) > 2 else None),)
    if op == "SpaceToDepth":
        return (_depth_space(ins[0], a, True),)
    if op == "DepthToSpace":
        return (_depth_space(ins[0], a, False),)
    if op == "Dropout":
        return (ins[0],)
    if op == "Constant":
        t = a["value"]
        return (jnp.asarray(t["array"]),)
    if op == "ScatterElements":
        data, indices, updates = ins[0], ins[1], ins[2]
        axis = int(a.get("axis", 0))
        idx = jnp.asarray(indices).astype(jnp.int32)
        axis = axis % data.ndim
        grids = jnp.indices(idx.shape)
        full_idx = tuple(grids[i] if i != axis else idx
                         for i in range(data.ndim))
        return (jnp.asarray(data).at[full_idx].set(jnp.asarray(updates)),)
    if op == "ConstantOfShape":
        shape = [int(v) for v in _np.asarray(ins[0]).tolist()]
        t = a.get("value")
        if t is None:
            return (jnp.zeros(shape, jnp.float32),)
        fill = _np.asarray(t["array"]).reshape(())
        return (jnp.full(shape, fill, fill.dtype),)
    if op == "MaxRoiPool":
        return (_max_roi_pool(ins[0], ins[1], a),)
    if op == "Resize":
        sizes = ins[3] if len(ins) > 3 and ins[3] is not None else None
        return (_resize(ins[0], sizes, a),)
    if op in ("LSTM", "GRU", "RNN"):
        return _rnn_eval(op, ins, a)
    if op == "RandomNormal":
        shape = [int(v) for v in a["shape"]]
        dt = P.DTYPE_REV[int(a.get("dtype", 1))]
        out = _np.random.normal(float(a.get("mean", 0.0)),
                                float(a.get("scale", 1.0)), shape)
        return (jnp.asarray(out.astype(dt)),)
    if op == "RandomUniform":
        shape = [int(v) for v in a["shape"]]
        dt = P.DTYPE_REV[int(a.get("dtype", 1))]
        out = _np.random.uniform(float(a.get("low", 0.0)),
                                 float(a.get("high", 1.0)), shape)
        return (jnp.asarray(out.astype(dt)),)
    if op == "Multinomial":
        logits = _np.asarray(ins[0], _np.float64)
        n = int(a.get("sample_size", 1))
        p = _np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        out = _np.stack([_np.random.choice(p.shape[-1], size=n, p=row)
                         for row in p.reshape(-1, p.shape[-1])])
        dt = P.DTYPE_REV[int(a.get("dtype", 6))]
        return (jnp.asarray(
            out.reshape(logits.shape[:-1] + (n,)).astype(dt)),)
    if op == "QuantizeLinear":
        scale, zp = ins[1], ins[2]
        info = _np.iinfo(_np.asarray(zp).dtype)
        q = jnp.round(ins[0] / scale) + jnp.asarray(zp, jnp.float32)
        return (jnp.clip(q, info.min, info.max).astype(
            _np.asarray(zp).dtype),)
    if op == "DequantizeLinear":
        scale, zp = ins[1], ins[2]
        return ((ins[0].astype(jnp.float32)
                 - jnp.asarray(zp, jnp.float32)) * scale,)
    raise NotImplementedError(f"onnx_eval: unsupported op {op!r}")


def evaluate(graph, feeds):
    """Evaluate a parsed GraphProto dict with `feeds` (name -> array).
    Returns {output_name: np.ndarray}."""
    env = {}
    for t in graph["initializers"]:
        env[t["name"]] = jnp.asarray(t["array"])
    for vi in graph["inputs"]:
        if vi["name"] in feeds:
            env[vi["name"]] = jnp.asarray(feeds[vi["name"]])
    missing = [vi["name"] for vi in graph["inputs"]
               if vi["name"] not in env]
    if missing:
        raise ValueError(f"missing feeds for {missing}")
    for n in graph["nodes"]:
        ins = [env[i] if i else None for i in n["input"]]
        outs = _eval_node(n["op_type"], ins, n["attrs"])
        for name, val in zip(n["output"], outs):
            if name:
                env[name] = val
    return {o["name"]: _np.asarray(env[o["name"]])
            for o in graph["outputs"]}


def run_model(path_or_bytes, feeds):
    """Parse + evaluate an ONNX file (the onnxruntime stand-in)."""
    buf = path_or_bytes
    if isinstance(buf, str):
        with open(buf, "rb") as f:
            buf = f.read()
    m = P.check_model(buf)
    return evaluate(m["graph"], feeds)
