"""mx.onnx — ONNX export (reference: python/mxnet/onnx/)."""
from . import _proto  # noqa: F401
from .mx2onnx import export_model  # noqa: F401
