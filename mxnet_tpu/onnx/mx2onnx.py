"""mx → ONNX exporter (reference: python/mxnet/onnx/mx2onnx/, 8,149 LoC of
op translation tables over the symbol graph).

TPU re-design notes: the exporter walks the mx.symbol DAG (the deployment
artifact, same as the reference), infers every intermediate shape with
jax.eval_shape (replacing the reference's mxnet shape inference), and emits
opset-11 ONNX via the dependency-free wire encoder in _proto.py. Training
graphs are exported in inference form (Dropout → ratio-annotated node,
BatchNorm → inference BN), matching reference behavior.
"""
from __future__ import annotations

import jax
import numpy as _np

from ..symbol.symbol import _OP_TABLE, Symbol, _op_fn
from . import _proto as P

__all__ = ["export_model"]


class _Ctx:
    def __init__(self, opset=11):
        self.nodes = []        # encoded NodeProtos
        self.initializers = []
        self._counter = 0
        self.structs = {}      # id(sym-node) -> ShapeDtypeStruct
        self.opset = opset     # 11 (default) or 13 — see export_model
        self.param_arrays = {}  # full static param values (RNN packing)

    def dtype_of(self, sym_node, default=_np.float32):
        st = self.structs.get(id(sym_node))
        if st is None:
            return _np.dtype(default)
        if isinstance(st, (tuple, list)):
            st = st[sym_node._out_index or 0]
        return _np.dtype(st.dtype)

    def fresh(self, base):
        self._counter += 1
        return f"{base}__{self._counter}"

    def add_node(self, op_type, inputs, outputs, name="", attrs=None):
        self.nodes.append(P.node(op_type, inputs, outputs, name, attrs))

    def add_init(self, name, arr):
        self.initializers.append(P.tensor(name, _np.asarray(arr)))
        return name

    def const_i64(self, base, vals):
        return self.add_init(self.fresh(base),
                             _np.asarray(vals, _np.int64))

    # opset-sensitive emissions: opset 13 moved `axes`/`split` from
    # attributes to inputs for Squeeze/Unsqueeze/ReduceSum/Split
    # (reference keeps twin tables _op_translations_opset12/13.py;
    # here one emission helper switches on ctx.opset)
    def squeeze(self, ins, outs, axes, name=""):
        if axes is None:
            self.add_node("Squeeze", ins, outs, name)
        elif self.opset >= 13:
            ax = self.const_i64((name or outs[0]) + "_axes", list(axes))
            self.add_node("Squeeze", [ins[0], ax], outs, name)
        else:
            self.add_node("Squeeze", ins, outs, name,
                          {"axes": list(axes)})

    def unsqueeze(self, ins, outs, axes, name=""):
        if self.opset >= 13:
            ax = self.const_i64((name or outs[0]) + "_axes", list(axes))
            self.add_node("Unsqueeze", [ins[0], ax], outs, name)
        else:
            self.add_node("Unsqueeze", ins, outs, name,
                          {"axes": list(axes)})

    def reduce_sum(self, ins, outs, axes, keepdims, name=""):
        attrs = {"keepdims": int(keepdims)}
        if axes is not None and self.opset >= 13:
            ax = self.const_i64((name or outs[0]) + "_axes", list(axes))
            self.add_node("ReduceSum", [ins[0], ax], outs, name, attrs)
        else:
            if axes is not None:
                attrs["axes"] = list(axes)
            self.add_node("ReduceSum", ins, outs, name, attrs)

    def split(self, ins, outs, axis, sizes, name=""):
        if self.opset >= 13:
            sp = self.const_i64((name or outs[0]) + "_split", list(sizes))
            self.add_node("Split", [ins[0], sp], outs, name,
                          {"axis": int(axis)})
        else:
            self.add_node("Split", [ins[0]], outs, name,
                          {"axis": int(axis), "split": list(sizes)})


# Each converter: fn(ctx, sym, in_names, out_names, in_shapes) -> None
_CONVERTERS = {}


def _conv(name):
    def deco(fn):
        _CONVERTERS[name] = fn
        return fn

    return deco


def _simple(onnx_op, **fixed):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        ctx.add_node(onnx_op, ins, outs, s.name, dict(fixed))

    return fn


for _mx, _onnx in [
    ("elemwise_add", "Add"), ("broadcast_add", "Add"),
    ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
    ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
    ("elemwise_div", "Div"), ("broadcast_div", "Div"),
    ("power", "Pow"), ("negative", "Neg"), ("exp", "Exp"), ("log", "Log"),
    ("sqrt", "Sqrt"), ("tanh", "Tanh"), ("abs", "Abs"),
    ("sigmoid", "Sigmoid"), ("relu", "Relu"),
    ("maximum", "Max"), ("minimum", "Min"),
]:
    _CONVERTERS[_mx] = _simple(_onnx)


@_conv("square")
def _square(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Mul", [ins[0], ins[0]], outs, s.name)


@_conv("where")
def _where(ctx, s, ins, outs, shapes):  # noqa: ARG001
    cond = ctx.fresh(s.name + "_cond")
    ctx.add_node("Cast", [ins[0]], [cond], attrs={"to": 9})  # bool
    ctx.add_node("Where", [cond, ins[1], ins[2]], outs, s.name)


@_conv("clip")
def _clip(ctx, s, ins, outs, shapes):  # noqa: ARG001
    lo = ctx.add_init(ctx.fresh(s.name + "_min"),
                      _np.float32(s.attr("a_min")))
    hi = ctx.add_init(ctx.fresh(s.name + "_max"),
                      _np.float32(s.attr("a_max")))
    ctx.add_node("Clip", [ins[0], lo, hi], outs, s.name)


def _reduce(onnx_op):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        keep = int(bool(s.attr("keepdims")))
        ax = s.attr("axis")
        if ax is not None:
            ax = [ax] if isinstance(ax, int) else list(ax)
        if onnx_op == "ReduceSum":   # axes moved to an input in opset 13
            ctx.reduce_sum(ins, outs, ax, keep, s.name)
            return
        attrs = {"keepdims": keep}
        if ax is not None:
            attrs["axes"] = ax
        ctx.add_node(onnx_op, ins, outs, s.name, attrs)

    return fn


_CONVERTERS["sum"] = _reduce("ReduceSum")
_CONVERTERS["mean"] = _reduce("ReduceMean")
_CONVERTERS["max"] = _reduce("ReduceMax")
_CONVERTERS["min"] = _reduce("ReduceMin")
_CONVERTERS["prod"] = _reduce("ReduceProd")


@_conv("norm")
def _norm(ctx, s, ins, outs, shapes):  # noqa: ARG001
    order = s.attr("ord")
    order = 2 if order is None else order
    if order == 2:
        op = "ReduceL2"
    elif order == 1:
        op = "ReduceL1"
    else:
        raise NotImplementedError(
            f"norm ord={order!r} not exportable (ReduceL1/L2 only)")
    _reduce(op)(ctx, s, ins, outs, shapes)


def _arg(onnx_op):
    def fn(ctx, s, ins, outs, shapes):
        ax = s.attr("axis")
        raw = ctx.fresh(s.name + "_i64")
        data = ins[0]
        if ax is None:
            # jnp.argmax(axis=None) reduces the flattened array to a scalar
            flat = ctx.fresh(s.name + "_flat")
            shp = ctx.const_i64(s.name + "_m1", [-1])
            ctx.add_node("Reshape", [ins[0], shp], [flat])
            data, ax = flat, 0
        ctx.add_node(onnx_op, [data], [raw], s.name,
                     {"axis": int(ax), "keepdims": 0})
        ctx.add_node("Cast", [raw], outs, attrs={"to": 1})  # float32 parity

    return fn


_CONVERTERS["argmax"] = _arg("ArgMax")
_CONVERTERS["argmin"] = _arg("ArgMin")


@_conv("transpose")
def _transpose(ctx, s, ins, outs, shapes):
    axes = s.attr("axes")
    if axes is None:
        axes = list(range(len(shapes[0])))[::-1]
    ctx.add_node("Transpose", ins, outs, s.name, {"perm": list(axes)})


@_conv("swapaxes")
def _swapaxes(ctx, s, ins, outs, shapes):
    rank = len(shapes[0])
    perm = list(range(rank))
    d1, d2 = s.attr("dim1") % rank, s.attr("dim2") % rank
    perm[d1], perm[d2] = perm[d2], perm[d1]
    ctx.add_node("Transpose", ins, outs, s.name, {"perm": perm})


@_conv("reshape")
def _reshape(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.const_i64(s.name + "_shape", list(s.attr("shape")))
    ctx.add_node("Reshape", [ins[0], shp], outs, s.name)


@_conv("Flatten")
def _flatten(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Flatten", ins, outs, s.name, {"axis": 1})


@_conv("expand_dims")
def _expand_dims(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.unsqueeze(ins, outs, [s.attr("axis")], s.name)


@_conv("squeeze")
def _squeeze(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ax = s.attr("axis")
    if ax is not None:
        ax = [ax] if isinstance(ax, int) else list(ax)
    ctx.squeeze(ins, outs, ax, s.name)


@_conv("broadcast_to")
def _broadcast_to(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.const_i64(s.name + "_shape", list(s.attr("shape")))
    ctx.add_node("Expand", [ins[0], shp], outs, s.name)


@_conv("zeros_like")
def _zeros_like(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.fresh(s.name + "_shape")
    ctx.add_node("Shape", ins, [shp])
    dt = ctx.dtype_of(s._inputs[0])  # emit in the source dtype
    ctx.add_node("ConstantOfShape", [shp], outs, s.name,
                 {"value": _np.zeros(1, dt)})


@_conv("ones_like")
def _ones_like(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.fresh(s.name + "_shape")
    ctx.add_node("Shape", ins, [shp])
    dt = ctx.dtype_of(s._inputs[0])
    ctx.add_node("ConstantOfShape", [shp], outs, s.name,
                 {"value": _np.ones(1, dt)})


@_conv("slice")
def _slice(ctx, s, ins, outs, shapes):
    begin, end = list(s.attr("begin")), list(s.attr("end"))
    step = list(s.attr("step") or [1] * len(begin))
    step = [1 if st is None else st for st in step]
    INT_MIN = -(2 ** 31)
    b_res, e_res = [], []
    for i, (b, e) in enumerate(zip(begin, end)):
        if step[i] < 0:
            # python slice(None, None, -st) == start at last elem, run past 0;
            # ONNX needs an out-of-range sentinel for "include index 0"
            b_res.append(shapes[0][i] - 1 if b is None else b)
            e_res.append(INT_MIN if e is None else e)
        else:
            b_res.append(0 if b is None else b)
            e_res.append(shapes[0][i] if e is None else e)
    starts = ctx.const_i64(s.name + "_starts", b_res)
    ends = ctx.const_i64(s.name + "_ends", e_res)
    axes = ctx.const_i64(s.name + "_axes", list(range(len(begin))))
    slice_ins = [ins[0], starts, ends, axes]
    if any(st != 1 for st in step):
        slice_ins.append(ctx.const_i64(s.name + "_steps", step))
    ctx.add_node("Slice", slice_ins, outs, s.name)


@_conv("slice_axis")
def _slice_axis(ctx, s, ins, outs, shapes):
    ax = s.attr("axis")
    begin = s.attr("begin") or 0
    end = s.attr("end")
    if end is None:
        end = shapes[0][ax]
    starts = ctx.const_i64(s.name + "_starts", [begin])
    ends = ctx.const_i64(s.name + "_ends", [end])
    axes = ctx.const_i64(s.name + "_axes", [ax])
    ctx.add_node("Slice", [ins[0], starts, ends, axes], outs, s.name)


@_conv("split")
def _split(ctx, s, ins, outs, shapes):
    ax = s.attr("axis") if s.attr("axis") is not None else 1
    n = len(outs)
    size = shapes[0][ax] // n
    ctx.split(ins, outs, ax, [size] * n, s.name)


@_conv("Concat")
def _concat(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Concat", ins, outs, s.name,
                 {"axis": s.attr("dim") if s.attr("dim") is not None else 1})


@_conv("stack")
def _stack(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ax = s.attr("axis") or 0
    unsq = []
    for i in ins:
        u = ctx.fresh(i + "_unsq")
        ctx.unsqueeze([i], [u], [ax])
        unsq.append(u)
    ctx.add_node("Concat", unsq, outs, s.name, {"axis": ax})


@_conv("dot")
def _dot(ctx, s, ins, outs, shapes):
    if len(shapes[0]) >= 2 and len(shapes[1]) >= 3:
        raise NotImplementedError(
            "dot with rank>=3 rhs follows np.dot outer-stacking semantics, "
            "which ONNX MatMul (batched) does not match; use batch_dot")
    ctx.add_node("MatMul", ins, outs, s.name)


@_conv("batch_dot")
def _batch_dot(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("MatMul", ins, outs, s.name)


@_conv("FullyConnected")
def _fc(ctx, s, ins, outs, shapes):
    data = ins[0]
    rank = len(shapes[0])
    if rank != 2 and s.attr("flatten") in (None, True):
        flat = ctx.fresh(s.name + "_flat")
        ctx.add_node("Flatten", [ins[0]], [flat], attrs={"axis": 1})
        data, rank = flat, 2
    if rank != 2:
        # flatten=False on rank>2: batched projection — Gemm requires 2-D,
        # so emit MatMul(x, W^T) (+ Add bias)
        wt = ctx.fresh(s.name + "_wT")
        ctx.add_node("Transpose", [ins[1]], [wt], attrs={"perm": [1, 0]})
        if len(ins) > 2:
            mm = ctx.fresh(s.name + "_mm")
            ctx.add_node("MatMul", [data, wt], [mm])
            ctx.add_node("Add", [mm, ins[2]], outs, s.name)
        else:
            ctx.add_node("MatMul", [data, wt], outs, s.name)
        return
    if len(ins) > 2:
        ctx.add_node("Gemm", [data, ins[1], ins[2]], outs, s.name,
                     {"transB": 1})
    else:
        ctx.add_node("Gemm", [data, ins[1]], outs, s.name, {"transB": 1})


@_conv("Convolution")
def _convolution(ctx, s, ins, outs, shapes):
    kshape = list(shapes[1][2:])  # weight (O, I/g, kh, kw)
    nd = len(kshape)
    stride = list(s.attr("stride") or (1,) * nd)
    dilate = list(s.attr("dilate") or (1,) * nd)
    pad = list(s.attr("pad") or (0,) * nd)
    ctx.add_node("Conv", ins, outs, s.name, {
        "kernel_shape": kshape, "strides": stride, "dilations": dilate,
        "pads": pad + pad, "group": int(s.attr("num_group") or 1)})


@_conv("Deconvolution")
def _deconvolution(ctx, s, ins, outs, shapes):
    kshape = list(shapes[1][2:])
    nd = len(kshape)
    stride = list(s.attr("stride") or (1,) * nd)
    pad = list(s.attr("pad") or (0,) * nd)
    ctx.add_node("ConvTranspose", ins, outs, s.name, {
        "kernel_shape": kshape, "strides": stride, "pads": pad + pad})


@_conv("Activation")
def _activation(ctx, s, ins, outs, shapes):  # noqa: ARG001
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = s.attr("act_type") or "relu"
    ctx.add_node(table[act], ins, outs, s.name)


@_conv("LeakyReLU")
def _leaky(ctx, s, ins, outs, shapes):  # noqa: ARG001
    act = s.attr("act_type") or "leaky"
    slope = float(s.attr("slope") if s.attr("slope") is not None else 0.25)
    if act == "leaky":
        ctx.add_node("LeakyRelu", ins, outs, s.name, {"alpha": slope})
    elif act == "elu":
        ctx.add_node("Elu", ins, outs, s.name, {"alpha": slope})
    elif act == "prelu":
        ctx.add_node("PRelu", ins, outs, s.name)
    elif act == "gelu":
        # opset-11 decomposition: x * 0.5 * (1 + erf(x / sqrt(2)))
        invsqrt2 = ctx.add_init(ctx.fresh(s.name + "_c"),
                                _np.float32(1 / _np.sqrt(2.0)))
        half = ctx.add_init(ctx.fresh(s.name + "_h"), _np.float32(0.5))
        one = ctx.add_init(ctx.fresh(s.name + "_1"), _np.float32(1.0))
        t1 = ctx.fresh(s.name + "_t1")
        ctx.add_node("Mul", [ins[0], invsqrt2], [t1])
        t2 = ctx.fresh(s.name + "_t2")
        ctx.add_node("Erf", [t1], [t2])
        t3 = ctx.fresh(s.name + "_t3")
        ctx.add_node("Add", [t2, one], [t3])
        t4 = ctx.fresh(s.name + "_t4")
        ctx.add_node("Mul", [ins[0], t3], [t4])
        ctx.add_node("Mul", [t4, half], outs, s.name)
    else:
        raise ValueError(f"LeakyReLU act_type {act!r} not exportable")


@_conv("Pooling")
def _pooling(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ptype = s.attr("pool_type") or "max"
    if s.attr("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        ctx.add_node(op, ins, outs, s.name)
        return
    kernel = list(s.attr("kernel") or (2, 2))
    nd = len(kernel)
    stride = list(s.attr("stride") or kernel)
    pad = list(s.attr("pad") or (0,) * nd)
    op = "MaxPool" if ptype == "max" else "AveragePool"
    attrs = {"kernel_shape": kernel, "strides": stride, "pads": pad + pad}
    if ptype != "max":
        # ops/nn.py:167 pooling defaults count_include_pad=True; honor an
        # explicit False from the symbol attrs
        cip = s.attr("count_include_pad")
        attrs["count_include_pad"] = 0 if cip in (False, 0, "False") else 1
    ctx.add_node(op, ins, outs, s.name, attrs)


@_conv("BatchNorm")
def _batchnorm(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("BatchNormalization", ins, outs, s.name,
                 {"epsilon": float(s.attr("eps") or 1e-5)})


@_conv("LayerNorm")
def _layernorm(ctx, s, ins, outs, shapes):
    """Opset-11 decomposition (LayerNormalization needs opset 17)."""
    ax = s.attr("axis")
    ax = -1 if ax is None else ax
    rank = len(shapes[0])
    ax = ax % rank
    eps = ctx.add_init(ctx.fresh(s.name + "_eps"),
                       _np.float32(s.attr("eps") or 1e-5))
    mean = ctx.fresh(s.name + "_mean")
    ctx.add_node("ReduceMean", [ins[0]], [mean],
                 attrs={"axes": [ax], "keepdims": 1})
    cent = ctx.fresh(s.name + "_cent")
    ctx.add_node("Sub", [ins[0], mean], [cent])
    sq = ctx.fresh(s.name + "_sq")
    ctx.add_node("Mul", [cent, cent], [sq])
    var = ctx.fresh(s.name + "_var")
    ctx.add_node("ReduceMean", [sq], [var], attrs={"axes": [ax],
                                                   "keepdims": 1})
    veps = ctx.fresh(s.name + "_veps")
    ctx.add_node("Add", [var, eps], [veps])
    std = ctx.fresh(s.name + "_std")
    ctx.add_node("Sqrt", [veps], [std])
    normed = ctx.fresh(s.name + "_normed")
    ctx.add_node("Div", [cent, std], [normed])
    scaled = ctx.fresh(s.name + "_scaled")
    ctx.add_node("Mul", [normed, ins[1]], [scaled])
    ctx.add_node("Add", [scaled, ins[2]], outs, s.name)


@_conv("Dropout")
def _dropout(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Dropout", ins, outs, s.name,
                 {"ratio": float(s.attr("p") if s.attr("p") is not None
                                 else 0.5)})


def _softmax_like(onnx_op):
    def fn(ctx, s, ins, outs, shapes):
        """Opset-11 Softmax flattens ALL trailing dims from `axis`; that
        only matches per-axis softmax when the axis is last. For any other
        axis, transpose it to last, apply, transpose back."""
        rank = len(shapes[0])
        ax = s.attr("axis")
        ax = (rank - 1) if ax is None else int(ax) % rank
        if ax == rank - 1:
            ctx.add_node(onnx_op, ins, outs, s.name, {"axis": rank - 1})
            return
        perm = [i for i in range(rank) if i != ax] + [ax]
        inv = [perm.index(i) for i in range(rank)]
        t1 = ctx.fresh(s.name + "_t")
        ctx.add_node("Transpose", ins, [t1], attrs={"perm": perm})
        sm = ctx.fresh(s.name + "_sm")
        ctx.add_node(onnx_op, [t1], [sm], attrs={"axis": rank - 1})
        ctx.add_node("Transpose", [sm], outs, s.name, {"perm": inv})

    return fn


_CONVERTERS["softmax"] = _softmax_like("Softmax")
_CONVERTERS["log_softmax"] = _softmax_like("LogSoftmax")


@_conv("Embedding")
def _embedding(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[0]], [idx], attrs={"to": 7})  # int64
    ctx.add_node("Gather", [ins[1], idx], outs, s.name, {"axis": 0})


@_conv("take")
def _take(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[1]], [idx], attrs={"to": 7})
    ctx.add_node("Gather", [ins[0], idx], outs, s.name,
                 {"axis": int(s.attr("axis") or 0)})


@_conv("one_hot")
def _one_hot(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[0]], [idx], attrs={"to": 7})
    depth = ctx.const_i64(s.name + "_depth", [s.attr("depth")])
    values = ctx.add_init(ctx.fresh(s.name + "_vals"),
                          _np.asarray([0.0, 1.0], _np.float32))
    ctx.add_node("OneHot", [idx, depth, values], outs, s.name, {"axis": -1})


# --- extended-table converters (symbol/op_extended.py vocabulary) ----------

for _mx, _onnx in [
    ("sin", "Sin"), ("cos", "Cos"), ("tan", "Tan"), ("arcsin", "Asin"),
    ("arccos", "Acos"), ("arctan", "Atan"), ("sinh", "Sinh"),
    ("cosh", "Cosh"), ("arcsinh", "Asinh"), ("arccosh", "Acosh"),
    ("arctanh", "Atanh"), ("floor", "Floor"), ("ceil", "Ceil"),
    ("round", "Round"), ("rint", "Round"), ("sign", "Sign"),
    ("erf", "Erf"), ("reciprocal", "Reciprocal"), ("softsign", "Softsign"),
    ("softplus", "Softplus"), ("identity", "Identity"),
    ("BlockGrad", "Identity"), ("make_loss", "Identity"),
    ("shape_array", "Shape"), ("gather_nd", "GatherND"),
]:
    _CONVERTERS[_mx] = _simple(_onnx)
_CONVERTERS["space_to_depth"] = lambda ctx, s, ins, outs, shapes: \
    ctx.add_node("SpaceToDepth", ins, outs, s.name,
                 {"blocksize": int(s.attr("block_size"))})
_CONVERTERS["depth_to_space"] = lambda ctx, s, ins, outs, shapes: \
    ctx.add_node("DepthToSpace", ins, outs, s.name,
                 {"blocksize": int(s.attr("block_size"))})


@_conv("rsqrt")
def _rsqrt(ctx, s, ins, outs, shapes):  # noqa: ARG001
    r = ctx.fresh(s.name + "_sqrt")
    ctx.add_node("Sqrt", ins, [r])
    ctx.add_node("Reciprocal", [r], outs, s.name)


@_conv("log1p")
def _log1p(ctx, s, ins, outs, shapes):  # noqa: ARG001
    one = ctx.add_init(ctx.fresh(s.name + "_one"), _np.float32(1.0))
    t = ctx.fresh(s.name + "_xp1")
    ctx.add_node("Add", [ins[0], one], [t])
    ctx.add_node("Log", [t], outs, s.name)


@_conv("expm1")
def _expm1(ctx, s, ins, outs, shapes):  # noqa: ARG001
    one = ctx.add_init(ctx.fresh(s.name + "_one"), _np.float32(1.0))
    t = ctx.fresh(s.name + "_expx")
    ctx.add_node("Exp", ins, [t])
    ctx.add_node("Sub", [t, one], outs, s.name)


def _log_base(base):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        ln = ctx.fresh(s.name + "_ln")
        ctx.add_node("Log", ins, [ln])
        k = ctx.add_init(ctx.fresh(s.name + "_k"),
                         _np.float32(1.0 / _np.log(base)))
        ctx.add_node("Mul", [ln, k], outs, s.name)

    return fn


_CONVERTERS["log2"] = _log_base(2.0)
_CONVERTERS["log10"] = _log_base(10.0)


@_conv("hard_sigmoid")
def _hard_sigmoid(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("HardSigmoid", ins, outs, s.name,
                 {"alpha": float(s.attr("alpha") or 0.2),
                  "beta": float(s.attr("beta") or 0.5)})


def _compare(onnx_op, negate=False):
    """mx comparisons return float 0/1; ONNX returns bool → Cast back."""
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        b = ctx.fresh(s.name + "_bool")
        ctx.add_node(onnx_op, ins, [b])
        if negate:
            nb = ctx.fresh(s.name + "_not")
            ctx.add_node("Not", [b], [nb])
            b = nb
        ctx.add_node("Cast", [b], outs, s.name, {"to": 1})

    return fn


_CONVERTERS["broadcast_equal"] = _compare("Equal")
_CONVERTERS["broadcast_not_equal"] = _compare("Equal", negate=True)
_CONVERTERS["broadcast_greater"] = _compare("Greater")
_CONVERTERS["broadcast_greater_equal"] = _compare("Less", negate=True)
_CONVERTERS["broadcast_lesser"] = _compare("Less")
_CONVERTERS["broadcast_lesser_equal"] = _compare("Greater", negate=True)


def _logical(onnx_op):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        bs = []
        for i, x in enumerate(ins):
            b = ctx.fresh(f"{s.name}_b{i}")
            ctx.add_node("Cast", [x], [b], attrs={"to": 9})
            bs.append(b)
        r = ctx.fresh(s.name + "_r")
        ctx.add_node(onnx_op, bs, [r])
        ctx.add_node("Cast", [r], outs, s.name, {"to": 1})

    return fn


_CONVERTERS["broadcast_logical_and"] = _logical("And")
_CONVERTERS["broadcast_logical_or"] = _logical("Or")
_CONVERTERS["broadcast_logical_xor"] = _logical("Xor")
_CONVERTERS["logical_not"] = _logical("Not")
_CONVERTERS["broadcast_maximum"] = _simple("Max")
_CONVERTERS["broadcast_minimum"] = _simple("Min")
_CONVERTERS["broadcast_power"] = _simple("Pow")


@_conv("mod")
def _mod(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # runtime is jnp.mod (floor modulo, sign follows divisor); ONNX Mod
    # with fmod=1 is C fmod — compose x - floor(x/y)*y to match
    q = ctx.fresh(s.name + "_q")
    ctx.add_node("Div", ins, [q])
    fq = ctx.fresh(s.name + "_fq")
    ctx.add_node("Floor", [q], [fq])
    prod = ctx.fresh(s.name + "_p")
    ctx.add_node("Mul", [fq, ins[1]], [prod])
    ctx.add_node("Sub", [ins[0], prod], outs, s.name)


_CONVERTERS["broadcast_mod"] = _mod


@_conv("broadcast_hypot")
def _hypot(ctx, s, ins, outs, shapes):  # noqa: ARG001
    sq = []
    for i, x in enumerate(ins):
        t = ctx.fresh(f"{s.name}_sq{i}")
        ctx.add_node("Mul", [x, x], [t])
        sq.append(t)
    ssum = ctx.fresh(s.name + "_ss")
    ctx.add_node("Add", sq, [ssum])
    ctx.add_node("Sqrt", [ssum], outs, s.name)


@_conv("Cast")
def _cast(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Cast", ins, outs, s.name,
                 {"to": P.DTYPE[str(_np.dtype(s.attr("dtype")))]})


@_conv("tile")
def _tile(ctx, s, ins, outs, shapes):  # noqa: ARG001
    reps = ctx.const_i64(s.name + "_reps", list(s.attr("reps")))
    ctx.add_node("Tile", [ins[0], reps], outs, s.name)


@_conv("pad")
def _pad(ctx, s, ins, outs, shapes):  # noqa: ARG001
    pw = list(s.attr("pad_width"))
    # mx interleaved (before0, after0, before1, ...) → onnx all-befores
    # then all-afters
    befores = pw[0::2]
    afters = pw[1::2]
    pads = ctx.const_i64(s.name + "_pads", befores + afters)
    mode = s.attr("mode") or "constant"
    cv = ctx.add_init(ctx.fresh(s.name + "_cv"),
                      _np.float32(s.attr("constant_value") or 0.0))
    ctx.add_node("Pad", [ins[0], pads, cv], outs, s.name,
                 {"mode": {"constant": "constant", "reflect": "reflect",
                           "edge": "edge"}[mode]})


_CONVERTERS["Pad"] = _pad


@_conv("topk")
def _topk(ctx, s, ins, outs, shapes):  # noqa: ARG001
    k = ctx.const_i64(s.name + "_k", [int(s.attr("k") or 1)])
    ax = int(s.attr("axis") if s.attr("axis") is not None else -1)
    vals = ctx.fresh(s.name + "_vals")
    idx = ctx.fresh(s.name + "_idx")
    ret = s.attr("ret_typ") or "indices"
    largest = 0 if s.attr("is_ascend") in (True, 1) else 1
    ctx.add_node("TopK", [ins[0], k], [vals, idx], s.name,
                 {"axis": ax, "largest": largest, "sorted": 1})
    if ret == "both":
        ctx.add_node("Identity", [vals], [outs[0]])
        ctx.add_node("Cast", [idx], [outs[1]], attrs={"to": 1})
    elif ret == "value":
        ctx.add_node("Identity", [vals], outs)
    elif ret == "mask":
        # input-shaped 0/1 mask (in the input's dtype, matching the
        # native op): ScatterElements of ones at the topk indices along
        # `axis` into zeros shaped like the input
        dt = ctx.dtype_of(s._inputs[0])
        zeros = ctx.fresh(s.name + "_zeros")
        shape_of = ctx.fresh(s.name + "_shapeof")
        ctx.add_node("Shape", [ins[0]], [shape_of])
        ctx.add_node("ConstantOfShape", [shape_of], [zeros], s.name + "_z",
                     {"value": _np.zeros(1, dt)})
        ones = ctx.fresh(s.name + "_ones")
        idx_shape = ctx.fresh(s.name + "_idxshape")
        ctx.add_node("Shape", [idx], [idx_shape])
        ctx.add_node("ConstantOfShape", [idx_shape], [ones], s.name + "_o",
                     {"value": _np.ones(1, dt)})
        ctx.add_node("ScatterElements", [zeros, idx, ones], outs,
                     s.name + "_scatter", {"axis": ax})
    else:
        ctx.add_node("Cast", [idx], outs, attrs={"to": 1})


@_conv("sort")
def _sort(ctx, s, ins, outs, shapes):
    ax = int(s.attr("axis") if s.attr("axis") is not None else -1)
    dim = shapes[0][ax]
    k = ctx.const_i64(s.name + "_k", [dim])
    idx = ctx.fresh(s.name + "_idx")
    ascend = s.attr("is_ascend") not in (False, 0)  # sort defaults ascending
    ctx.add_node("TopK", [ins[0], k], [outs[0], idx], s.name,
                 {"axis": ax, "largest": 0 if ascend else 1, "sorted": 1})


@_conv("argsort")
def _argsort(ctx, s, ins, outs, shapes):
    ax = int(s.attr("axis") if s.attr("axis") is not None else -1)
    dim = shapes[0][ax]
    k = ctx.const_i64(s.name + "_k", [dim])
    vals = ctx.fresh(s.name + "_vals")
    idx = ctx.fresh(s.name + "_idx")
    ascend = s.attr("is_ascend") not in (False, 0)  # argsort defaults ascend
    ctx.add_node("TopK", [ins[0], k], [vals, idx], s.name,
                 {"axis": ax, "largest": 0 if ascend else 1, "sorted": 1})
    ctx.add_node("Cast", [idx], outs, attrs={"to": 1})


@_conv("pick")
def _pick(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # opset-11 forms: Unsqueeze/Squeeze carry axes as attributes
    ax = int(s.attr("axis") if s.attr("axis") is not None else -1)
    idx64 = ctx.fresh(s.name + "_idx64")
    ctx.add_node("Cast", [ins[1]], [idx64], attrs={"to": 7})
    idxu = ctx.fresh(s.name + "_idxu")
    ctx.add_node("Unsqueeze", [idx64], [idxu], attrs={"axes": [ax]})
    g = ctx.fresh(s.name + "_g")
    ctx.add_node("GatherElements", [ins[0], idxu], [g], attrs={"axis": ax})
    if s.attr("keepdims"):
        ctx.add_node("Identity", [g], outs, s.name)
    else:
        ctx.squeeze([g], outs, [ax], s.name)


@_conv("batch_take")
def _batch_take(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx64 = ctx.fresh(s.name + "_idx64")
    ctx.add_node("Cast", [ins[1]], [idx64], attrs={"to": 7})
    idxu = ctx.fresh(s.name + "_idxu")
    ctx.unsqueeze([idx64], [idxu], [1])
    g = ctx.fresh(s.name + "_g")
    ctx.add_node("GatherElements", [ins[0], idxu], [g], attrs={"axis": 1})
    ctx.squeeze([g], outs, [1], s.name)


@_conv("flip")
def _flip(ctx, s, ins, outs, shapes):
    ax = s.attr("axis")
    if ax is None:  # runtime jnp.flip(x, None) flips every axis
        axes = list(range(len(shapes[0])))
    else:
        axes = [ax] if isinstance(ax, int) else list(ax)
    starts = ctx.const_i64(s.name + "_st", [-1] * len(axes))
    INT_MIN = -(2 ** 31)
    ends = ctx.const_i64(s.name + "_en", [INT_MIN] * len(axes))
    axs = ctx.const_i64(s.name + "_ax", axes)
    steps = ctx.const_i64(s.name + "_sp", [-1] * len(axes))
    ctx.add_node("Slice", [ins[0], starts, ends, axs, steps], outs, s.name)


_CONVERTERS["reverse"] = _flip


@_conv("logsumexp")
def _logsumexp(ctx, s, ins, outs, shapes):  # noqa: ARG001
    attrs = {"keepdims": int(bool(s.attr("keepdims")))}
    ax = s.attr("axis")
    if ax is not None:
        attrs["axes"] = [ax] if isinstance(ax, int) else list(ax)
    ctx.add_node("ReduceLogSumExp", ins, outs, s.name, attrs)


@_conv("broadcast_axis")
def _broadcast_axis(ctx, s, ins, outs, shapes):
    axes = s.attr("axis")
    sizes = s.attr("size")
    if isinstance(axes, int):
        axes, sizes = [axes], [sizes]
    target = list(shapes[0])
    for ax, sz in zip(axes, sizes):
        target[ax] = sz
    shp = ctx.const_i64(s.name + "_shape", target)
    ctx.add_node("Expand", [ins[0], shp], outs, s.name)


@_conv("broadcast_like")
def _broadcast_like(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.fresh(s.name + "_shape")
    ctx.add_node("Shape", [ins[1]], [shp])
    ctx.add_node("Expand", [ins[0], shp], outs, s.name)


@_conv("GELU")
def _gelu(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # x * 0.5 * (1 + erf(x / sqrt(2)))
    inv = ctx.add_init(ctx.fresh(s.name + "_is2"),
                       _np.float32(1 / _np.sqrt(2.0)))
    half = ctx.add_init(ctx.fresh(s.name + "_half"), _np.float32(0.5))
    one = ctx.add_init(ctx.fresh(s.name + "_one"), _np.float32(1.0))
    t = ctx.fresh(s.name + "_t")
    ctx.add_node("Mul", [ins[0], inv], [t])
    e = ctx.fresh(s.name + "_erf")
    ctx.add_node("Erf", [t], [e])
    e1 = ctx.fresh(s.name + "_e1")
    ctx.add_node("Add", [e, one], [e1])
    xh = ctx.fresh(s.name + "_xh")
    ctx.add_node("Mul", [ins[0], half], [xh])
    ctx.add_node("Mul", [xh, e1], outs, s.name)


@_conv("masked_softmax")
def _masked_softmax(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ax = int(s.attr("axis") if s.attr("axis") is not None else -1)
    b = ctx.fresh(s.name + "_mask")
    ctx.add_node("Cast", [ins[1]], [b], attrs={"to": 9})
    neg = ctx.add_init(ctx.fresh(s.name + "_neg"), _np.float32(-1e30))
    masked = ctx.fresh(s.name + "_m")
    ctx.add_node("Where", [b, ins[0], neg], [masked])
    temp = float(s.attr("temperature") or 1.0)
    if temp != 1.0:
        t = ctx.add_init(ctx.fresh(s.name + "_t"), _np.float32(temp))
        scaled = ctx.fresh(s.name + "_sc")
        ctx.add_node("Div", [masked, t], [scaled])
        masked = scaled
    sm = ctx.fresh(s.name + "_sm")
    ctx.add_node("Softmax", [masked], [sm], attrs={"axis": ax})
    zero = ctx.add_init(ctx.fresh(s.name + "_z"), _np.float32(0.0))
    ctx.add_node("Where", [b, sm, zero], outs, s.name)


@_conv("L2Normalization")
def _l2norm(ctx, s, ins, outs, shapes):
    # match runtime l2_normalization axes per mode (ops/nn.py:525):
    # instance = all non-batch, channel = 1, spatial = 2..rank-1
    mode = s.attr("mode") or "instance"
    rank = len(shapes[0])
    axes = {"instance": list(range(1, rank)), "channel": [1],
            "spatial": list(range(2, rank))}[mode]
    sq = ctx.fresh(s.name + "_sq")
    ctx.add_node("Mul", [ins[0], ins[0]], [sq])
    ss = ctx.fresh(s.name + "_ss")
    ctx.reduce_sum([sq], [ss], axes, keepdims=1)
    eps = ctx.add_init(ctx.fresh(s.name + "_eps"),
                       _np.float32(s.attr("eps") or 1e-10))
    se = ctx.fresh(s.name + "_se")
    ctx.add_node("Add", [ss, eps], [se])
    nrm = ctx.fresh(s.name + "_n")
    ctx.add_node("Sqrt", [se], [nrm])
    ctx.add_node("Div", [ins[0], nrm], outs, s.name)


@_conv("LRN")
def _lrn(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("LRN", ins, outs, s.name, {
        "alpha": float(s.attr("alpha") or 1e-4),
        "beta": float(s.attr("beta") or 0.75),
        "bias": float(s.attr("knorm") or 2.0),
        "size": int(s.attr("nsize") or 5)})


@_conv("InstanceNorm")
def _instance_norm(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("InstanceNormalization", ins, outs, s.name,
                 {"epsilon": float(s.attr("eps") or 1e-3)})


@_conv("arange_like")
def _arange_like(ctx, s, ins, outs, shapes):
    ax = s.attr("axis")
    shape = shapes[0]
    n = int(_np.prod(shape)) if ax is None else shape[int(ax)]
    start = float(s.attr("start") or 0.0)
    step = float(s.attr("step") or 1.0)
    repeat = int(s.attr("repeat") or 1)
    count = -(-n // repeat) if repeat > 1 else n
    vals = _np.arange(count, dtype=_np.float32) * step + start
    if repeat > 1:
        vals = _np.repeat(vals, repeat)[:n]
    if ax is None:
        vals = vals.reshape(shape)
    ctx.add_node("Constant", [], outs, s.name, {"value": vals})


@_conv("SliceChannel")
def _slice_channel(ctx, s, ins, outs, shapes):
    ax = int(s.attr("axis") if s.attr("axis") is not None else 1)
    n = int(s.attr("num_outputs"))
    size = shapes[0][ax] // n
    # opset-11 Split: sizes via the `split` attribute
    ctx.add_node("Split", [ins[0]], outs, s.name,
                 {"axis": ax, "split": [size] * n})


# --- shape inference over the symbol DAG -----------------------------------

def _infer_all_shapes(order, input_structs):
    """Per-node output ShapeDtypeStructs via jax.eval_shape, one op at a
    time (the reference ran nnvm InferShape over the whole graph)."""
    shapes = {}
    for s in order:
        if s._op is None:
            shapes[id(s)] = input_structs[s._name]
        elif s._op == "_const":
            v = _np.asarray(s._attrs["value"])
            shapes[id(s)] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        elif s._op == "_group":
            continue
        else:
            # slice multi-output producers at the consumer edge (the
            # stored struct stays the full tuple so graph outputs and
            # dtype_of can pick any slot)
            ins = []
            for i in s._inputs:
                st = shapes[id(i)]
                if isinstance(st, (tuple, list)) and \
                        i._out_index is not None:
                    st = st[i._out_index]
                ins.append(st)
            fn = _op_fn(s._op)
            out = jax.eval_shape(lambda *xs, _fn=fn, _a=s._attrs: _fn(
                list(xs), _a), *ins)
            shapes[id(s)] = out
    return shapes


# --- quantized op family -> ONNX QDQ form ---------------------------------
# The reference exported its INT8 graphs as QDQ (QuantizeLinear /
# DequantizeLinear pairs around float ops — the form onnxruntime fuses back
# into int8 kernels). Our quantized ops are deq -> float op -> requantize
# with symmetric int8 scaling (contrib/quantization.py), which maps exactly.

_INT8_MAX = 127.0


def _qdq_scale(ctx, base, lo, hi, denom=_INT8_MAX):
    """Emit scale = max(|lo|, |hi|, 1e-20) / denom; returns (scale, amax).

    Ranges that are exported parameter initializers constant-fold into
    scale initializers — onnxruntime's QDQ fusion requires constant Q/DQ
    scales to rebuild int8 kernels, and the runtime subgraph would defeat
    the point of the QDQ form."""
    pv = getattr(ctx, "param_values", {})
    if lo in pv and hi in pv:
        amax_v = max(abs(float(pv[lo])), abs(float(pv[hi])), 1e-20)
        sc = ctx.add_init(ctx.fresh(base + "_scale"),
                          _np.asarray(amax_v / denom, _np.float32))
        amax = ctx.add_init(ctx.fresh(base + "_amax"),
                            _np.asarray(amax_v, _np.float32))
        return sc, amax
    alo = ctx.fresh(base + "_alo")
    ctx.add_node("Abs", [lo], [alo])
    ahi = ctx.fresh(base + "_ahi")
    ctx.add_node("Abs", [hi], [ahi])
    raw = ctx.fresh(base + "_raw")
    ctx.add_node("Max", [alo, ahi], [raw])
    eps = ctx.add_init(ctx.fresh(base + "_eps"),
                       _np.asarray(1e-20, _np.float32))
    amax = ctx.fresh(base + "_amax")
    ctx.add_node("Max", [raw, eps], [amax])  # all-zero tensor: scale!=0
    den = ctx.add_init(ctx.fresh(base + "_den"),
                       _np.asarray(denom, _np.float32))
    sc = ctx.fresh(base + "_scale")
    ctx.add_node("Div", [amax, den], [sc])
    return sc, amax


def _qdq_zp(ctx, base, dtype=_np.int8):
    return ctx.add_init(ctx.fresh(base + "_zp"), _np.zeros((), dtype))


def _clip_to_range(ctx, base, x, amax):
    """Clip the float tensor to [-amax, amax] BEFORE QuantizeLinear: the
    imperative _q clamps codes to [-127, 127], while QuantizeLinear
    saturates at -128 — pre-clipping makes round(+-amax/scale) = +-127
    exactly. `amax` may be a tensor name or a python float."""
    if isinstance(amax, str):
        neg = ctx.fresh(base + "_neg")
        ctx.add_node("Neg", [amax], [neg])
        lo, hi = neg, amax
    else:
        lo = ctx.add_init(ctx.fresh(base + "_lo"),
                          _np.asarray(-amax, _np.float32))
        hi = ctx.add_init(ctx.fresh(base + "_hi"),
                          _np.asarray(amax, _np.float32))
    out = ctx.fresh(base + "_clip")
    ctx.add_node("Clip", [x, lo, hi], [out])
    return out


def _emit_deq(ctx, base, q, lo, hi, denom=_INT8_MAX):
    sc, _ = _qdq_scale(ctx, base, lo, hi, denom)
    out = ctx.fresh(base + "_deq")
    ctx.add_node("DequantizeLinear", [q, sc, _qdq_zp(ctx, base)], [out])
    return out


def _emit_req(ctx, base, y, outs):
    """Dynamic requantize: lo/hi measured from y (quantization._req)."""
    lo = ctx.fresh(base + "_lo")
    ctx.add_node("ReduceMin", [y], [lo], attrs={"keepdims": 0})
    hi = ctx.fresh(base + "_hi")
    ctx.add_node("ReduceMax", [y], [hi], attrs={"keepdims": 0})
    sc, amax = _qdq_scale(ctx, base, lo, hi)
    ctx.add_node("QuantizeLinear", [y, sc, _qdq_zp(ctx, base)], [outs[0]])
    ctx.add_node("Neg", [amax], [outs[1]])
    ctx.add_node("Identity", [amax], [outs[2]])


@_conv("_contrib_quantize_v2")
def _c_quantize_v2(ctx, s, ins, outs, shapes):  # noqa: ARG001
    lo = s.attr("min_calib_range")
    if lo is not None:
        hi = s.attr("max_calib_range")
        amax = max(abs(float(lo)), abs(float(hi)))
        sc = ctx.add_init(ctx.fresh(s.name + "_scale"),
                          _np.asarray(amax / _INT8_MAX, _np.float32))
        clipped = _clip_to_range(ctx, s.name, ins[0], amax)
        ctx.add_node("QuantizeLinear", [clipped, sc, _qdq_zp(ctx, s.name)],
                     [outs[0]], s.name)
        for o, v in ((outs[1], -amax), (outs[2], amax)):
            c = ctx.add_init(ctx.fresh(s.name + "_r"),
                             _np.asarray(v, _np.float32))
            ctx.add_node("Identity", [c], [o])
        return
    _emit_req(ctx, s.name, ins[0], outs)


@_conv("_contrib_quantize")
def _c_quantize(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # quantize with the CALLER-SUPPLIED range (quantize.cc), unlike
    # quantize_v2's dynamic/calibrated forms
    sc, amax = _qdq_scale(ctx, s.name, ins[1], ins[2])
    clipped = _clip_to_range(ctx, s.name, ins[0], amax)
    ctx.add_node("QuantizeLinear", [clipped, sc, _qdq_zp(ctx, s.name)],
                 [outs[0]], s.name)
    ctx.add_node("Neg", [amax], [outs[1]])
    ctx.add_node("Identity", [amax], [outs[2]])


@_conv("_contrib_dequantize")
def _c_dequantize(ctx, s, ins, outs, shapes):  # noqa: ARG001
    sc, _ = _qdq_scale(ctx, s.name, ins[1], ins[2])
    ctx.add_node("DequantizeLinear", [ins[0], sc, _qdq_zp(ctx, s.name)],
                 outs[:1], s.name)


@_conv("_contrib_requantize")
def _c_requantize(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # int32 accumulator input scaled against 2^31-1
    f = _emit_deq(ctx, s.name + "_in", ins[0], ins[1], ins[2],
                  denom=2.0 ** 31 - 1)
    lo = s.attr("min_calib_range")
    if lo is not None:
        # calibrated: fixed scale, out-of-range values saturate at +-127
        # (quantization.py requantize calib branch)
        hi = s.attr("max_calib_range")
        amax = max(abs(float(lo)), abs(float(hi)), 1e-20)
        sc = ctx.add_init(ctx.fresh(s.name + "_scale"),
                          _np.asarray(amax / _INT8_MAX, _np.float32))
        clipped = _clip_to_range(ctx, s.name, f, amax)
        ctx.add_node("QuantizeLinear", [clipped, sc, _qdq_zp(ctx, s.name)],
                     [outs[0]], s.name)
        for o, v in ((outs[1], -amax), (outs[2], amax)):
            c = ctx.add_init(ctx.fresh(s.name + "_r"),
                             _np.asarray(v, _np.float32))
            ctx.add_node("Identity", [c], [o])
        return
    _emit_req(ctx, s.name, f, outs)


@_conv("_contrib_quantized_conv")
def _c_quantized_conv(ctx, s, ins, outs, shapes):  # noqa: ARG001
    no_bias = s.attr("no_bias") in (True, 1, "True", "1")
    i = 2 if no_bias else 3
    data = _emit_deq(ctx, s.name + "_d", ins[0], ins[i], ins[i + 1])
    weight = _emit_deq(ctx, s.name + "_w", ins[1], ins[i + 2], ins[i + 3])
    conv_ins = [data, weight]
    if not no_bias:
        conv_ins.append(_emit_deq(ctx, s.name + "_b", ins[2], ins[i + 4],
                                  ins[i + 5]))
    kernel = list(s.attr("kernel"))
    nd = len(kernel)
    pad = list(s.attr("pad") or (0,) * nd)
    y = ctx.fresh(s.name + "_f")
    ctx.add_node("Conv", conv_ins, [y], s.name, {
        "kernel_shape": kernel,
        "strides": list(s.attr("stride") or (1,) * nd),
        "pads": pad + pad,
        "dilations": list(s.attr("dilate") or (1,) * nd),
        "group": int(s.attr("num_group") or 1)})
    _emit_req(ctx, s.name, y, outs)


@_conv("_contrib_quantized_fully_connected")
def _c_quantized_fc(ctx, s, ins, outs, shapes):
    no_bias = s.attr("no_bias") in (True, 1, "True", "1")
    i = 2 if no_bias else 3
    data = _emit_deq(ctx, s.name + "_d", ins[0], ins[i], ins[i + 1])
    weight = _emit_deq(ctx, s.name + "_w", ins[1], ins[i + 2], ins[i + 3])
    if len(shapes[0]) > 2:   # flatten=True default
        flat = ctx.fresh(s.name + "_flat")
        ctx.add_node("Flatten", [data], [flat], attrs={"axis": 1})
        data = flat
    y = ctx.fresh(s.name + "_f")
    gemm_ins = [data, weight]
    if not no_bias:
        gemm_ins.append(_emit_deq(ctx, s.name + "_b", ins[2], ins[i + 4],
                                  ins[i + 5]))
    ctx.add_node("Gemm", gemm_ins, [y], s.name, {"transB": 1})
    _emit_req(ctx, s.name, y, outs)


@_conv("_contrib_quantized_pooling")
def _c_quantized_pool(ctx, s, ins, outs, shapes):  # noqa: ARG001
    data = _emit_deq(ctx, s.name + "_d", ins[0], ins[1], ins[2])
    ptype = s.attr("pool_type") or "max"
    y = ctx.fresh(s.name + "_f")
    if s.attr("global_pool"):
        ctx.add_node("GlobalMaxPool" if ptype == "max"
                     else "GlobalAveragePool", [data], [y], s.name)
    else:
        kernel = list(s.attr("kernel") or (2, 2))
        nd = len(kernel)
        pad = list(s.attr("pad") or (0,) * nd)
        attrs = {"kernel_shape": kernel,
                 "strides": list(s.attr("stride") or kernel),
                 "pads": pad + pad}
        if ptype != "max":
            # ops/nn.py pooling averages WITH padded zeros in the count
            attrs["count_include_pad"] = 1
        ctx.add_node("MaxPool" if ptype == "max" else "AveragePool",
                     [data], [y], s.name, attrs)
    _emit_req(ctx, s.name, y, outs)


@_conv("_contrib_quantized_act")
def _c_quantized_act(ctx, s, ins, outs, shapes):  # noqa: ARG001
    data = _emit_deq(ctx, s.name + "_d", ins[0], ins[1], ins[2])
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus"}
    act = s.attr("act_type") or "relu"
    if act not in table:
        raise NotImplementedError(
            f"quantized_act act_type={act!r} not exportable "
            f"(supported: {sorted(table)})")
    y = ctx.fresh(s.name + "_f")
    ctx.add_node(table[act], [data], [y], s.name)
    _emit_req(ctx, s.name, y, outs)


@_conv("_contrib_quantized_flatten")
def _c_quantized_flatten(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # int8 codes and ranges pass through unchanged (quantized_flatten.cc)
    ctx.add_node("Flatten", [ins[0]], outs[:1], s.name, {"axis": 1})
    ctx.add_node("Identity", [ins[1]], [outs[1]])
    ctx.add_node("Identity", [ins[2]], [outs[2]])


@_conv("_contrib_quantized_elemwise_add")
def _c_quantized_eadd(ctx, s, ins, outs, shapes):  # noqa: ARG001
    a = _emit_deq(ctx, s.name + "_a", ins[0], ins[2], ins[3])
    b = _emit_deq(ctx, s.name + "_b", ins[1], ins[4], ins[5])
    y = ctx.fresh(s.name + "_f")
    ctx.add_node("Add", [a, b], [y], s.name)
    _emit_req(ctx, s.name, y, outs)


@_conv("_contrib_quantized_batch_norm")
def _c_quantized_bn(ctx, s, ins, outs, shapes):  # noqa: ARG001
    data = _emit_deq(ctx, s.name + "_d", ins[0], ins[5], ins[6])
    y = ctx.fresh(s.name + "_f")
    ctx.add_node("BatchNormalization",
                 [data, ins[1], ins[2], ins[3], ins[4]], [y], s.name,
                 {"epsilon": float(s.attr("eps") or 1e-3)})
    _emit_req(ctx, s.name, y, outs)


# ---------------------------------------------------------------------------
# Round-3 breadth: the remaining names of the reference's registered
# converter table (python/mxnet/onnx/mx2onnx/_op_translations/
# _op_translations_opset12.py + _op_translations_opset13.py, 170 names).
# ---------------------------------------------------------------------------

def _out_struct(ctx, s):
    st = ctx.structs.get(id(s))
    if isinstance(st, (tuple, list)):
        st = st[s._out_index or 0]
    return st


def _scalar_bin(onnx_op, reverse=False):
    """Legacy `<op>_scalar` spellings: the scalar attr folds to a const
    initializer cast to the tensor dtype (reference _op_translations:
    scalar ops)."""
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        dt = ctx.dtype_of(s._inputs[0])
        c = ctx.add_init(ctx.fresh(s.name + "_scalar"),
                         _np.asarray(s.attr("scalar"), dt))
        pair = [c, ins[0]] if reverse else [ins[0], c]
        ctx.add_node(onnx_op, pair, outs, s.name)

    return fn


def _scalar_cmp(onnx_op, reverse=False, negate=False):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        dt = ctx.dtype_of(s._inputs[0])
        c = ctx.add_init(ctx.fresh(s.name + "_scalar"),
                         _np.asarray(s.attr("scalar"), dt))
        b = ctx.fresh(s.name + "_bool")
        pair = [c, ins[0]] if reverse else [ins[0], c]
        ctx.add_node(onnx_op, pair, [b], s.name)
        if negate:
            nb = ctx.fresh(s.name + "_not")
            ctx.add_node("Not", [b], [nb])
            b = nb
        ctx.add_node("Cast", [b], outs,
                     attrs={"to": P.DTYPE.get(str(dt), 1)})

    return fn


for _name, _op, _rev in [
    ("_plus_scalar", "Add", False), ("_npi_add_scalar", "Add", False),
    ("_minus_scalar", "Sub", False),
    ("_npi_subtract_scalar", "Sub", False),
    ("_rminus_scalar", "Sub", True),
    ("_npi_rsubtract_scalar", "Sub", True),
    ("_mul_scalar", "Mul", False), ("_npi_multiply_scalar", "Mul", False),
    ("_div_scalar", "Div", False),
    ("_npi_true_divide_scalar", "Div", False),
    ("_rdiv_scalar", "Div", True),
    ("_npi_rtrue_divide_scalar", "Div", True),
    ("_power_scalar", "Pow", False), ("_npi_power_scalar", "Pow", False),
    ("_rpower_scalar", "Pow", True),
    ("_maximum_scalar", "Max", False), ("_minimum_scalar", "Min", False),
]:
    _CONVERTERS.setdefault(_name, _scalar_bin(_op, _rev))

for _name, _op, _rev, _neg in [
    ("_equal_scalar", "Equal", False, False),
    ("_not_equal_scalar", "Equal", False, True),
    ("_greater_scalar", "Greater", False, False),
    ("_greater_equal_scalar", "Less", False, True),
    ("_lesser_scalar", "Less", False, False),
    ("_lesser_equal_scalar", "Greater", False, True),
]:
    _CONVERTERS.setdefault(_name, _scalar_cmp(_op, _rev, _neg))


def _static_reshape(ctx, s, ins, outs, shapes):  # noqa: ARG001
    """Any reshape-flavored op with a statically-known output shape
    (legacy `Reshape` special codes 0/-1/-2/-3/-4, `_npx_reshape`,
    `reshape_like`): the inferred struct already has the answer."""
    st = _out_struct(ctx, s)
    shp = ctx.const_i64(s.name + "_shape", list(st.shape))
    ctx.add_node("Reshape", [ins[0], shp], outs, s.name)


_CONVERTERS.setdefault("Reshape", _static_reshape)
_CONVERTERS.setdefault("_npx_reshape", _static_reshape)
_CONVERTERS.setdefault("reshape_like", _static_reshape)


@_conv("size_array")
def _size_array(ctx, s, ins, outs, shapes):  # noqa: ARG001
    c = ctx.add_init(ctx.fresh(s.name + "_size"),
                     _np.asarray([int(_np.prod(shapes[0]))], _np.int64))
    ctx.add_node("Identity", [c], outs, s.name)


def _static_fill(fill):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        st = _out_struct(ctx, s)
        v = s.attr("value") if fill is None else fill
        c = ctx.add_init(ctx.fresh(s.name + "_c"),
                         _np.full(st.shape, v, _np.dtype(st.dtype)))
        ctx.add_node("Identity", [c], outs, s.name)

    return fn


for _name, _fill in [("_zeros", 0), ("_npi_zeros", 0), ("_ones", 1),
                     ("_npi_ones", 1), ("_full", None)]:
    _CONVERTERS.setdefault(_name, _static_fill(_fill))


def _static_arange(ctx, s, ins, outs, shapes):  # noqa: ARG001
    st = _out_struct(ctx, s)
    start = float(s.attr("start") or 0.0)
    step = float(s.attr("step") if s.attr("step") is not None else 1.0)
    repeat = int(s.attr("repeat") or 1)
    n = int(_np.prod(st.shape))
    base = start + step * _np.arange(-(-n // repeat))
    vals = (_np.repeat(base, repeat)[:n] if repeat > 1 else base[:n])
    c = ctx.add_init(ctx.fresh(s.name + "_ar"),
                     vals.reshape(st.shape).astype(st.dtype))
    ctx.add_node("Identity", [c], outs, s.name)


_CONVERTERS.setdefault("_arange", _static_arange)
_CONVERTERS.setdefault("_npi_arange", _static_arange)
_CONVERTERS.setdefault("_contrib_arange_like",
                       _CONVERTERS.get("arange_like"))

_CONVERTERS.setdefault("_copy", _simple("Identity"))
_CONVERTERS.setdefault("MakeLoss", _simple("Identity"))
_CONVERTERS.setdefault("add_n", _simple("Sum"))


@_conv("SoftmaxOutput")
def _softmax_output(ctx, s, ins, outs, shapes):  # noqa: ARG001
    # inference export: plain class-axis softmax; the label input and grad
    # scaling are training-only (reference opset13 convert_softmax_output)
    ctx.add_node("Softmax", [ins[0]], outs[:1], s.name, {"axis": 1})


@_conv("LogisticRegressionOutput")
def _logistic_output(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Sigmoid", [ins[0]], outs[:1], s.name)


@_conv("SequenceMask")
def _sequence_mask(ctx, s, ins, outs, shapes):
    from ..ops.rnn import _battr

    use_sl = _battr(str(s.attr("use_sequence_length")))
    if not use_sl or len(ins) < 2:
        ctx.add_node("Identity", [ins[0]], outs, s.name)
        return
    ax = int(s.attr("axis") or 0)          # time axis: 0 (TN...) or 1 (NT...)
    value = float(s.attr("value") or 0.0)
    rank = len(shapes[0])
    T = shapes[0][ax]
    pos_shape = (T, 1) if ax == 0 else (1, T)
    pos = ctx.add_init(ctx.fresh(s.name + "_pos"),
                       _np.arange(T, dtype=_np.float32).reshape(pos_shape))
    sl = ctx.fresh(s.name + "_slf")
    ctx.add_node("Cast", [ins[1]], [sl], attrs={"to": 1})
    slr = ctx.fresh(s.name + "_slr")
    shp = ctx.const_i64(s.name + "_slshape",
                        [1, -1] if ax == 0 else [-1, 1])
    ctx.add_node("Reshape", [sl, shp], [slr])
    mask = ctx.fresh(s.name + "_mask")
    ctx.add_node("Less", [pos, slr], [mask])       # (T,N) / (N,T) bool
    cur = mask
    if rank > 2:
        u = ctx.fresh(s.name + "_masku")
        ctx.unsqueeze([cur], [u], list(range(2, rank)))
        cur = u
    vc = ctx.add_init(ctx.fresh(s.name + "_val"),
                      _np.asarray(value, ctx.dtype_of(s._inputs[0])))
    ctx.add_node("Where", [cur, ins[0], vc], outs, s.name)


@_conv("ROIPooling")
def _roi_pooling(ctx, s, ins, outs, shapes):  # noqa: ARG001
    pooled = s.attr("pooled_size")
    ctx.add_node("MaxRoiPool", ins, outs, s.name,
                 {"pooled_shape": [int(p) for p in pooled],
                  "spatial_scale": float(s.attr("spatial_scale") or 1.0)})


def _maybe_transpose_last2(ctx, name, x, rank, do):
    if not do:
        return x
    perm = list(range(rank))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    t = ctx.fresh(name + "_T")
    ctx.add_node("Transpose", [x], [t], attrs={"perm": perm})
    return t


def _gemm2(ctx, s, ins, outs, shapes):
    from ..ops.rnn import _battr

    ta = s.attr("transpose_a") is not None and _battr(s.attr("transpose_a"))
    tb = s.attr("transpose_b") is not None and _battr(s.attr("transpose_b"))
    alpha = float(s.attr("alpha") if s.attr("alpha") is not None else 1.0)
    a = _maybe_transpose_last2(ctx, s.name + "_a", ins[0],
                               len(shapes[0]), ta)
    b = _maybe_transpose_last2(ctx, s.name + "_b", ins[1],
                               len(shapes[1]), tb)
    if alpha == 1.0:
        ctx.add_node("MatMul", [a, b], outs, s.name)
        return
    mm = ctx.fresh(s.name + "_mm")
    ctx.add_node("MatMul", [a, b], [mm], s.name)
    al = ctx.add_init(ctx.fresh(s.name + "_alpha"),
                      _np.asarray(alpha, ctx.dtype_of(s._inputs[0])))
    ctx.add_node("Mul", [mm, al], outs)


_CONVERTERS.setdefault("linalg_gemm2", _gemm2)
_CONVERTERS.setdefault("_linalg_gemm2", _gemm2)


def _selfatt_split_head(ctx, name, qkv, L, B, heads, D, which):
    """Interleaved (L, B, H*3*D) -> (B*heads, L, D) for q/k/v slot
    `which` (reference transformer.cc interleaved layout)."""
    r5 = ctx.fresh(name + "_r5")
    shp = ctx.const_i64(name + "_s5", [L, B, heads, 3, D])
    ctx.add_node("Reshape", [qkv, shp], [r5])
    sl = ctx.fresh(name + f"_slot{which}")
    starts = ctx.const_i64(name + "_st", [which])
    ends = ctx.const_i64(name + "_en", [which + 1])
    axes = ctx.const_i64(name + "_ax", [3])
    ctx.add_node("Slice", [r5, starts, ends, axes], [sl])
    sq = ctx.fresh(name + "_sq")
    ctx.squeeze([sl], [sq], [3])
    tr = ctx.fresh(name + "_tr")
    ctx.add_node("Transpose", [sq], [tr], attrs={"perm": [1, 2, 0, 3]})
    out = ctx.fresh(name + "_bh")
    shp2 = ctx.const_i64(name + "_s3", [B * heads, L, D])
    ctx.add_node("Reshape", [tr, shp2], [out])
    return out


@_conv("_contrib_interleaved_matmul_selfatt_qk")
def _c_selfatt_qk(ctx, s, ins, outs, shapes):
    heads = int(s.attr("heads"))
    L, B, E = shapes[0]
    D = E // (3 * heads)
    q = _selfatt_split_head(ctx, s.name + "_q", ins[0], L, B, heads, D, 0)
    k = _selfatt_split_head(ctx, s.name + "_k", ins[0], L, B, heads, D, 1)
    kt = ctx.fresh(s.name + "_kT")
    ctx.add_node("Transpose", [k], [kt], attrs={"perm": [0, 2, 1]})
    mm = ctx.fresh(s.name + "_mm")
    ctx.add_node("MatMul", [q, kt], [mm], s.name)
    scale = ctx.add_init(ctx.fresh(s.name + "_scale"),
                         _np.asarray(1.0 / _np.sqrt(D),
                                     ctx.dtype_of(s._inputs[0])))
    ctx.add_node("Mul", [mm, scale], outs)


@_conv("_contrib_interleaved_matmul_selfatt_valatt")
def _c_selfatt_valatt(ctx, s, ins, outs, shapes):
    heads = int(s.attr("heads"))
    L, B, E = shapes[0]
    D = E // (3 * heads)
    v = _selfatt_split_head(ctx, s.name + "_v", ins[0], L, B, heads, D, 2)
    mm = ctx.fresh(s.name + "_mm")
    ctx.add_node("MatMul", [ins[1], v], [mm], s.name)
    r4 = ctx.fresh(s.name + "_r4")
    shp = ctx.const_i64(s.name + "_s4", [B, heads, L, D])
    ctx.add_node("Reshape", [mm, shp], [r4])
    tr = ctx.fresh(s.name + "_tr")
    ctx.add_node("Transpose", [r4], [tr], attrs={"perm": [2, 0, 1, 3]})
    shp2 = ctx.const_i64(s.name + "_s3", [L, B, heads * D])
    ctx.add_node("Reshape", [tr, shp2], outs, s.name)


@_conv("_contrib_box_decode")
def _c_box_decode(ctx, s, ins, outs, shapes):  # noqa: ARG001
    """Decode center/size deltas against anchors (bounding_box.cc
    BoxDecode) as a Slice/Mul/Exp/Concat chain."""
    stds = [float(s.attr(f"std{i}") if s.attr(f"std{i}") is not None
                  else d) for i, d in enumerate((0.1, 0.1, 0.2, 0.2))]
    fmt = str(s.attr("format") or "corner")
    clip = float(s.attr("clip") if s.attr("clip") is not None else -1.0)
    dt = ctx.dtype_of(s._inputs[0])

    def chan(base, src, i):
        st = ctx.const_i64(base + "_st", [i])
        en = ctx.const_i64(base + "_en", [i + 1])
        ax = ctx.const_i64(base + "_ax", [2])
        out = ctx.fresh(base)
        ctx.add_node("Slice", [src, st, en, ax], [out])
        return out

    d = [chan(s.name + f"_d{i}", ins[0], i) for i in range(4)]
    a = [chan(s.name + f"_a{i}", ins[1], i) for i in range(4)]

    def binop(op, x, y, base):
        out = ctx.fresh(base)
        ctx.add_node(op, [x, y], [out])
        return out

    def constf(v, base):
        return ctx.add_init(ctx.fresh(base), _np.asarray(v, dt))

    if fmt == "corner":
        aw = binop("Sub", a[2], a[0], s.name + "_aw")
        ah = binop("Sub", a[3], a[1], s.name + "_ah")
        half = constf(0.5, s.name + "_half")
        acx = binop("Add", a[0],
                    binop("Mul", aw, half, s.name + "_awh"),
                    s.name + "_acx")
        acy = binop("Add", a[1],
                    binop("Mul", ah, half, s.name + "_ahh"),
                    s.name + "_acy")
    else:
        acx, acy, aw, ah = a
    cx = binop("Add", binop("Mul", binop(
        "Mul", d[0], constf(stds[0], s.name + "_s0"), s.name + "_ds0"),
        aw, s.name + "_dw"), acx, s.name + "_cx")
    cy = binop("Add", binop("Mul", binop(
        "Mul", d[1], constf(stds[1], s.name + "_s1"), s.name + "_ds1"),
        ah, s.name + "_dh"), acy, s.name + "_cy")
    ew = ctx.fresh(s.name + "_ew")
    ctx.add_node("Exp", [binop("Mul", d[2], constf(
        stds[2], s.name + "_s2"), s.name + "_ds2")], [ew])
    eh = ctx.fresh(s.name + "_eh")
    ctx.add_node("Exp", [binop("Mul", d[3], constf(
        stds[3], s.name + "_s3c"), s.name + "_ds3")], [eh])
    halfc = constf(0.5, s.name + "_halfc")
    w2 = binop("Mul", binop("Mul", ew, aw, s.name + "_w"), halfc,
               s.name + "_w2")
    h2 = binop("Mul", binop("Mul", eh, ah, s.name + "_h"), halfc,
               s.name + "_h2")
    parts = [binop("Sub", cx, w2, s.name + "_x0"),
             binop("Sub", cy, h2, s.name + "_y0"),
             binop("Add", cx, w2, s.name + "_x1"),
             binop("Add", cy, h2, s.name + "_y1")]
    if clip > 0:
        cat = ctx.fresh(s.name + "_cat")
        ctx.add_node("Concat", parts, [cat], attrs={"axis": 2})
        lo = constf(0.0, s.name + "_lo")
        hi = constf(clip, s.name + "_hi")
        ctx.add_node("Clip", [cat, lo, hi], outs, s.name)
    else:
        ctx.add_node("Concat", parts, outs, s.name, {"axis": 2})


@_conv("_contrib_AdaptiveAvgPooling2D")
def _c_adaptive_avg_pool(ctx, s, ins, outs, shapes):
    osz = s.attr("output_size") or 1
    oh, ow = ((int(osz), int(osz)) if isinstance(osz, int)
              else (int(osz[0]), int(osz[-1])))
    h, w = shapes[0][2], shapes[0][3]
    if (oh, ow) == (1, 1):
        ctx.add_node("GlobalAveragePool", ins, outs, s.name)
        return
    if h % oh or w % ow:
        raise NotImplementedError(
            f"AdaptiveAvgPooling2D {h}x{w}->{oh}x{ow}: non-divisible "
            "bins have data-dependent windows ONNX AveragePool can't "
            "express")
    ctx.add_node("AveragePool", ins, outs, s.name,
                 {"kernel_shape": [h // oh, w // ow],
                  "strides": [h // oh, w // ow]})


@_conv("_contrib_BilinearResize2D")
def _c_bilinear_resize(ctx, s, ins, outs, shapes):  # noqa: ARG001
    st = _out_struct(ctx, s)
    roi = ctx.add_init(ctx.fresh(s.name + "_roi"),
                       _np.zeros((0,), _np.float32))
    scales = ctx.add_init(ctx.fresh(s.name + "_scales"),
                          _np.zeros((0,), _np.float32))
    sizes = ctx.const_i64(s.name + "_sizes", list(st.shape))
    ctx.add_node("Resize", [ins[0], roi, scales, sizes], outs, s.name,
                 {"mode": "linear",
                  "coordinate_transformation_mode": "align_corners"})


def _random_node(onnx_op):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        st = _out_struct(ctx, s)
        attrs = {"shape": list(st.shape),
                 "dtype": P.DTYPE.get(str(st.dtype), 1)}
        def first_set(*keys, default):
            for k in keys:
                v = s.attr(k)
                if v is not None:
                    return float(v)
            return float(default)

        if onnx_op == "RandomNormal":
            attrs["mean"] = first_set("loc", "mu", default=0.0)
            attrs["scale"] = first_set("scale", "sigma", default=1.0)
        else:
            attrs["low"] = first_set("low", default=0.0)
            attrs["high"] = first_set("high", default=1.0)
        ctx.add_node(onnx_op, [], outs, s.name, attrs)

    return fn


for _name, _op in [("_random_normal", "RandomNormal"),
                   ("_npi_normal", "RandomNormal"),
                   ("_random_uniform", "RandomUniform"),
                   ("_npi_uniform", "RandomUniform")]:
    _CONVERTERS.setdefault(_name, _random_node(_op))


@_conv("_sample_multinomial")
def _c_sample_multinomial(ctx, s, ins, outs, shapes):  # noqa: ARG001
    st = ctx.structs.get(id(s))
    if isinstance(st, (tuple, list)):
        idx_st = st[0]
    else:
        idx_st = st
    n = int(_np.prod(idx_st.shape[len(shapes[0]) - 1:])) if len(
        idx_st.shape) >= len(shapes[0]) else 1
    lg = ctx.fresh(s.name + "_log")
    ctx.add_node("Log", [ins[0]], [lg])
    # ONNX Multinomial requires 2-D [batch, class] input; mx accepts any
    # leading batch rank (incl. a bare 1-D pvals vector)
    lg2 = ctx.fresh(s.name + "_log2d")
    k2 = int(shapes[0][-1])
    flat = ctx.const_i64(s.name + "_log2dshape", [-1, k2])
    ctx.add_node("Reshape", [lg, flat], [lg2])
    mn = ctx.fresh(s.name + "_mn")
    ctx.add_node("Multinomial", [lg2], [mn], s.name,
                 {"sample_size": max(n, 1), "dtype": 6})
    shp = ctx.const_i64(s.name + "_shape", list(idx_st.shape))
    ctx.add_node("Reshape", [mn, shp], outs[:1])
    if len(outs) > 1:
        # get_prob=True: gather each drawn index's log-probability
        k = shapes[0][-1]
        batch = list(shapes[0][:-1])
        S = list(idx_st.shape[len(batch):])
        lge = ctx.fresh(s.name + "_lge")
        rshp = ctx.const_i64(s.name + "_lgshape",
                             batch + [1] * len(S) + [k])
        ctx.add_node("Reshape", [lg, rshp], [lge])
        lgb = ctx.fresh(s.name + "_lgb")
        tgt = ctx.const_i64(s.name + "_lgtarget",
                            batch + S + [k])
        ctx.add_node("Expand", [lge, tgt], [lgb])
        idx64 = ctx.fresh(s.name + "_idx64")
        ctx.add_node("Cast", [outs[0]], [idx64], attrs={"to": 7})
        idxu = ctx.fresh(s.name + "_idxu")
        ctx.unsqueeze([idx64], [idxu], [len(batch) + len(S)])
        g = ctx.fresh(s.name + "_g")
        ctx.add_node("GatherElements", [lgb, idxu], [g],
                     attrs={"axis": len(batch) + len(S)})
        ctx.squeeze([g], [outs[1]], [len(batch) + len(S)])


# ---- fused RNN (reference opset13 convert_RNN) ---------------------------

_ONNX_GATE_PERM = {"lstm": [0, 3, 1, 2],   # mx [i,f,g,o] -> onnx [i,o,f,c]
                   "gru": [1, 0, 2]}       # mx [r,z,n]   -> onnx [z,r,h]


@_conv("RNN")
def _rnn(ctx, s, ins, outs, shapes):
    """Fused RNN -> ONNX LSTM/GRU/RNN node chain. Parameters must be a
    static initializer (they always are for exported models); the flat
    cuDNN blob is sliced host-side with ops.rnn.slice_rnn_params and
    re-packed into ONNX W/R/B with the gate-order permutation."""
    from ..ops.rnn import _GATES, _battr, slice_rnn_params

    mode = str(s.attr("mode") or "lstm")
    H = int(s.attr("state_size"))
    L = int(s.attr("num_layers") or 1)
    bi = _battr(str(s.attr("bidirectional")))
    state_out = _battr(str(s.attr("state_outputs")))
    if s.attr("projection_size"):
        raise NotImplementedError("LSTMP projection has no ONNX RNN form")
    D = 2 if bi else 1
    G = _GATES[mode]
    T, N, I = shapes[0]
    w_name = s._inputs[1]._name
    w = ctx.param_arrays.get(w_name)
    if w is None:
        raise NotImplementedError(
            f"RNN export needs static parameters ({w_name!r} is a "
            "runtime input)")
    blks = slice_rnn_params(_np.asarray(w, _np.float32).ravel(), mode, L,
                            I, H, bi)
    perm = _ONNX_GATE_PERM.get(mode)

    def gate_perm(mat):
        if perm is None:
            return mat
        return mat.reshape((G, H) + mat.shape[1:])[perm].reshape(mat.shape)

    onnx_op = {"lstm": "LSTM", "gru": "GRU",
               "rnn_relu": "RNN", "rnn_tanh": "RNN"}[mode]
    x = ins[0]
    hs, cs = [], []
    for layer in range(L):
        base = f"{s.name}_l{layer}"
        bl = [blks[layer * D + d] for d in range(D)]
        W = _np.stack([gate_perm(b["wx"]) for b in bl])
        R = _np.stack([gate_perm(b["wh"]) for b in bl])
        B = _np.stack([_np.concatenate([gate_perm(b["bx"]),
                                        gate_perm(b["bh"])]) for b in bl])
        wn = ctx.add_init(ctx.fresh(base + "_W"), W.astype(_np.float32))
        rn = ctx.add_init(ctx.fresh(base + "_R"), R.astype(_np.float32))
        bn = ctx.add_init(ctx.fresh(base + "_B"), B.astype(_np.float32))
        # initial states: rows [layer*D, (layer+1)*D) of the state input
        def state_slice(src, tag):
            st = ctx.const_i64(base + f"_{tag}st", [layer * D])
            en = ctx.const_i64(base + f"_{tag}en", [(layer + 1) * D])
            ax = ctx.const_i64(base + f"_{tag}ax", [0])
            out = ctx.fresh(base + f"_{tag}")
            ctx.add_node("Slice", [src, st, en, ax], [out])
            return out

        h0 = state_slice(ins[2], "h0")
        node_ins = [x, wn, rn, bn, "", h0]
        if mode == "lstm":
            node_ins.append(state_slice(ins[3], "c0"))
        attrs = {"hidden_size": H,
                 "direction": "bidirectional" if bi else "forward"}
        if mode == "gru":
            attrs["linear_before_reset"] = 1   # cuDNN/mx candidate form
        elif mode == "rnn_relu":
            attrs["activations"] = ["Relu"] * D
        elif mode == "rnn_tanh":
            attrs["activations"] = ["Tanh"] * D
        y = ctx.fresh(base + "_Y")
        yh = ctx.fresh(base + "_Yh")
        node_outs = [y, yh]
        if mode == "lstm":
            node_outs.append(ctx.fresh(base + "_Yc"))
        ctx.add_node(onnx_op, node_ins, node_outs, base, attrs)
        hs.append(yh)
        if mode == "lstm":
            cs.append(node_outs[2])
        # Y (T, D, N, H) -> (T, N, D*H) for the next layer / output
        tr = ctx.fresh(base + "_Ytr")
        ctx.add_node("Transpose", [y], [tr], attrs={"perm": [0, 2, 1, 3]})
        nxt = ctx.fresh(base + "_Yr")
        shp = ctx.const_i64(base + "_Yshape", [T, N, D * H])
        ctx.add_node("Reshape", [tr, shp], [nxt])
        x = nxt
    ctx.add_node("Identity", [x], outs[:1], s.name)
    if state_out and len(outs) > 1:
        if L == 1:
            ctx.add_node("Identity", [hs[0]], [outs[1]])
        else:
            ctx.add_node("Concat", hs, [outs[1]], attrs={"axis": 0})
        if mode == "lstm" and len(outs) > 2:
            if L == 1:
                ctx.add_node("Identity", [cs[0]], [outs[2]])
            else:
                ctx.add_node("Concat", cs, [outs[2]], attrs={"axis": 0})


# ---- alias spellings onto existing emission logic ------------------------

_ALIAS_TABLE = {
    "_npi_add": "broadcast_add", "_npi_subtract": "broadcast_sub",
    "_npi_multiply": "broadcast_mul", "_npi_true_divide": "broadcast_div",
    "_npi_power": "power", "_npi_absolute": "abs", "_npi_negative":
    "negative", "_npi_exp": "exp", "_npi_log": "log", "_npi_sqrt": "sqrt",
    "_npi_square": "square", "_npi_tanh": "tanh", "_npi_sin": "sin",
    "_npi_cos": "cos", "_npi_tan": "tan", "_npi_arcsin": "arcsin",
    "_npi_arccos": "arccos", "_npi_arctan": "arctan",
    "_npi_ceil": "ceil", "_npi_floor": "floor",
    "_npi_reciprocal": "reciprocal",
    "_npi_logical_and": "broadcast_logical_and",
    "_npi_logical_or": "broadcast_logical_or",
    "_npi_logical_xor": "broadcast_logical_xor",
    "_npi_logical_not": "logical_not",
    "_npi_sum": "sum", "_npi_mean": "mean", "_npi_max": "max",
    "_npi_min": "min", "_npi_prod": "prod",
    "_npi_squeeze": "squeeze", "_npi_broadcast_to": "broadcast_to",
    "_npx_relu": "relu", "_npx_sigmoid": "sigmoid",
    "_maximum": "maximum", "_minimum": "minimum", "_power": "power",
    "sum_axis": "sum", "BlockGrad": "identity",
}
for _alias, _target in _ALIAS_TABLE.items():
    if _target in _CONVERTERS:
        _CONVERTERS.setdefault(_alias, _CONVERTERS[_target])


def export_model(sym, params, in_shapes=None, in_types=_np.float32,
                 onnx_file_path="model.onnx", verbose=False, dynamic=False,
                 dynamic_input_shapes=None, opset_version=11):  # noqa: ARG001
    """Export a symbol + params to an ONNX file
    (reference: mx.onnx.export_model, mx2onnx/_export_model.py).

    sym: Symbol or path to a saved symbol json; params: dict name→NDArray
    (or path to a saved params file); in_shapes: list of shapes for the
    data inputs (arguments not found in params), in graph order.
    opset_version: 11 (default, attr-form Squeeze/Unsqueeze/ReduceSum/
    Split) or 12/13 (reference supports both via twin tables; 13 moves
    those ops' axes/split to inputs). Returns onnx_file_path.
    """
    if int(opset_version) not in (11, 12, 13):
        raise ValueError(f"opset_version {opset_version} unsupported "
                         "(11, 12, 13)")
    from ..ndarray.ndarray import NDArray

    if isinstance(sym, str):
        from ..symbol.symbol import load as _load_sym

        sym = _load_sym(sym)
    if isinstance(params, str):
        from ..ndarray.utils import load as _load_params

        params = _load_params(params)
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    args = sym.list_arguments()
    data_inputs = [a for a in args if a not in params]
    if in_shapes is None or len(in_shapes) != len(data_inputs):
        raise ValueError(
            f"in_shapes must give shapes for data inputs {data_inputs}")
    if not isinstance(in_types, (list, tuple)):
        in_types = [in_types] * len(data_inputs)

    np_params = {n: (v.asnumpy() if isinstance(v, NDArray)
                     else _np.asarray(v))
                 for n, v in params.items() if n in args}
    input_structs = {}
    for n, shp, dt in zip(data_inputs, in_shapes, in_types):
        input_structs[n] = jax.ShapeDtypeStruct(tuple(shp), _np.dtype(dt))
    for n, arr in np_params.items():
        input_structs[n] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    order = [s for s in sym._topo() if s._op != "_group"]
    shapes = _infer_all_shapes(order, input_structs)

    ctx = _Ctx(opset=int(opset_version))
    ctx.structs = shapes
    # scalar params (quantization ranges) fold into constant QDQ scales
    ctx.param_values = {n: a for n, a in np_params.items() if a.ndim == 0}
    ctx.param_arrays = np_params  # full static values (RNN blob slicing)
    tensor_names = {}  # id(sym-node) -> list of output tensor names
    converted = {}     # node name -> output tensor names (dedups the
    #                    out_index clones _flat_outputs creates)
    shape_by_name = {}

    for n, arr in np_params.items():
        ctx.add_init(n, arr)

    def _in_shape(i, pick):
        st = shapes[id(i)]
        if isinstance(st, (tuple, list)):
            st = st[pick]
        return tuple(st.shape)

    for s in order:
        shape_by_name.setdefault(s._name, shapes.get(id(s)))
        if s._op is None:
            tensor_names[id(s)] = [s._name]
            converted[s._name] = [s._name]
            continue
        if s._op == "_const":
            if s._name not in converted:
                cname = ctx.fresh(s._name)
                ctx.add_init(cname, _np.asarray(s._attrs["value"]))
                converted[s._name] = [cname]
            tensor_names[id(s)] = converted[s._name]
            continue
        if s._name in converted:  # out_index clone of an emitted node
            tensor_names[id(s)] = converted[s._name]
            continue
        outs = ([f"{s._name}_output{i}" for i in range(s._nout)]
                if s._nout > 1 else [f"{s._name}_output"])
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise NotImplementedError(
                f"op {s._op!r} has no ONNX converter "
                f"(node {s._name!r}); supported: {sorted(_CONVERTERS)}")
        in_names, in_shapes_list = [], []
        for i in s._inputs:
            names = tensor_names[id(i)]
            pick = i._out_index or 0
            in_names.append(names[pick] if len(names) > 1 else names[0])
            in_shapes_list.append(_in_shape(i, pick))
        conv(ctx, s, in_names, outs, in_shapes_list)
        converted[s._name] = outs
        tensor_names[id(s)] = outs

    # graph outputs
    out_infos = []
    for h in sym._flat_outputs():
        names = converted[h._name]
        pick = h._out_index or 0
        oname = names[pick] if len(names) > 1 else names[0]
        st = shape_by_name[h._name]
        if isinstance(st, (tuple, list)):
            st = st[pick]
        out_infos.append(P.value_info(
            oname, list(st.shape), P.DTYPE.get(str(st.dtype), 1)))

    in_infos = [P.value_info(n, list(input_structs[n].shape),
                             P.DTYPE.get(str(input_structs[n].dtype), 1))
                for n in data_inputs]

    g = P.graph(ctx.nodes, "mxnet_tpu_graph", ctx.initializers, in_infos,
                out_infos)
    buf = P.model(g, opset=ctx.opset)
    P.check_model(buf)
    with open(onnx_file_path, "wb") as f:
        f.write(buf)
    if verbose:
        print(f"exported {len(ctx.nodes)} nodes to {onnx_file_path}")
    return onnx_file_path
