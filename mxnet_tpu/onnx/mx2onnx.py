"""mx → ONNX exporter (reference: python/mxnet/onnx/mx2onnx/, 8,149 LoC of
op translation tables over the symbol graph).

TPU re-design notes: the exporter walks the mx.symbol DAG (the deployment
artifact, same as the reference), infers every intermediate shape with
jax.eval_shape (replacing the reference's mxnet shape inference), and emits
opset-11 ONNX via the dependency-free wire encoder in _proto.py. Training
graphs are exported in inference form (Dropout → ratio-annotated node,
BatchNorm → inference BN), matching reference behavior.
"""
from __future__ import annotations

import jax
import numpy as _np

from ..symbol.symbol import _OP_TABLE, Symbol
from . import _proto as P

__all__ = ["export_model"]


class _Ctx:
    def __init__(self):
        self.nodes = []        # encoded NodeProtos
        self.initializers = []
        self._counter = 0
        self.structs = {}      # id(sym-node) -> ShapeDtypeStruct

    def dtype_of(self, sym_node, default=_np.float32):
        st = self.structs.get(id(sym_node))
        if st is None:
            return _np.dtype(default)
        if isinstance(st, (tuple, list)):
            st = st[0]
        return _np.dtype(st.dtype)

    def fresh(self, base):
        self._counter += 1
        return f"{base}__{self._counter}"

    def add_node(self, op_type, inputs, outputs, name="", attrs=None):
        self.nodes.append(P.node(op_type, inputs, outputs, name, attrs))

    def add_init(self, name, arr):
        self.initializers.append(P.tensor(name, _np.asarray(arr)))
        return name

    def const_i64(self, base, vals):
        return self.add_init(self.fresh(base),
                             _np.asarray(vals, _np.int64))


# Each converter: fn(ctx, sym, in_names, out_names, in_shapes) -> None
_CONVERTERS = {}


def _conv(name):
    def deco(fn):
        _CONVERTERS[name] = fn
        return fn

    return deco


def _simple(onnx_op, **fixed):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        ctx.add_node(onnx_op, ins, outs, s.name, dict(fixed))

    return fn


for _mx, _onnx in [
    ("elemwise_add", "Add"), ("broadcast_add", "Add"),
    ("elemwise_sub", "Sub"), ("broadcast_sub", "Sub"),
    ("elemwise_mul", "Mul"), ("broadcast_mul", "Mul"),
    ("elemwise_div", "Div"), ("broadcast_div", "Div"),
    ("power", "Pow"), ("negative", "Neg"), ("exp", "Exp"), ("log", "Log"),
    ("sqrt", "Sqrt"), ("tanh", "Tanh"), ("abs", "Abs"),
    ("sigmoid", "Sigmoid"), ("relu", "Relu"),
    ("maximum", "Max"), ("minimum", "Min"),
]:
    _CONVERTERS[_mx] = _simple(_onnx)


@_conv("square")
def _square(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Mul", [ins[0], ins[0]], outs, s.name)


@_conv("where")
def _where(ctx, s, ins, outs, shapes):  # noqa: ARG001
    cond = ctx.fresh(s.name + "_cond")
    ctx.add_node("Cast", [ins[0]], [cond], attrs={"to": 9})  # bool
    ctx.add_node("Where", [cond, ins[1], ins[2]], outs, s.name)


@_conv("clip")
def _clip(ctx, s, ins, outs, shapes):  # noqa: ARG001
    lo = ctx.add_init(ctx.fresh(s.name + "_min"),
                      _np.float32(s.attr("a_min")))
    hi = ctx.add_init(ctx.fresh(s.name + "_max"),
                      _np.float32(s.attr("a_max")))
    ctx.add_node("Clip", [ins[0], lo, hi], outs, s.name)


def _reduce(onnx_op):
    def fn(ctx, s, ins, outs, shapes):  # noqa: ARG001
        attrs = {"keepdims": int(bool(s.attr("keepdims")))}
        ax = s.attr("axis")
        if ax is not None:
            attrs["axes"] = [ax] if isinstance(ax, int) else list(ax)
        ctx.add_node(onnx_op, ins, outs, s.name, attrs)

    return fn


_CONVERTERS["sum"] = _reduce("ReduceSum")
_CONVERTERS["mean"] = _reduce("ReduceMean")
_CONVERTERS["max"] = _reduce("ReduceMax")
_CONVERTERS["min"] = _reduce("ReduceMin")
_CONVERTERS["prod"] = _reduce("ReduceProd")


@_conv("norm")
def _norm(ctx, s, ins, outs, shapes):  # noqa: ARG001
    order = s.attr("ord")
    order = 2 if order is None else order
    if order == 2:
        op = "ReduceL2"
    elif order == 1:
        op = "ReduceL1"
    else:
        raise NotImplementedError(
            f"norm ord={order!r} not exportable (ReduceL1/L2 only)")
    _reduce(op)(ctx, s, ins, outs, shapes)


def _arg(onnx_op):
    def fn(ctx, s, ins, outs, shapes):
        ax = s.attr("axis")
        raw = ctx.fresh(s.name + "_i64")
        data = ins[0]
        if ax is None:
            # jnp.argmax(axis=None) reduces the flattened array to a scalar
            flat = ctx.fresh(s.name + "_flat")
            shp = ctx.const_i64(s.name + "_m1", [-1])
            ctx.add_node("Reshape", [ins[0], shp], [flat])
            data, ax = flat, 0
        ctx.add_node(onnx_op, [data], [raw], s.name,
                     {"axis": int(ax), "keepdims": 0})
        ctx.add_node("Cast", [raw], outs, attrs={"to": 1})  # float32 parity

    return fn


_CONVERTERS["argmax"] = _arg("ArgMax")
_CONVERTERS["argmin"] = _arg("ArgMin")


@_conv("transpose")
def _transpose(ctx, s, ins, outs, shapes):
    axes = s.attr("axes")
    if axes is None:
        axes = list(range(len(shapes[0])))[::-1]
    ctx.add_node("Transpose", ins, outs, s.name, {"perm": list(axes)})


@_conv("swapaxes")
def _swapaxes(ctx, s, ins, outs, shapes):
    rank = len(shapes[0])
    perm = list(range(rank))
    d1, d2 = s.attr("dim1") % rank, s.attr("dim2") % rank
    perm[d1], perm[d2] = perm[d2], perm[d1]
    ctx.add_node("Transpose", ins, outs, s.name, {"perm": perm})


@_conv("reshape")
def _reshape(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.const_i64(s.name + "_shape", list(s.attr("shape")))
    ctx.add_node("Reshape", [ins[0], shp], outs, s.name)


@_conv("Flatten")
def _flatten(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Flatten", ins, outs, s.name, {"axis": 1})


@_conv("expand_dims")
def _expand_dims(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Unsqueeze", ins, outs, s.name, {"axes": [s.attr("axis")]})


@_conv("squeeze")
def _squeeze(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ax = s.attr("axis")
    attrs = {}
    if ax is not None:
        attrs["axes"] = [ax] if isinstance(ax, int) else list(ax)
    ctx.add_node("Squeeze", ins, outs, s.name, attrs)


@_conv("broadcast_to")
def _broadcast_to(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.const_i64(s.name + "_shape", list(s.attr("shape")))
    ctx.add_node("Expand", [ins[0], shp], outs, s.name)


@_conv("zeros_like")
def _zeros_like(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.fresh(s.name + "_shape")
    ctx.add_node("Shape", ins, [shp])
    dt = ctx.dtype_of(s._inputs[0])  # emit in the source dtype
    ctx.add_node("ConstantOfShape", [shp], outs, s.name,
                 {"value": _np.zeros(1, dt)})


@_conv("ones_like")
def _ones_like(ctx, s, ins, outs, shapes):  # noqa: ARG001
    shp = ctx.fresh(s.name + "_shape")
    ctx.add_node("Shape", ins, [shp])
    dt = ctx.dtype_of(s._inputs[0])
    ctx.add_node("ConstantOfShape", [shp], outs, s.name,
                 {"value": _np.ones(1, dt)})


@_conv("slice")
def _slice(ctx, s, ins, outs, shapes):
    begin, end = list(s.attr("begin")), list(s.attr("end"))
    step = list(s.attr("step") or [1] * len(begin))
    step = [1 if st is None else st for st in step]
    INT_MIN = -(2 ** 31)
    b_res, e_res = [], []
    for i, (b, e) in enumerate(zip(begin, end)):
        if step[i] < 0:
            # python slice(None, None, -st) == start at last elem, run past 0;
            # ONNX needs an out-of-range sentinel for "include index 0"
            b_res.append(shapes[0][i] - 1 if b is None else b)
            e_res.append(INT_MIN if e is None else e)
        else:
            b_res.append(0 if b is None else b)
            e_res.append(shapes[0][i] if e is None else e)
    starts = ctx.const_i64(s.name + "_starts", b_res)
    ends = ctx.const_i64(s.name + "_ends", e_res)
    axes = ctx.const_i64(s.name + "_axes", list(range(len(begin))))
    slice_ins = [ins[0], starts, ends, axes]
    if any(st != 1 for st in step):
        slice_ins.append(ctx.const_i64(s.name + "_steps", step))
    ctx.add_node("Slice", slice_ins, outs, s.name)


@_conv("slice_axis")
def _slice_axis(ctx, s, ins, outs, shapes):
    ax = s.attr("axis")
    begin = s.attr("begin") or 0
    end = s.attr("end")
    if end is None:
        end = shapes[0][ax]
    starts = ctx.const_i64(s.name + "_starts", [begin])
    ends = ctx.const_i64(s.name + "_ends", [end])
    axes = ctx.const_i64(s.name + "_axes", [ax])
    ctx.add_node("Slice", [ins[0], starts, ends, axes], outs, s.name)


@_conv("split")
def _split(ctx, s, ins, outs, shapes):
    ax = s.attr("axis") if s.attr("axis") is not None else 1
    n = len(outs)
    size = shapes[0][ax] // n
    ctx.add_node("Split", ins, outs, s.name,
                 {"axis": ax, "split": [size] * n})


@_conv("Concat")
def _concat(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Concat", ins, outs, s.name,
                 {"axis": s.attr("dim") if s.attr("dim") is not None else 1})


@_conv("stack")
def _stack(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ax = s.attr("axis") or 0
    unsq = []
    for i in ins:
        u = ctx.fresh(i + "_unsq")
        ctx.add_node("Unsqueeze", [i], [u], attrs={"axes": [ax]})
        unsq.append(u)
    ctx.add_node("Concat", unsq, outs, s.name, {"axis": ax})


@_conv("dot")
def _dot(ctx, s, ins, outs, shapes):
    if len(shapes[0]) >= 2 and len(shapes[1]) >= 3:
        raise NotImplementedError(
            "dot with rank>=3 rhs follows np.dot outer-stacking semantics, "
            "which ONNX MatMul (batched) does not match; use batch_dot")
    ctx.add_node("MatMul", ins, outs, s.name)


@_conv("batch_dot")
def _batch_dot(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("MatMul", ins, outs, s.name)


@_conv("FullyConnected")
def _fc(ctx, s, ins, outs, shapes):
    data = ins[0]
    rank = len(shapes[0])
    if rank != 2 and s.attr("flatten") in (None, True):
        flat = ctx.fresh(s.name + "_flat")
        ctx.add_node("Flatten", [ins[0]], [flat], attrs={"axis": 1})
        data, rank = flat, 2
    if rank != 2:
        # flatten=False on rank>2: batched projection — Gemm requires 2-D,
        # so emit MatMul(x, W^T) (+ Add bias)
        wt = ctx.fresh(s.name + "_wT")
        ctx.add_node("Transpose", [ins[1]], [wt], attrs={"perm": [1, 0]})
        if len(ins) > 2:
            mm = ctx.fresh(s.name + "_mm")
            ctx.add_node("MatMul", [data, wt], [mm])
            ctx.add_node("Add", [mm, ins[2]], outs, s.name)
        else:
            ctx.add_node("MatMul", [data, wt], outs, s.name)
        return
    if len(ins) > 2:
        ctx.add_node("Gemm", [data, ins[1], ins[2]], outs, s.name,
                     {"transB": 1})
    else:
        ctx.add_node("Gemm", [data, ins[1]], outs, s.name, {"transB": 1})


@_conv("Convolution")
def _convolution(ctx, s, ins, outs, shapes):
    kshape = list(shapes[1][2:])  # weight (O, I/g, kh, kw)
    nd = len(kshape)
    stride = list(s.attr("stride") or (1,) * nd)
    dilate = list(s.attr("dilate") or (1,) * nd)
    pad = list(s.attr("pad") or (0,) * nd)
    ctx.add_node("Conv", ins, outs, s.name, {
        "kernel_shape": kshape, "strides": stride, "dilations": dilate,
        "pads": pad + pad, "group": int(s.attr("num_group") or 1)})


@_conv("Deconvolution")
def _deconvolution(ctx, s, ins, outs, shapes):
    kshape = list(shapes[1][2:])
    nd = len(kshape)
    stride = list(s.attr("stride") or (1,) * nd)
    pad = list(s.attr("pad") or (0,) * nd)
    ctx.add_node("ConvTranspose", ins, outs, s.name, {
        "kernel_shape": kshape, "strides": stride, "pads": pad + pad})


@_conv("Activation")
def _activation(ctx, s, ins, outs, shapes):  # noqa: ARG001
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = s.attr("act_type") or "relu"
    ctx.add_node(table[act], ins, outs, s.name)


@_conv("LeakyReLU")
def _leaky(ctx, s, ins, outs, shapes):  # noqa: ARG001
    act = s.attr("act_type") or "leaky"
    slope = float(s.attr("slope") if s.attr("slope") is not None else 0.25)
    if act == "leaky":
        ctx.add_node("LeakyRelu", ins, outs, s.name, {"alpha": slope})
    elif act == "elu":
        ctx.add_node("Elu", ins, outs, s.name, {"alpha": slope})
    elif act == "prelu":
        ctx.add_node("PRelu", ins, outs, s.name)
    elif act == "gelu":
        # opset-11 decomposition: x * 0.5 * (1 + erf(x / sqrt(2)))
        invsqrt2 = ctx.add_init(ctx.fresh(s.name + "_c"),
                                _np.float32(1 / _np.sqrt(2.0)))
        half = ctx.add_init(ctx.fresh(s.name + "_h"), _np.float32(0.5))
        one = ctx.add_init(ctx.fresh(s.name + "_1"), _np.float32(1.0))
        t1 = ctx.fresh(s.name + "_t1")
        ctx.add_node("Mul", [ins[0], invsqrt2], [t1])
        t2 = ctx.fresh(s.name + "_t2")
        ctx.add_node("Erf", [t1], [t2])
        t3 = ctx.fresh(s.name + "_t3")
        ctx.add_node("Add", [t2, one], [t3])
        t4 = ctx.fresh(s.name + "_t4")
        ctx.add_node("Mul", [ins[0], t3], [t4])
        ctx.add_node("Mul", [t4, half], outs, s.name)
    else:
        raise ValueError(f"LeakyReLU act_type {act!r} not exportable")


@_conv("Pooling")
def _pooling(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ptype = s.attr("pool_type") or "max"
    if s.attr("global_pool"):
        op = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
        ctx.add_node(op, ins, outs, s.name)
        return
    kernel = list(s.attr("kernel") or (2, 2))
    nd = len(kernel)
    stride = list(s.attr("stride") or kernel)
    pad = list(s.attr("pad") or (0,) * nd)
    op = "MaxPool" if ptype == "max" else "AveragePool"
    ctx.add_node(op, ins, outs, s.name, {
        "kernel_shape": kernel, "strides": stride, "pads": pad + pad})


@_conv("BatchNorm")
def _batchnorm(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("BatchNormalization", ins, outs, s.name,
                 {"epsilon": float(s.attr("eps") or 1e-5)})


@_conv("LayerNorm")
def _layernorm(ctx, s, ins, outs, shapes):
    """Opset-11 decomposition (LayerNormalization needs opset 17)."""
    ax = s.attr("axis")
    ax = -1 if ax is None else ax
    rank = len(shapes[0])
    ax = ax % rank
    eps = ctx.add_init(ctx.fresh(s.name + "_eps"),
                       _np.float32(s.attr("eps") or 1e-5))
    mean = ctx.fresh(s.name + "_mean")
    ctx.add_node("ReduceMean", [ins[0]], [mean],
                 attrs={"axes": [ax], "keepdims": 1})
    cent = ctx.fresh(s.name + "_cent")
    ctx.add_node("Sub", [ins[0], mean], [cent])
    sq = ctx.fresh(s.name + "_sq")
    ctx.add_node("Mul", [cent, cent], [sq])
    var = ctx.fresh(s.name + "_var")
    ctx.add_node("ReduceMean", [sq], [var], attrs={"axes": [ax],
                                                   "keepdims": 1})
    veps = ctx.fresh(s.name + "_veps")
    ctx.add_node("Add", [var, eps], [veps])
    std = ctx.fresh(s.name + "_std")
    ctx.add_node("Sqrt", [veps], [std])
    normed = ctx.fresh(s.name + "_normed")
    ctx.add_node("Div", [cent, std], [normed])
    scaled = ctx.fresh(s.name + "_scaled")
    ctx.add_node("Mul", [normed, ins[1]], [scaled])
    ctx.add_node("Add", [scaled, ins[2]], outs, s.name)


@_conv("Dropout")
def _dropout(ctx, s, ins, outs, shapes):  # noqa: ARG001
    ctx.add_node("Dropout", ins, outs, s.name,
                 {"ratio": float(s.attr("p") if s.attr("p") is not None
                                 else 0.5)})


def _softmax_like(onnx_op):
    def fn(ctx, s, ins, outs, shapes):
        """Opset-11 Softmax flattens ALL trailing dims from `axis`; that
        only matches per-axis softmax when the axis is last. For any other
        axis, transpose it to last, apply, transpose back."""
        rank = len(shapes[0])
        ax = s.attr("axis")
        ax = (rank - 1) if ax is None else int(ax) % rank
        if ax == rank - 1:
            ctx.add_node(onnx_op, ins, outs, s.name, {"axis": rank - 1})
            return
        perm = [i for i in range(rank) if i != ax] + [ax]
        inv = [perm.index(i) for i in range(rank)]
        t1 = ctx.fresh(s.name + "_t")
        ctx.add_node("Transpose", ins, [t1], attrs={"perm": perm})
        sm = ctx.fresh(s.name + "_sm")
        ctx.add_node(onnx_op, [t1], [sm], attrs={"axis": rank - 1})
        ctx.add_node("Transpose", [sm], outs, s.name, {"perm": inv})

    return fn


_CONVERTERS["softmax"] = _softmax_like("Softmax")
_CONVERTERS["log_softmax"] = _softmax_like("LogSoftmax")


@_conv("Embedding")
def _embedding(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[0]], [idx], attrs={"to": 7})  # int64
    ctx.add_node("Gather", [ins[1], idx], outs, s.name, {"axis": 0})


@_conv("take")
def _take(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[1]], [idx], attrs={"to": 7})
    ctx.add_node("Gather", [ins[0], idx], outs, s.name,
                 {"axis": int(s.attr("axis") or 0)})


@_conv("one_hot")
def _one_hot(ctx, s, ins, outs, shapes):  # noqa: ARG001
    idx = ctx.fresh(s.name + "_idx")
    ctx.add_node("Cast", [ins[0]], [idx], attrs={"to": 7})
    depth = ctx.const_i64(s.name + "_depth", [s.attr("depth")])
    values = ctx.add_init(ctx.fresh(s.name + "_vals"),
                          _np.asarray([0.0, 1.0], _np.float32))
    ctx.add_node("OneHot", [idx, depth, values], outs, s.name, {"axis": -1})


# --- shape inference over the symbol DAG -----------------------------------

def _infer_all_shapes(order, input_structs):
    """Per-node output ShapeDtypeStructs via jax.eval_shape, one op at a
    time (the reference ran nnvm InferShape over the whole graph)."""
    shapes = {}
    for s in order:
        if s._op is None:
            shapes[id(s)] = input_structs[s._name]
        elif s._op == "_const":
            v = _np.asarray(s._attrs["value"])
            shapes[id(s)] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        elif s._op == "_group":
            continue
        else:
            ins = [shapes[id(i)] for i in s._inputs]
            fn = _OP_TABLE[s._op]
            out = jax.eval_shape(lambda *xs, _fn=fn, _a=s._attrs: _fn(
                list(xs), _a), *ins)
            shapes[id(s)] = out
    return shapes


def export_model(sym, params, in_shapes=None, in_types=_np.float32,
                 onnx_file_path="model.onnx", verbose=False, dynamic=False,
                 dynamic_input_shapes=None):  # noqa: ARG001
    """Export a symbol + params to an ONNX file
    (reference: mx.onnx.export_model, mx2onnx/_export_model.py).

    sym: Symbol or path to a saved symbol json; params: dict name→NDArray
    (or path to a saved params file); in_shapes: list of shapes for the
    data inputs (arguments not found in params), in graph order.
    Returns onnx_file_path.
    """
    from ..ndarray.ndarray import NDArray

    if isinstance(sym, str):
        from ..symbol.symbol import load as _load_sym

        sym = _load_sym(sym)
    if isinstance(params, str):
        from ..ndarray.utils import load as _load_params

        params = _load_params(params)
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    args = sym.list_arguments()
    data_inputs = [a for a in args if a not in params]
    if in_shapes is None or len(in_shapes) != len(data_inputs):
        raise ValueError(
            f"in_shapes must give shapes for data inputs {data_inputs}")
    if not isinstance(in_types, (list, tuple)):
        in_types = [in_types] * len(data_inputs)

    np_params = {n: (v.asnumpy() if isinstance(v, NDArray)
                     else _np.asarray(v))
                 for n, v in params.items() if n in args}
    input_structs = {}
    for n, shp, dt in zip(data_inputs, in_shapes, in_types):
        input_structs[n] = jax.ShapeDtypeStruct(tuple(shp), _np.dtype(dt))
    for n, arr in np_params.items():
        input_structs[n] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    order = [s for s in sym._topo() if s._op != "_group"]
    shapes = _infer_all_shapes(order, input_structs)

    ctx = _Ctx()
    ctx.structs = shapes
    tensor_names = {}  # id(sym-node) -> list of output tensor names
    converted = {}     # node name -> output tensor names (dedups the
    #                    out_index clones _flat_outputs creates)
    shape_by_name = {}

    for n, arr in np_params.items():
        ctx.add_init(n, arr)

    def _in_shape(i, pick):
        st = shapes[id(i)]
        if isinstance(st, (tuple, list)):
            st = st[pick]
        return tuple(st.shape)

    for s in order:
        shape_by_name.setdefault(s._name, shapes.get(id(s)))
        if s._op is None:
            tensor_names[id(s)] = [s._name]
            converted[s._name] = [s._name]
            continue
        if s._op == "_const":
            if s._name not in converted:
                cname = ctx.fresh(s._name)
                ctx.add_init(cname, _np.asarray(s._attrs["value"]))
                converted[s._name] = [cname]
            tensor_names[id(s)] = converted[s._name]
            continue
        if s._name in converted:  # out_index clone of an emitted node
            tensor_names[id(s)] = converted[s._name]
            continue
        outs = ([f"{s._name}_output{i}" for i in range(s._nout)]
                if s._nout > 1 else [f"{s._name}_output"])
        conv = _CONVERTERS.get(s._op)
        if conv is None:
            raise NotImplementedError(
                f"op {s._op!r} has no ONNX converter "
                f"(node {s._name!r}); supported: {sorted(_CONVERTERS)}")
        in_names, in_shapes_list = [], []
        for i in s._inputs:
            names = tensor_names[id(i)]
            pick = i._out_index or 0
            in_names.append(names[pick] if len(names) > 1 else names[0])
            in_shapes_list.append(_in_shape(i, pick))
        conv(ctx, s, in_names, outs, in_shapes_list)
        converted[s._name] = outs
        tensor_names[id(s)] = outs

    # graph outputs
    out_infos = []
    for h in sym._flat_outputs():
        names = converted[h._name]
        pick = h._out_index or 0
        oname = names[pick] if len(names) > 1 else names[0]
        st = shape_by_name[h._name]
        if isinstance(st, (tuple, list)):
            st = st[pick]
        out_infos.append(P.value_info(
            oname, list(st.shape), P.DTYPE.get(str(st.dtype), 1)))

    in_infos = [P.value_info(n, list(input_structs[n].shape),
                             P.DTYPE.get(str(input_structs[n].dtype), 1))
                for n in data_inputs]

    g = P.graph(ctx.nodes, "mxnet_tpu_graph", ctx.initializers, in_infos,
                out_infos)
    buf = P.model(g)
    P.check_model(buf)
    with open(onnx_file_path, "wb") as f:
        f.write(buf)
    if verbose:
        print(f"exported {len(ctx.nodes)} nodes to {onnx_file_path}")
    return onnx_file_path
