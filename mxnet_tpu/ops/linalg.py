"""linalg op family (reference: src/operator/tensor/la_op.cc — the `_linalg_*`
NNVM names: gemm/gemm2/potrf/potri/trmm/trsm/syrk/gelqf/syevd/
sumlogdiag/extractdiag/makediag/extracttrian/maketrian/inverse/det/slogdet).

All ops batch over leading dimensions like the reference (la_op.h
LaOpCaller). XLA lowers cholesky/qr/eigh/triangular_solve natively on TPU;
gradients ride jax's built-in rules.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

from .registry import register_op


@register_op("linalg_gemm")
def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
         axis=-2):  # noqa: ARG001 - axis parity (batch axis position)
    """C' = alpha * op(A) @ op(B) + beta * C (la_op.cc linalg_gemm)."""
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register_op("linalg_gemm2")
def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register_op("linalg_potrf")
def potrf(A):
    """Cholesky factor L with A = L L^T (la_op.cc linalg_potrf)."""
    return jnp.linalg.cholesky(A)


@register_op("linalg_potri")
def potri(A):
    """Inverse from the Cholesky factor: given L, compute (L L^T)^-1."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register_op("linalg_trmm")
def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular matrix multiply (la_op.cc linalg_trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    out = jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B)
    return alpha * out


@register_op("linalg_trsm")
def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B) for triangular A."""
    if rightside:
        # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
        xt = solve_triangular(
            jnp.swapaxes(A, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            lower=not lower, trans=1 if transpose else 0)
        return jnp.swapaxes(xt, -1, -2)
    return solve_triangular(A, alpha * B, lower=lower,
                            trans=1 if transpose else 0)


@register_op("linalg_syrk")
def syrk(A, transpose=False, alpha=1.0):
    """alpha * A A^T (or A^T A when transpose) — la_op.cc linalg_syrk."""
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register_op("linalg_gelqf")
def gelqf(A):
    """LQ factorization A = L Q with Q orthonormal rows (la_op.cc:821
    gelqf). Output order is **(Q, L)** — the reference's documented
    `Q, L = gelqf(A)` (la_op.cc examples); r5 fixed a swapped order that
    an identity-only test had encoded.

    Computed via QR of A^T: A^T = Q' R'  =>  A = R'^T Q'^T.
    """
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


@register_op("linalg_syevd")
def syevd(A):
    """Symmetric eigendecomposition: returns (U, L) with A = U^T diag(L) U."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register_op("linalg_sumlogdiag")
def sumlogdiag(A):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register_op("linalg_extractdiag")
def extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register_op("linalg_makediag")
def makediag(A, offset=0):
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    idx = jnp.arange(A.shape[-1])
    r = idx + (-offset if offset < 0 else 0)
    c = idx + (offset if offset > 0 else 0)
    return out.at[..., r, c].set(A)


@register_op("linalg_extracttrian")
def extracttrian(A, offset=0, lower=True):
    """Extract (packed) triangle incl. the offset diagonal (la_op.cc)."""
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


@register_op("linalg_maketrian")
def maketrian(A, offset=0, lower=True):
    """Unpack a packed triangle back into an (n, n) matrix (la_op.cc).

    n is recovered in closed form from the packed length m: with
    o = offset for lower (o = -offset for upper, by tril/triu symmetry),
      o <= 0:  m = (n+o)(n+o+1)/2          =>  n = tri_root(m) - o
      o  > 0:  m = n(n+1)/2 + o*n - o(o+1)/2  (quadratic in n)
    """
    import math

    m = A.shape[-1]
    o = offset if lower else -offset
    if o <= 0:
        t = int((math.isqrt(8 * m + 1) - 1) // 2)
        n = t - o
    else:
        disc = (1 + 2 * o) ** 2 + 4 * (o * o + o + 2 * m)
        n = int((math.isqrt(disc) - (1 + 2 * o)) // 2)
    rows, cols = (jnp.tril_indices(n, k=offset) if lower
                  else jnp.triu_indices(n, k=offset))
    if int(rows.shape[0]) != m:
        raise ValueError(
            f"packed length {m} does not form a triangle with offset "
            f"{offset}")
    out = jnp.zeros(A.shape[:-1] + (n, n), A.dtype)
    return out.at[..., rows, cols].set(A)


@register_op("linalg_inverse")
def inverse(A):
    return jnp.linalg.inv(A)


@register_op("linalg_det")
def det(A):
    return jnp.linalg.det(A)


@register_op("linalg_slogdet")
def slogdet(A):
    sign, logabs = jnp.linalg.slogdet(A)
    return sign, logabs


@register_op("linalg_svd")
def svd(A):
    """Reference: gesvd — returns (UT, L, V) with A = U diag(L) V."""
    u, s, vh = jnp.linalg.svd(A, full_matrices=False)
    return jnp.swapaxes(u, -1, -2), s, vh


@register_op("linalg_matrix_rank")
def matrix_rank(A, tol=None):
    return jnp.linalg.matrix_rank(A, tol=tol)


@register_op("linalg_norm")
def matrix_norm(A, ord=None, axis=None, keepdims=False):  # noqa: A002
    return jnp.linalg.norm(A, ord=ord, axis=axis, keepdims=keepdims)


@register_op("linalg_solve")
def solve(A, B):
    return jnp.linalg.solve(A, B)


@register_op("linalg_tensorinv")
def tensorinv(A, ind=2):
    return jnp.linalg.tensorinv(A, ind=ind)


@register_op("linalg_tensorsolve")
def tensorsolve(A, B, axes=None):
    return jnp.linalg.tensorsolve(A, B, axes=axes)


@register_op("linalg_cholesky")
def cholesky(A, lower=True):
    L = jnp.linalg.cholesky(A)
    return L if lower else jnp.swapaxes(L, -1, -2)


@register_op("linalg_qr")
def qr(A):
    return jnp.linalg.qr(A, mode="reduced")


@register_op("linalg_eig")
def eig(A):
    # general eig is CPU-only in XLA; documented limitation
    return jnp.linalg.eig(A)


@register_op("linalg_eigh")
def eigh(A, upper=False):
    return jnp.linalg.eigh(A, UPLO="U" if upper else "L")


@register_op("linalg_eigvals")
def eigvals(A):
    return jnp.linalg.eigvals(A)


@register_op("linalg_eigvalsh")
def eigvalsh(A):
    return jnp.linalg.eigvalsh(A)


@register_op("linalg_lstsq")
def lstsq(A, B, rcond=None):
    return jnp.linalg.lstsq(A, B, rcond=rcond)


@register_op("linalg_pinv")
def pinv(A, rcond=None):
    return jnp.linalg.pinv(A, rcond=rcond)


@register_op("linalg_multi_dot")
def multi_dot(*arrays):
    return jnp.linalg.multi_dot(arrays)


@register_op("linalg_matrix_power")
def matrix_power(A, n):
    return jnp.linalg.matrix_power(A, n)


@register_op("linalg_kron")
def kron(a, b):
    return jnp.kron(a, b)


@register_op("linalg_matmul")
def matmul(a, b):
    return jnp.matmul(a, b)
