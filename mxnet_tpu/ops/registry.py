"""Operator registry.

The reference registers 595 ops via NNVM_REGISTER_OP with attrs
(FCompute/FInferShape/FGradient..., include/mxnet/op_attr_types.h). Here an op
is a pure jax function — shape/dtype inference is `jax.eval_shape` (free),
gradients are `jax.vjp` (free), fusion is XLA (free). The registry exists for
discoverability, docs, and the external-extension surface (lib_api.h parity):
third parties can `register_op` a pure function and it becomes available to
the frontends and to CachedOp tracing with autograd support for free.
"""
from __future__ import annotations

_OPS = {}


def register_op(name, fn=None):
    """Register a pure jax function as a named operator."""
    def _do(f):
        _OPS[name] = f
        return f

    if fn is not None:
        return _do(fn)
    return _do


def get_op(name):
    return _OPS[name]


def list_ops():
    return sorted(_OPS)
