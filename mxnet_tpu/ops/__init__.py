"""Pure-jax op implementations — the kernel corpus.

This package is the TPU analog of the reference's `src/operator/` (225k LoC of
C++/CUDA kernels): every function here is a *pure* function of jax arrays,
lowered by XLA onto the MXU/VPU, fused automatically. The NDArray/np frontends
wrap these through `apply_op` for eager+taped execution; Gluon layers call
them directly inside traced forwards.

Layout convention: NCHW/NCW/NCDHW ("channels first"), matching the reference's
default conv/pool layout so model code ports unchanged. XLA transposes
internally to its preferred layout at negligible cost on TPU.
"""
from . import nn  # noqa: F401
from . import tensor  # noqa: F401
from . import linalg  # noqa: F401
from . import vision  # noqa: F401
from . import legacy  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import pallas_attention  # noqa: F401
from .registry import list_ops, register_op, get_op  # noqa: F401
