"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer family (BERT zoo, ring/Ulysses sequence
parallelism): fused QK^T → online-softmax → PV with O(S) memory instead of
materializing the (S, S) score matrix in HBM. Reference framework analog:
the fused attention the reference lacked (its transformer era predated it);
TPU design per /opt/skills/guides/pallas_guide.md — q blocks stay resident
in VMEM, k/v blocks stream through the grid's inner dimension, the MXU sees
(block_q, d) x (d, block_k) matmuls, and the online-softmax running max /
sum live in VMEM scratch across the inner grid steps.

`flash_attention` is differentiable via custom_vjp with a block-streamed
Pallas backward (FlashAttention-2): the forward saves only (out, lse);
backward recomputes P tiles per block from (q, k, lse), so training is
O(S) memory end to end — dQ accumulates over streaming K/V blocks, dK/dV
over streaming Q blocks, and delta = rowsum(dO*O) supplies the softmax
correction.

Falls back to the jnp reference implementation off-TPU; tests run the
kernel in interpret mode for numerics.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = ["flash_attention", "attention_reference"]


_H1 = 0x9E3779B1
_H2 = 0x85EBCA6B
_H3 = 0xC2B2AE35


def _dropout_keep(seed, bh, q_pos, k_pos, dropout_p):
    """Deterministic per-element keep mask: murmur3-finalizer counter
    hash of (seed, batch·head, global q position, global k position).

    Pure uint32 jnp arithmetic, so the SAME mask materializes inside
    Pallas kernel tiles (fwd and both bwd passes), in interpret mode,
    and on the full matrix of the jnp reference path — dropout is
    exactly reproducible across all of them."""
    h = (q_pos.astype(jnp.uint32) * jnp.uint32(_H1)
         + k_pos.astype(jnp.uint32) * jnp.uint32(_H2)
         + jnp.asarray(seed).astype(jnp.uint32)
         + jnp.asarray(bh).astype(jnp.uint32) * jnp.uint32(_H3))
    h = h ^ (h >> 16)
    h = h * jnp.uint32(_H2)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(_H3)
    h = h ^ (h >> 16)
    thresh = jnp.uint32(max(int((1.0 - dropout_p) * 4294967296.0) - 1, 0))
    return h <= thresh


def attention_reference(q, k, v, causal=False, scale=None,
                        dropout_p=0.0, dropout_seed=None):
    """Plain jnp attention (the numeric oracle + off-TPU fallback).
    q/k/v: (B, H, S, D). dropout uses the same counter-hash mask as the
    Pallas kernel, applied to the normalized probabilities (numerator
    only, inverted scaling) — bit-identical semantics to the kernel."""
    b, h, s, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k.astype(q.dtype)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if dropout_p > 0.0:
        bh = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
        q_pos = jnp.arange(s, dtype=jnp.int32).reshape(1, 1, s, 1)
        k_pos = jnp.arange(s, dtype=jnp.int32).reshape(1, 1, 1, s)
        keep = _dropout_keep(dropout_seed, bh, q_pos, k_pos, dropout_p)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref,
                l_ref, acc_ref, *,
                scale, causal, block_q, block_k, valid_len=None,
                dropout_p=0.0):
    import jax.experimental.pallas as pl

    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                     # (block_q, d)
    k = k_ref[0]                                     # (block_k, d)
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # (block_q, block_k)

    if causal or valid_len is not None:
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = jnp.ones(s.shape, bool)
        if causal:
            q_idx = pl.program_id(1)
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            keep &= q_pos >= k_pos
        if valid_len is not None:
            # S was padded up to a tile multiple; padded keys are dead
            keep &= k_pos < valid_len
        s = jnp.where(keep, s, -jnp.inf)

    m_prev = m_ref[:]                                # (block_q, 1)
    l_prev = l_ref[:]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (causal blocks above the diagonal)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(m_new), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    # dropout masks the numerator only (the softmax denominator l stays
    # un-dropped): out = Σ M·p·v / (l·(1−p)) — FlashAttention dropout
    p_v = p
    if dropout_p > 0.0:
        q_idx = pl.program_id(1)
        q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
            jnp.int32, p.shape, 0)
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, p.shape, 1)
        keep = _dropout_keep(seed_ref[0], pl.program_id(0), q_pos, k_pos,
                             dropout_p)
        p_v = jnp.where(keep, p, 0.0)
    acc = acc_ref[:] * alpha + jax.lax.dot_general(
        p_v.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[:] = m_new
    l_ref[:] = l_new
    acc_ref[:] = acc

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:], 1e-30)
        # lse records the TRUE softmax normalizer (backward recomputes
        # p̂ from it); only the output division carries the inverted
        # dropout scale
        o_denom = denom * (1.0 - dropout_p) if dropout_p > 0.0 else denom
        o_ref[0] = (acc_ref[:] / o_denom).astype(o_ref.dtype)
        # logsumexp per row: m + log l (-inf for fully-masked rows).
        # Stored as a (block_q, 1) column — the trailing singleton keeps
        # the block's last two dims (block_q, 1) legal for Mosaic tiling
        # (block_q % 8 == 0; 1 == array dim), where a 2-D (1, block_q)
        # block is not (sublane dim 1 is neither 8-aligned nor full).
        lse_ref[0] = jnp.where(jnp.isfinite(m_ref[:]),
                               m_ref[:] + jnp.log(denom), -jnp.inf)


def _seed_arr(dropout_seed):
    if dropout_seed is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(dropout_seed, jnp.int32).reshape((1,))


def _smem_spec():
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               valid_len=None, dropout_p=0.0, dropout_seed=None):
    import jax.experimental.pallas as pl

    b, h, s_len, d = q.shape
    bh = b * h
    qr = q.reshape(bh, s_len, d)
    kr = k.reshape(bh, s_len, d)
    vr = v.reshape(bh, s_len, d)
    block_q = min(block_q, s_len)
    block_k = min(block_k, s_len)
    grid = (bh, s_len // block_q, s_len // block_k)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, valid_len=valid_len, dropout_p=dropout_p)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, 1)),   # running max m
            _scratch((block_q, 1)),   # running sum l
            _scratch((block_q, d)),   # output accumulator
        ],
        interpret=interpret,
    )(_seed_arr(dropout_seed), qr, kr, vr)
    return out.reshape(b, h, s_len, d), lse[..., 0]


def _scratch(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _recompute_p(q, k, lse_col, scale, causal, q_idx, kv_idx, block_q,
                 block_k, valid_len=None):
    """exp(QK^T * scale - lse) for one (q block, k block) tile.
    lse_col: (block_q, 1) column (see _finish in _fwd_kernel)."""
    import jax.experimental.pallas as pl  # noqa: F401

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal or valid_len is not None:
        k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = jnp.ones(s.shape, bool)
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            keep &= q_pos >= k_pos
        if valid_len is not None:
            keep &= k_pos < valid_len
        s = jnp.where(keep, s, -jnp.inf)
    return jnp.where(jnp.isfinite(lse_col), jnp.exp(s - lse_col), 0.0)


def _tile_keep(seed_ref, bh, q_idx, kv_idx, block_q, block_k, shape,
               dropout_p):
    """Regenerate the forward pass's keep mask for one tile."""
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return _dropout_keep(seed_ref[0], bh, q_pos, k_pos, dropout_p)


def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale, causal, block_q,
                   block_k, valid_len=None, dropout_p=0.0):
    import jax.experimental.pallas as pl

    kv_idx = pl.program_id(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)  # hoisted: program_id inside pl.when
    # bodies breaks interpret mode
    # causal: tiles strictly above the diagonal are all-zero P — skip
    if causal:
        live = kv_idx * block_k <= q_idx * block_q + block_q - 1
    else:
        live = kv_idx >= 0  # always true (traced predicate)
    if valid_len is not None:
        # k tiles entirely inside the padding are all-zero P — skip
        live &= kv_idx * block_k < valid_len

    @pl.when(live)
    def _accum():
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0], scale, causal,
                         q_idx, kv_idx, block_q, block_k, valid_len)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, bk)
        if dropout_p > 0.0:
            # dP̂ = M/(1−p)·(dO V^T); delta already equals
            # rowsum(P̂∘dP̂) because delta = rowsum(dO∘O)
            keep = _tile_keep(seed_ref, bh_idx, q_idx, kv_idx,
                              block_q, block_k, p.shape, dropout_p)
            dp = jnp.where(keep, dp, 0.0) / (1.0 - dropout_p)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale,
                    causal, block_q, block_k, valid_len=None,
                    dropout_p=0.0):
    import jax.experimental.pallas as pl

    q_idx = pl.program_id(2)       # q blocks stream in the inner axis

    @pl.when(q_idx == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    kv_idx = pl.program_id(1)
    bh_idx = pl.program_id(0)  # hoisted: program_id inside pl.when
    # bodies breaks interpret mode
    if causal:
        # q tiles strictly above this k tile's diagonal see zero P
        live = kv_idx * block_k <= q_idx * block_q + block_q - 1
    else:
        live = q_idx >= 0  # always true (traced predicate)
    if valid_len is not None:
        live &= kv_idx * block_k < valid_len

    @pl.when(live)
    def _accum():
        p = _recompute_p(q_ref[0], k_ref[0], lse_ref[0], scale, causal,
                         q_idx, kv_idx, block_q, block_k, valid_len)
        if dropout_p > 0.0:
            keep = _tile_keep(seed_ref, bh_idx, q_idx, kv_idx,
                              block_q, block_k, p.shape, dropout_p)
            p_d = jnp.where(keep, p, 0.0) / (1.0 - dropout_p)
        else:
            keep = None
            p_d = p
        # dV += P_d^T dO (P_d = dropped+rescaled probs, what fwd used)
        dv_acc[:] += jax.lax.dot_general(
            p_d.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp, 0.0) / (1.0 - dropout_p)
        ds = p * (dp - delta_ref[0]) * scale
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
               interpret, valid_len=None, dropout_p=0.0,
               dropout_seed=None):
    """Block-streamed FlashAttention-2 backward: O(S) memory, no (S, S)
    residual — P tiles are recomputed from (q, k, lse) per block (and
    the dropout keep mask from its counter hash)."""
    import jax.experimental.pallas as pl

    b, h, s_len, d = q.shape
    bh = b * h
    block_q = min(block_q, s_len)
    block_k = min(block_k, s_len)
    qr = q.reshape(bh, s_len, d)
    kr = k.reshape(bh, s_len, d)
    vr = v.reshape(bh, s_len, d)
    do = g.reshape(bh, s_len, d)
    orr = out.reshape(bh, s_len, d)
    # delta = rowsum(dO * O) — the softmax-grad correction term (with
    # dropout it still equals rowsum(P̂∘dP̂) since O = P_d V).
    # lse/delta ride as (bh, s_len, 1) columns so their (block_q, 1)
    # blocks satisfy Mosaic's last-two-dims tiling rule.
    delta = jnp.sum(do.astype(jnp.float32) * orr.astype(jnp.float32),
                    axis=-1)[..., None]             # (bh, s_len, 1)
    lse = lse[..., None]                            # (bh, s_len, 1)
    seed = _seed_arr(dropout_seed)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          valid_len=valid_len, dropout_p=dropout_p),
        grid=(bh, s_len // block_q, s_len // block_k),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_len, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        interpret=interpret,
    )(seed, qr, kr, vr, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          valid_len=valid_len, dropout_p=dropout_p),
        grid=(bh, s_len // block_k, s_len // block_q),
        in_specs=[
            _smem_spec(),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s_len, d), v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        interpret=interpret,
    )(seed, qr, kr, vr, do, lse, delta)
    shape = (b, h, s_len, d)
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, seed, causal, scale, block_q, block_k, interpret,
           dropout_p=0.0, valid_len=None):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                        interpret, valid_len, dropout_p, seed)
    return out


def _flash_vjp_fwd(q, k, v, seed, causal, scale, block_q, block_k,
                   interpret, dropout_p=0.0, valid_len=None):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                          interpret, valid_len, dropout_p, seed)
    return out, (q, k, v, seed, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, dropout_p,
                   valid_len, res, g):
    import numpy as _onp

    q, k, v, seed, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q,
                            block_k, interpret, valid_len, dropout_p,
                            seed)
    # integer seed takes a float0 cotangent
    return dq, dk, dv, _onp.zeros(seed.shape, jax.dtypes.float0)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@register_op("flash_attention")
def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128, interpret=None, dropout_p=0.0,
                    dropout_seed=None):
    """Fused multi-head attention: softmax(QK^T * scale) V.

    q/k/v: (B, H, S, D). Runs the Pallas kernel on TPU (or anywhere with
    interpret=True); falls back to the jnp reference otherwise. Ragged S
    is tile-padded and the kernel masks the padded keys (static
    `valid_len`) — only a ragged head dim D takes the reference path.

    dropout_p > 0 with an int32 `dropout_seed` applies attention-prob
    dropout inside the kernel (numerator-masked, inverted scaling; the
    counter-hash mask regenerates identically in the backward kernels
    and the reference path — see _dropout_keep).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    dropout_p = float(dropout_p)

    def _fallback(qq, kk, vv):
        return attention_reference(qq, kk, vv, causal=causal, scale=scale,
                                   dropout_p=dropout_p,
                                   dropout_seed=dropout_seed)

    if interpret is None:
        interpret = False
        platform = jax.devices()[0].platform
        if platform not in ("tpu", "axon"):
            return _fallback(q, k, v)
    if d % 8:
        # ragged head dim: blocks can't stay lane-aligned
        return _fallback(q, k, v)
    s_len = q.shape[2]
    s_pad = _tile_pad_len(s_len, block_q)
    bq = min(block_q, s_pad)
    bk = min(block_k, s_pad)
    if s_pad % bq or s_pad % bk or bq % 8 or bk % 8:
        # non-dividing custom block sizes: reference path
        return _fallback(q, k, v)
    seed = _seed_arr(dropout_seed)
    if s_pad == s_len:
        return _flash(q, k, v, seed, causal, scale, bq, bk, interpret,
                      dropout_p)
    pad = [(0, 0), (0, 0), (0, s_pad - s_len), (0, 0)]
    out = _flash(jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
                 seed, causal, scale, bq, bk, interpret, dropout_p, s_len)
    return out[:, :, :s_len]


def _tile_pad_len(s_len, block):
    """Smallest padded length that tiles: multiple of 8 below one block,
    multiple of the block size above."""
    if s_len >= block:
        return -(-s_len // block) * block
    return -(-s_len // 8) * 8
