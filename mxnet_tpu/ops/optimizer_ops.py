"""Fused optimizer-update ops (reference: src/operator/optimizer_op.cc —
sgd_update, sgd_mom_update, adam_update, lamb_update_phase1/2, ftrl_update,
rmsprop_update, signsgd/signum, adagrad/adadelta, all_finite,
multi_sum_sq; the reference registers optimizer math as engine ops so
updates run fused on-device).

TPU design: each update is one pure jitted function — XLA fuses the whole
rescale→clip→wd→update chain into a single elementwise kernel. State
(momenta etc.) is returned, not mutated; the mx.nd wrappers layer the
reference's in-place-mutation convention on top (ndarray/__init__.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op

__all__ = []


def _prep(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register_op("sgd_update")
def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False):  # noqa: ARG001
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register_op("sgd_mom_update")
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0,
                   lazy_update=False):  # noqa: ARG001
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register_op("nag_mom_update")
def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mom = momentum * mom + g
    return weight - lr * (g + momentum * new_mom), new_mom


@register_op("signsgd_update")
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update")
def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0,
                  wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - (1.0 - momentum) * (g + wd * weight)
    return weight * (1 - lr * wd_lh) + lr * jnp.sign(new_mom), new_mom


@register_op("adam_update")
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=False):  # noqa: ARG001
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return w, new_mean, new_var


@register_op("adamw_update")
def adamw_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """Decoupled weight decay (reference: contrib adamw.cc)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon)
                        + wd * weight)
    return w, new_mean, new_var


@register_op("lamb_update_phase1")
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1 - beta1 ** t)
        v = v / (1 - beta2 ** t)
    update = m / (jnp.sqrt(v) + epsilon) + wd * weight
    return update, new_mean, new_var


@register_op("lamb_update_phase2")
def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    if lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g


@register_op("rmsprop_update")
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n


@register_op("rmspropalex_update")
def rmspropalex_update(weight, grad, n, g_avg, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves' RMSProp variant (reference: rmspropalex_update)."""
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    new_g = gamma1 * g_avg + (1 - gamma1) * g
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon)
    w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, new_n, new_g, new_delta


@register_op("ftrl_update")
def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        0.0,
        -(new_z - jnp.sign(new_z) * lamda1)
        / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, new_z, new_n


@register_op("adagrad_update")
def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_hist = history + jnp.square(g)
    w = weight - lr * (g / jnp.sqrt(new_hist + epsilon) + wd * weight)
    return w, new_hist


@register_op("adadelta_update")
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(new_acc_g + epsilon) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return weight - delta - wd * weight, new_acc_g, new_acc_delta


@register_op("all_finite")
def all_finite(data, init_output=True):  # noqa: ARG001
    """1 if every element is finite (reference: all_finite op used by AMP
    loss-scaler overflow checks)."""
    return jnp.isfinite(data).all().astype(jnp.float32).reshape(1)


@register_op("multi_all_finite")
def multi_all_finite(*arrays, num_arrays=None,
                     init_output=True):  # noqa: ARG001
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.astype(jnp.float32).reshape(1)


@register_op("multi_sum_sq")
def multi_sum_sq(*arrays, num_arrays=None):  # noqa: ARG001
    """Per-array sum of squares (reference: multi_sum_sq.cc — feeds LARS/
    clip-by-global-norm)."""
    return tuple(jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays)


@register_op("adabelief_update")
def adabelief_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                     epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """AdaBelief (reference: contrib/adabelief.cc): variance of the
    prediction error (g - m)^2 instead of g^2."""
    g = _prep(grad, rescale_grad, clip_gradient) + wd * weight
    m = beta1 * mean + (1 - beta1) * g
    s = beta2 * var + (1 - beta2) * jnp.square(g - m) + epsilon
    w = weight - lr * m / (jnp.sqrt(s) + epsilon)
    return w, m, s


@register_op("ftml_update")
def ftml_update(weight, grad, d, v, z, lr, t, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                clip_grad=-1.0):
    """FTML (reference: optimizer_op.cc FTMLUpdate)."""
    g = _prep(grad, rescale_grad, clip_grad) + wd * weight
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (
        jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    w = -z_new / d_new
    return w, d_new, v_new, z_new


@register_op("group_adagrad_update")
def group_adagrad_update(weight, grad, history, lr, rescale_grad=1.0,
                         clip_gradient=-1.0, epsilon=1e-5):
    """Group (row-wise) AdaGrad (reference: contrib/optimizer_op.cc
    _contrib_group_adagrad_update): one accumulator per output row."""
    g = _prep(grad, rescale_grad, clip_gradient)
    axes = tuple(range(1, g.ndim))
    h = history + jnp.mean(jnp.square(g), axis=axes) if g.ndim > 1 \
        else history + jnp.square(g)
    scale = h if g.ndim == 1 else h.reshape(
        (-1,) + (1,) * (g.ndim - 1))
    w = weight - lr * g / (jnp.sqrt(scale) + epsilon)
    return w, h


@register_op("lans_update_phase1")
def lans_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0):
    """LANS phase 1 (reference: contrib/multi_lans.cc): like LAMB but the
    gradient is L2-normalized before the moment updates."""
    g = _prep(grad, rescale_grad, clip_gradient)
    gnorm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g / jnp.maximum(gnorm, 1e-12)
    m = beta1 * mean + (1 - beta1) * g
    v = beta2 * var + (1 - beta2) * jnp.square(g)
    mhat = m / (1 - beta1 ** t)
    vhat = v / (1 - beta2 ** t)
    update_m = mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight
    update_g = g / (jnp.sqrt(vhat) + epsilon) + wd * weight
    return update_m, update_g, m, v


