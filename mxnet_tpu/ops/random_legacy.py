"""Legacy per-row sampling (`_sample_*`) and density (`_random_pdf_*`) op
families (reference: src/operator/random/multisample_op.cc — each row of the
parameter tensors gets `shape` samples drawn with its own parameters — and
src/operator/random/pdf_op.cc — elementwise densities of samples under
per-row parameters, with an `is_log` switch).

TPU re-design: every sampler is a jax.random transform under the framework's
stateful key provider (_random.next_key, the Resource-kRandom analog); the
count distributions (poisson / negative binomial families) use the standard
gamma-Poisson mixture constructions so everything stays vectorized on
device. Densities are closed-form jnp math.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import _random
from .registry import register_op

__all__ = ["install_legacy_random"]


def _unwrap(x):
    data = getattr(x, "_data", None)
    return jnp.asarray(data if data is not None else x)


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def _expand(p, extra):
    """Broadcast per-row params against trailing sample dims."""
    return p.reshape(p.shape + (1,) * extra) if extra else p


def _sampler(name, draw):
    """draw(key, out_shape, *expanded_params) -> samples."""

    def fn(*params, shape=None, dtype=None, **kw):  # noqa: ARG001
        from ..ndarray.ndarray import NDArray

        ps = [_unwrap(p) for p in params]
        S = _shape_tuple(shape)
        out_shape = tuple(ps[0].shape) + S
        ps = [_expand(p, len(S)) for p in ps]
        out = draw(_random.next_key(), out_shape, *ps)
        if dtype is not None and str(dtype) != "None":
            out = out.astype(dtype)
        return NDArray(out)

    fn.__name__ = name
    return fn


def _draw_uniform(key, shape, low, high):
    return low + jax.random.uniform(key, shape, jnp.float32) * (high - low)


def _draw_normal(key, shape, mu, sigma):
    return mu + sigma * jax.random.normal(key, shape, jnp.float32)


def _draw_exponential(key, shape, lam):
    # rate parameterization (reference sample_op.h ExponentialSampler)
    return jax.random.exponential(key, shape, jnp.float32) / lam


def _draw_gamma(key, shape, alpha, beta):
    # alpha = shape, beta = scale (reference GammaSampler)
    return jax.random.gamma(key, jnp.broadcast_to(alpha, shape),
                            dtype=jnp.float32) * beta


def _draw_poisson(key, shape, lam):
    return jax.random.poisson(
        key, jnp.broadcast_to(lam, shape)).astype(jnp.float32)


def _draw_negative_binomial(key, shape, k, p):
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p)) (reference NegativeBinomialSampler)
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, jnp.broadcast_to(k, shape),
                           dtype=jnp.float32) * (1.0 - p) / p
    return jax.random.poisson(kp, lam).astype(jnp.float32)


def _draw_generalized_negative_binomial(key, shape, mu, alpha):
    # GNB(mu, alpha) = Poisson(Gamma(1/alpha, alpha*mu))
    kg, kp = jax.random.split(key)
    a = jnp.broadcast_to(1.0 / jnp.maximum(alpha, 1e-12), shape)
    lam = jax.random.gamma(kg, a, dtype=jnp.float32) * alpha * mu
    return jax.random.poisson(kp, lam).astype(jnp.float32)


def multinomial_logp(p):
    """log of the NORMALIZED probability row `p` (one shared kernel for
    both multinomial entry points — the semantics are delicate): the
    sampler draws from p/sum(p), so the forward value is the true
    log-probability even for unnormalized input, while the VJP matches
    the reference exactly — one-hot/p_raw at sampled classes
    (sample_multinomial_op.h), NO -1/sum term (normalizer gradient
    stopped), and exactly 0 at p==0 classes (double-where safe log; a
    maximum(p, tiny) floor NaNs there because tiny flushes to a 0
    subnormal on TPU)."""
    pos = p > 0
    logz = jax.lax.stop_gradient(
        jnp.log(jnp.sum(p, axis=-1, keepdims=True)))
    return jnp.where(pos, jnp.log(jnp.where(pos, p, 1.0)), -87.0) - logz


def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                        **kw):  # noqa: ARG001
    """_sample_multinomial: rows of probabilities (..., k) -> indices
    (..., *shape) by inverse-CDF (reference sample_multinomial_op.h)."""
    from ..ndarray.ndarray import NDArray

    from .rnn import _battr

    get_prob = _battr(get_prob)
    p = _unwrap(data)
    S = _shape_tuple(shape)
    batch = p.shape[:-1]
    k = p.shape[-1]
    cdf = jnp.cumsum(p, axis=-1)
    cdf = cdf / cdf[..., -1:]                        # tolerate unnormalized
    cdf_e = cdf.reshape(batch + (1,) * len(S) + (k,))
    u = jax.random.uniform(_random.next_key(), batch + S, jnp.float32)
    idx = jnp.sum(u[..., None] >= cdf_e, axis=-1).clip(0, k - 1)
    idx = idx.astype(dtype)
    if not get_prob:
        return NDArray(idx)
    logp = multinomial_logp(p).reshape(batch + (1,) * len(S) + (k,))
    lp = jnp.take_along_axis(
        jnp.broadcast_to(logp, batch + S + (k,)), idx[..., None].astype(
            jnp.int32), axis=-1)[..., 0]
    return NDArray(idx), NDArray(lp)


def _zipfian_draws(u, range_max):
    """Uniform [0,1) draws -> log-uniform classes in [0, range_max), the
    reference kernel exactly: ``lround(exp(u * log(range_max))) - 1``
    (sampler.h LogUniformSampler::Sample — lround is round-half-away-from-
    zero, which for positive values is floor(x + 0.5), NOT numpy's
    banker's rounding)."""
    import math

    import numpy as onp

    raw = onp.floor(
        onp.exp(u * math.log(range_max)) + 0.5).astype(onp.int64) - 1
    # exp() can land exactly on range_max before the -1; clamp like the
    # reference's `% range_max` guard without the wraparound-to-0 bias
    return onp.clip(raw, 0, range_max - 1)


def _sample_unique_zipfian(range_max, shape=None, **kw):  # noqa: ARG001
    """_sample_unique_zipfian: draw `shape[-1]` UNIQUE classes per batch
    row from the log-uniform (Zipfian) distribution — the reference draw
    kernel `lround(exp(u * log(range_max))) - 1` (see _zipfian_draws) —
    counting how many raw draws each row needed (reference: sampler.h
    UniqueSampler +
    random/unique_sample_op.cc — a CPU-only op there too; this sampler
    is host-side numpy by design). Returns (classes, num_trials)."""
    import numpy as onp

    from ..ndarray.ndarray import NDArray

    S = _shape_tuple(shape)
    if len(S) == 1:
        S = (1,) + S
    batch, num_sampled = S
    if num_sampled > range_max:
        raise ValueError(
            f"cannot draw {num_sampled} unique classes from range_max="
            f"{range_max}")
    seed = int(jax.random.randint(_random.next_key(), (), 0, 2**31 - 1))
    rs = onp.random.RandomState(seed)
    classes = onp.empty((batch, num_sampled), onp.int64)
    trials = onp.empty((batch,), onp.int64)
    for i in range(batch):
        draws = onp.empty((0,), onp.int64)
        chunk = max(4 * num_sampled, 1024)
        while True:
            new = _zipfian_draws(rs.random_sample(chunk), range_max)
            draws = onp.concatenate([draws, new])
            uniq, first = onp.unique(draws, return_index=True)
            if uniq.size >= num_sampled:
                # trial count = position of the draw completing the set
                order = onp.sort(first)
                cut = order[num_sampled - 1]
                trials[i] = cut + 1
                keep = first <= cut
                vals, idxs = uniq[keep], first[keep]
                classes[i] = vals[onp.argsort(idxs)]
                break
            chunk *= 2
    return NDArray(jnp.asarray(classes)), NDArray(jnp.asarray(trials))


def _shuffle(data, **kw):  # noqa: ARG001
    """_shuffle: permute along the first axis (reference shuffle_op.cc)."""
    from ..ndarray.ndarray import NDArray

    x = _unwrap(data)
    return NDArray(jax.random.permutation(_random.next_key(), x, axis=0,
                                          independent=False))


# ---- densities (reference src/operator/random/pdf_op.cc) -----------------

def _pdf(name, logpdf, nparams, consumes_last=False):
    def fn(sample, *params, is_log=False, **kw):  # noqa: ARG001
        from ..ndarray.ndarray import NDArray

        s = _unwrap(sample)
        ps = [_unwrap(p) for p in params[:nparams]]
        extra = s.ndim - ps[0].ndim
        if consumes_last:
            # params carry the event axis last (dirichlet alpha (n, k)):
            # sample-dim singletons go BEFORE it, not after
            ps = [p.reshape(p.shape[:-1] + (1,) * extra + p.shape[-1:])
                  if extra else p for p in ps]
        else:
            ps = [_expand(p, extra) for p in ps]
        ll = logpdf(s, *ps)
        return NDArray(ll if is_log else jnp.exp(ll))

    fn.__name__ = name
    return fn


def _lp_uniform(x, low, high):
    inside = (x >= low) & (x <= high)
    return jnp.where(inside, -jnp.log(high - low), -jnp.inf)


def _lp_normal(x, mu, sigma):
    z = (x - mu) / sigma
    return -0.5 * z * z - jnp.log(sigma) - 0.5 * jnp.log(2 * jnp.pi)


def _lp_gamma(x, alpha, beta):
    # shape/scale (matches the sampler above)
    return ((alpha - 1) * jnp.log(x) - x / beta
            - jax.scipy.special.gammaln(alpha) - alpha * jnp.log(beta))


def _lp_exponential(x, lam):
    return jnp.log(lam) - lam * x


def _lp_poisson(x, lam):
    return x * jnp.log(lam) - lam - jax.scipy.special.gammaln(x + 1)


def _lp_negative_binomial(x, k, p):
    return (jax.scipy.special.gammaln(x + k)
            - jax.scipy.special.gammaln(k)
            - jax.scipy.special.gammaln(x + 1)
            + k * jnp.log(p) + x * jnp.log1p(-p))


def _lp_generalized_negative_binomial(x, mu, alpha):
    r = 1.0 / jnp.maximum(alpha, 1e-12)
    p = r / (r + mu)
    return _lp_negative_binomial(x, r, p)


def _lp_dirichlet(x, alpha):
    # x (..., k) consumed; alpha broadcast over the batch dims
    return (jnp.sum((alpha - 1) * jnp.log(x), axis=-1)
            + jax.scipy.special.gammaln(jnp.sum(alpha, axis=-1))
            - jnp.sum(jax.scipy.special.gammaln(alpha), axis=-1))


def install_legacy_random():
    """Register the `_sample_*` / `_random_pdf_*` spellings. Idempotent."""
    from .registry import _OPS

    entries = {
        "_sample_uniform": _sampler("_sample_uniform", _draw_uniform),
        "_sample_normal": _sampler("_sample_normal", _draw_normal),
        "_sample_exponential":
            _sampler("_sample_exponential", _draw_exponential),
        "_sample_gamma": _sampler("_sample_gamma", _draw_gamma),
        "_sample_poisson": _sampler("_sample_poisson", _draw_poisson),
        "_sample_negative_binomial":
            _sampler("_sample_negative_binomial", _draw_negative_binomial),
        "_sample_generalized_negative_binomial":
            _sampler("_sample_generalized_negative_binomial",
                     _draw_generalized_negative_binomial),
        "_sample_multinomial": _sample_multinomial,
        "_sample_unique_zipfian": _sample_unique_zipfian,
        "_shuffle": _shuffle,
        "_random_pdf_uniform": _pdf("_random_pdf_uniform", _lp_uniform, 2),
        "_random_pdf_normal": _pdf("_random_pdf_normal", _lp_normal, 2),
        "_random_pdf_gamma": _pdf("_random_pdf_gamma", _lp_gamma, 2),
        "_random_pdf_exponential":
            _pdf("_random_pdf_exponential", _lp_exponential, 1),
        "_random_pdf_poisson": _pdf("_random_pdf_poisson", _lp_poisson, 1),
        "_random_pdf_negative_binomial":
            _pdf("_random_pdf_negative_binomial", _lp_negative_binomial, 2),
        "_random_pdf_generalized_negative_binomial":
            _pdf("_random_pdf_generalized_negative_binomial",
                 _lp_generalized_negative_binomial, 2),
        "_random_pdf_dirichlet":
            _pdf("_random_pdf_dirichlet", _lp_dirichlet, 1,
                 consumes_last=True),
    }
    for name, fn in entries.items():
        if name not in _OPS:
            register_op(name, fn)
