"""Legacy standalone vision ops (reference: src/operator/bilinear_sampler.cc,
grid_generator.cc, spatial_transformer.cc, roi_pooling.cc, correlation.cc,
contrib/deformable_convolution.cc, crop.cc).

TPU re-design notes: all of these are gather/sample ops. Instead of the
reference's hand-rolled CPU/CUDA loops they are expressed as vectorized
jnp gathers with *static* kernel-position loops (unrolled at trace time), so
XLA fuses each into a handful of HLOs; gradients come from jax.vjp of the
same expressions (the reference hand-writes each backward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

# ---------------------------------------------------------------------------
# bilinear sampling core (shared by BilinearSampler / SpatialTransformer /
# DeformableConvolution) — matches bilinear_sampler.cc: out-of-bounds corner
# samples contribute 0 (`between` checks), coords map (g+1)*(size-1)/2.
# ---------------------------------------------------------------------------


def _bilinear_gather(data, x_real, y_real):
    """Sample data (N,C,H,W) at real-valued pixel coords x_real/y_real
    (N,*spatial), zero outside [-1, size]. Returns (N,C,*spatial)."""
    n, c, h, w = data.shape
    sp = x_real.shape[1:]
    x0 = jnp.floor(x_real).astype(jnp.int32)
    y0 = jnp.floor(y_real).astype(jnp.int32)
    wx1 = x_real - x0  # weight of right sample
    wy1 = y_real - y0  # weight of bottom sample

    def corner(yc, xc, wgt):
        valid = (yc >= 0) & (yc < h) & (xc >= 0) & (xc < w)
        ycl = jnp.clip(yc, 0, h - 1)
        xcl = jnp.clip(xc, 0, w - 1)
        # gather per batch: data (N,C,H,W) indexed at (n, :, ycl[n], xcl[n])
        flat = ycl.reshape(n, -1) * w + xcl.reshape(n, -1)  # (N, S)
        g = jnp.take_along_axis(
            data.reshape(n, c, h * w), flat[:, None, :], axis=2)
        g = g.reshape((n, c) + sp)
        wgt = jnp.where(valid, wgt, 0.0)
        return g * wgt[:, None].astype(data.dtype)

    out = corner(y0, x0, (1 - wy1) * (1 - wx1))
    out = out + corner(y0, x0 + 1, (1 - wy1) * wx1)
    out = out + corner(y0 + 1, x0, wy1 * (1 - wx1))
    out = out + corner(y0 + 1, x0 + 1, wy1 * wx1)
    return out


@register_op("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=None):  # noqa: ARG001
    """Reference: bilinear_sampler.cc. grid (N,2,Ho,Wo) in [-1,1]:
    channel 0 = x, channel 1 = y; coord = (g+1)*(size-1)/2."""
    _, _, h, w = data.shape
    x_real = (grid[:, 0] + 1) * (w - 1) / 2
    y_real = (grid[:, 1] + 1) * (h - 1) / 2
    return _bilinear_gather(data, x_real, y_real)


@register_op("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=None):
    """Reference: grid_generator.cc. affine: data (N,6) -> sampling grid
    (N,2,H,W) in [-1,1]. warp: data = flow (N,2,H,W) added to the identity
    pixel grid, then normalized to [-1,1]."""
    if transform_type == "affine":
        h, w = target_shape
        theta = data.reshape(-1, 2, 3)
        ys, xs = jnp.meshgrid(
            jnp.linspace(-1.0, 1.0, h, dtype=data.dtype),
            jnp.linspace(-1.0, 1.0, w, dtype=data.dtype),
            indexing="ij")
        ones = jnp.ones_like(xs)
        coords = jnp.stack([xs, ys, ones]).reshape(3, -1)  # (3, H*W)
        out = jnp.einsum("nij,jk->nik", theta, coords.astype(data.dtype))
        return out.reshape(-1, 2, h, w)
    # warp
    n, _, h, w = data.shape
    ys, xs = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
    x_new = data[:, 0] + xs.astype(data.dtype)
    y_new = data[:, 1] + ys.astype(data.dtype)
    gx = 2 * x_new / (w - 1) - 1
    gy = 2 * y_new / (h - 1) - 1
    return jnp.stack([gx, gy], axis=1)


@register_op("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):  # noqa: ARG001
    """Reference: spatial_transformer.cc — affine grid from loc (N,6) then
    bilinear sampling."""
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register_op("ROIPooling")
def roi_pooling(data, rois, pooled_size, spatial_scale):
    """Reference: roi_pooling.cc. rois (R,5) = [batch_idx, x1, y1, x2, y2]
    in image coords; max-pool each of pooled_size bins; empty bins -> 0.

    Bin edges follow the reference exactly: rounded roi corners, bin
    [floor(p*bin), ceil((p+1)*bin)) clipped to the feature map. Masked
    separable max keeps the broadcast at (R,C,H,PW,W) rather than
    materializing a 6-d corner tensor.
    """
    _, c, h, w = data.shape
    ph, pw = pooled_size
    batch_ind = rois[:, 0].astype(jnp.int32)
    roi_start_w = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
    roi_start_h = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
    roi_end_w = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)
    roi_end_h = jnp.round(rois[:, 4] * spatial_scale).astype(jnp.int32)
    roi_h = jnp.maximum(roi_end_h - roi_start_h + 1, 1).astype(jnp.float32)
    roi_w = jnp.maximum(roi_end_w - roi_start_w + 1, 1).astype(jnp.float32)
    bin_h = roi_h / ph  # (R,)
    bin_w = roi_w / pw

    pidx_h = jnp.arange(ph, dtype=jnp.float32)
    pidx_w = jnp.arange(pw, dtype=jnp.float32)
    hstart = jnp.floor(pidx_h[None] * bin_h[:, None]).astype(jnp.int32) \
        + roi_start_h[:, None]                      # (R, PH)
    hend = jnp.ceil((pidx_h[None] + 1) * bin_h[:, None]).astype(jnp.int32) \
        + roi_start_h[:, None]
    wstart = jnp.floor(pidx_w[None] * bin_w[:, None]).astype(jnp.int32) \
        + roi_start_w[:, None]
    wend = jnp.ceil((pidx_w[None] + 1) * bin_w[:, None]).astype(jnp.int32) \
        + roi_start_w[:, None]
    hstart = jnp.clip(hstart, 0, h)
    hend = jnp.clip(hend, 0, h)
    wstart = jnp.clip(wstart, 0, w)
    wend = jnp.clip(wend, 0, w)

    hs = jnp.arange(h)
    ws = jnp.arange(w)
    mask_h = (hs[None, None] >= hstart[..., None]) \
        & (hs[None, None] < hend[..., None])        # (R, PH, H)
    mask_w = (ws[None, None] >= wstart[..., None]) \
        & (ws[None, None] < wend[..., None])        # (R, PW, W)

    gathered = data[batch_ind]                      # (R, C, H, W)
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    gf = gathered.astype(jnp.float32)
    # reduce W:   (R,C,H,PW)
    tw = jnp.max(jnp.where(mask_w[:, None, None], gf[:, :, :, None, :], neg),
                 axis=-1)
    # reduce H:   (R,C,PH,PW)
    out = jnp.max(jnp.where(mask_h[:, None, :, :, None],
                            tw[:, :, None], neg), axis=-2)
    out = jnp.where(jnp.isfinite(out), out, 0.0)
    return out.astype(data.dtype)


@register_op("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Reference: correlation.cc. FlowNet-style patch correlation; output
    channel = displacement index over a (2r+1)^2 grid, r =
    max_displacement//stride2; each value averages over kernel window and
    input channels (sumelems = k*k*C)."""
    n, c, h, w = data1.shape
    k = kernel_size
    kr = (k - 1) // 2
    border = max_displacement + kr
    ph_, pw_ = h + 2 * pad_size, w + 2 * pad_size
    top_h = -(-(ph_ - 2 * border) // stride1)
    top_w = -(-(pw_ - 2 * border) // stride1)
    r = max_displacement // stride2
    gw = 2 * r + 1

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    sumelems = k * k * c
    outs = []
    for di in range(-r, r + 1):
        for dj in range(-r, r + 1):
            s2p, s2o = di * stride2, dj * stride2
            acc = 0.0
            for hh in range(-kr, kr + 1):
                for ww in range(-kr, kr + 1):
                    a = jax.lax.dynamic_slice(
                        p1, (0, 0, max_displacement + hh + kr,
                             max_displacement + ww + kr),
                        (n, c, ph_ - 2 * border, pw_ - 2 * border))
                    b = jax.lax.dynamic_slice(
                        p2, (0, 0, max_displacement + hh + kr + s2p,
                             max_displacement + ww + kr + s2o),
                        (n, c, ph_ - 2 * border, pw_ - 2 * border))
                    acc = acc + (a * b if is_multiply else jnp.abs(a - b))
            acc = jnp.sum(acc, axis=1) / sumelems  # (N, H', W')
            outs.append(acc[:, ::stride1, ::stride1][:, :top_h, :top_w])
    return jnp.stack(outs, axis=1)


@register_op("DeformableConvolution")
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_deformable_group=1, groups=1, mask=None):
    """Reference: contrib/deformable_convolution.cc (DCNv1), and with
    `mask` the modulated DCNv2 variant (contrib ModulatedDeformableConvolution):
    mask (N, k*k*G, Ho, Wo) multiplies each tap's bilinear sample.

    offset (N, 2*k*k*G, Ho, Wo) gives per-output-position (dy, dx) for each
    kernel tap. Implemented as k*k bilinear gathers (static unroll) + one
    einsum contraction onto the MXU — no im2col buffer.
    """
    n, c, h, w = data.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    ho = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    wo = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    g = num_deformable_group
    cg = c // g

    ys = jnp.arange(ho) * sh - ph
    xs = jnp.arange(wo) * sw - pw
    base_y, base_x = jnp.meshgrid(ys, xs, indexing="ij")  # (Ho, Wo)

    cols = []  # per kernel tap: (N, C, Ho, Wo)
    for ki in range(kh):
        for kj in range(kw):
            tap = ki * kw + kj
            dy = offset[:, 2 * tap::2 * kh * kw]        # (N, G, Ho, Wo)
            dx = offset[:, 2 * tap + 1::2 * kh * kw]
            m = mask[:, tap::kh * kw] if mask is not None else None
            samples = []
            for gi in range(g):
                y_real = base_y[None] + ki * dh + dy[:, gi]
                x_real = base_x[None] + kj * dw + dx[:, gi]
                sub = data[:, gi * cg:(gi + 1) * cg]
                samp = _bilinear_gather(
                    sub, x_real.astype(jnp.float32),
                    y_real.astype(jnp.float32))
                if m is not None:
                    samp = samp * m[:, gi:gi + 1]
                samples.append(samp)
            cols.append(jnp.concatenate(samples, axis=1))
    col = jnp.stack(cols, axis=2)  # (N, C, k*k, Ho, Wo)
    wmat = weight.reshape(weight.shape[0], weight.shape[1], kh * kw)
    if groups == 1:
        out = jnp.einsum("nckhw,ock->nohw", col, wmat)
    else:
        og = weight.shape[0] // groups
        outs = []
        for gi in range(groups):
            outs.append(jnp.einsum(
                "nckhw,ock->nohw",
                col[:, gi * (c // groups):(gi + 1) * (c // groups)],
                wmat[gi * og:(gi + 1) * og]))
        out = jnp.concatenate(outs, axis=1)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@register_op("Crop")
def crop(data, crop_like=None, offset=(0, 0), h_w=(0, 0), center_crop=False):
    """Reference: crop.cc (v1 op). Crop H/W either to `h_w` or to match
    `crop_like`'s spatial shape; offset or center anchoring."""
    _, _, h, w = data.shape
    if crop_like is not None:
        th, tw = crop_like.shape[2], crop_like.shape[3]
    else:
        th, tw = h_w
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = offset
    return data[:, :, oy:oy + th, ox:ox + tw]
