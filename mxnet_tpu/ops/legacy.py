"""Legacy CamelCase op names with reference call signatures.

The reference's v1 symbol/ndarray API spells NN ops CamelCase with
attribute-style kwargs (`nd.Convolution(data, weight, bias, kernel=(3,3),
num_filter=64, ...)` — src/operator/nn/convolution.cc param struct). These
adapters accept that surface and forward to the pure TPU ops, so
reference-era scripts resolve against mx.nd/mx.sym unchanged.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import nn as _nn
from . import tensor as _tensor
from .registry import register_op


@register_op("Convolution")
def Convolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                dilate=None, num_filter=None, num_group=1, no_bias=False,
                workspace=None, cudnn_tune=None, cudnn_off=None,
                layout=None):  # noqa: ARG001, N802
    if no_bias:
        bias = None
    nd = data.ndim - 2
    return _nn.conv(data, weight, bias, stride=stride or (1,) * nd,
                    pad=pad or (0,) * nd, dilate=dilate or (1,) * nd,
                    groups=num_group)


@register_op("Deconvolution")
def Deconvolution(data, weight, bias=None, kernel=None, stride=None, pad=None,
                  dilate=None, adj=None, target_shape=None, num_filter=None,
                  num_group=1, no_bias=True, workspace=None,
                  cudnn_tune=None, cudnn_off=None, layout=None):  # noqa: ARG001, N802
    if no_bias:
        bias = None
    nd = data.ndim - 2
    return _nn.conv_transpose(
        data, weight, bias, stride=stride or (1,) * nd, pad=pad or (0,) * nd,
        dilate=dilate or (1,) * nd, output_padding=adj or (0,) * nd,
        groups=num_group)


@register_op("FullyConnected")
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):  # noqa: N802
    return _nn.dense(data, weight, bias, flatten=flatten,
                     num_hidden=num_hidden, no_bias=no_bias)


@register_op("Pooling")
def Pooling(data, kernel=(2, 2), pool_type="max", stride=None, pad=None,
            global_pool=False, pooling_convention="valid",
            count_include_pad=True, cudnn_off=None, p_value=None,
            layout=None):  # noqa: ARG001, N802
    return _nn.pool(data, kernel, pool_type=pool_type, stride=stride, pad=pad,
                    global_pool=global_pool,
                    count_include_pad=count_include_pad)


@register_op("BatchNorm")
def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=None,
              min_calib_range=None, max_calib_range=None):  # noqa: ARG001, N802
    if fix_gamma:
        gamma = jnp.ones_like(gamma)
    out, nm, nv = _nn.batch_norm(
        data, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, training=not use_global_stats,
        use_global_stats=use_global_stats, axis=axis)
    if output_mean_var:
        return out, nm, nv
    return out


@register_op("LayerNorm")
def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5,
              output_mean_var=False):  # noqa: N802
    out = _nn.layer_norm(data, gamma, beta, axis=axis, eps=eps)
    if output_mean_var:
        mean = jnp.mean(data, axis=axis, keepdims=True)
        var = jnp.var(data, axis=axis, keepdims=True)
        return out, mean, var
    return out


@register_op("InstanceNorm")
def InstanceNorm(data, gamma, beta, eps=1e-3):  # noqa: N802
    return _nn.instance_norm(data, gamma, beta, eps=eps)


@register_op("L2Normalization")
def L2Normalization(data, eps=1e-10, mode="instance"):  # noqa: N802
    return _nn.l2_normalization(data, eps=eps, mode=mode)


@register_op("Activation")
def Activation(data, act_type="relu"):  # noqa: N802
    return _nn.activation(data, act_type)


@register_op("LeakyReLU")
def LeakyReLU(data, gamma=None, act_type="leaky", slope=0.25,
              lower_bound=None, upper_bound=None):  # noqa: ARG001, N802
    return _nn.leaky_relu(data, gamma, act_type=act_type, slope=slope)


@register_op("SoftmaxActivation")
def SoftmaxActivation(data, mode="instance"):  # noqa: N802
    """Reference: nn/softmax_activation.cc (deprecated alias of softmax)."""
    if mode == "channel":
        return _nn.softmax(data, axis=1)
    return _nn.softmax(data, axis=-1)


@register_op("Embedding")
def Embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):  # noqa: ARG001, N802
    return _nn.embedding(data, weight)


@register_op("Concat")
def Concat(*data, dim=1, num_args=None):  # noqa: ARG001, N802
    return _tensor.concat(*data, dim=dim)


@register_op("Flatten")
def Flatten(data):  # noqa: N802
    return _tensor.flatten(data)


@register_op("Reshape")
def Reshape(data, shape=None, reverse=False, target_shape=None,
            keep_highest=False):  # noqa: ARG001, N802
    return _tensor.reshape(data, shape=shape, reverse=reverse)


@register_op("Cast")
def Cast(data, dtype):  # noqa: N802
    return _tensor.cast(data, dtype)


@register_op("SwapAxis")
def SwapAxis(data, dim1=0, dim2=1):  # noqa: N802
    return _tensor.swapaxes(data, dim1, dim2)


@register_op("SequenceLast")
def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0):  # noqa: N802
    return _nn.sequence_last(data, sequence_length, use_sequence_length, axis)


@register_op("SequenceMask")
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):  # noqa: N802
    return _nn.sequence_mask(data, sequence_length, use_sequence_length,
                             value, axis)


@register_op("SequenceReverse")
def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0):  # noqa: N802
    return _nn.sequence_reverse(data, sequence_length, use_sequence_length,
                                axis)


@register_op("UpSampling")
def UpSampling(*data, scale=2, sample_type="nearest", num_args=None,
               num_filter=None, multi_input_mode=None,
               workspace=None):  # noqa: ARG001, N802
    return _nn.upsample(data[0], scale=scale, sample_type=sample_type)


@register_op("LRN")
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):  # noqa: N802
    return _nn.lrn(data, nsize=nsize, alpha=alpha, beta=beta, knorm=knorm)


@register_op("SliceChannel")
def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False):  # noqa: N802
    return _tensor.split(data, num_outputs, axis=axis,
                         squeeze_axis=squeeze_axis)


@register_op("Pad")
def Pad(data, mode="constant", pad_width=None, constant_value=0.0):  # noqa: N802
    return _tensor.pad(data, mode=mode, pad_width=pad_width,
                       constant_value=constant_value)


@register_op("Dropout")
def Dropout(data, key=None, p=0.5, mode="training", axes=None,
            cudnn_off=None):  # noqa: ARG001, N802
    """Needs an explicit key when training (the eager facade injects one)."""
    if key is None:
        return data
    return _nn.dropout(data, key, p=p, training=True, axes=axes)


@register_op("IdentityAttachKLSparseReg")
def IdentityAttachKLSparseReg(data, sparseness_target=0.1, penalty=0.001,
                              momentum=0.9):  # noqa: ARG001, N802
    """Reference: identity_attach_KL_sparse_reg.cc — forward identity."""
    return data
