"""Tensor-op corpus: the reference's `src/operator/tensor/` family as pure
jax functions with legacy MXNet semantics.

Covers elemwise unary/binary (elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc), broadcast_* (elemwise_binary_broadcast_op_*.cc),
reductions with `exclude` (broadcast_reduce_op_value.cc), ordering
(ordering_op.cc), indexing (indexing_op.cc, ravel.cc), matrix/shape
manipulation incl. legacy reshape codes 0/-1/-2/-3/-4
(matrix_op.cc:Reshape), dot/batch_dot (dot.cc), and the loss-output ops with
their reference gradient quirks (SoftmaxOutput's out-label backward,
MakeLoss, BlockGrad — src/operator/softmax_output.cc, make_loss.cc).

Everything here is shape-static and jit-safe; gradients come from jax.vjp
except where the reference defines a *different* backward (custom_vjp).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

# ---------------------------------------------------------------------------
# unary elemwise (reference: elemwise_unary_op_basic.cc, *_trig.cc, *_pow.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "round": jnp.round,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "reciprocal": lambda x: 1.0 / x,
    "negative": jnp.negative,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": lambda x: jax.scipy.special.gammaln(x),
    "digamma": lambda x: jax.scipy.special.digamma(x),
    "erf": lambda x: jax.scipy.special.erf(x),
    "erfinv": lambda x: jax.scipy.special.erfinv(x),
    "sigmoid": jax.nn.sigmoid,
    "log_sigmoid": jax.nn.log_sigmoid,
    "softsign": jax.nn.soft_sign,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}

for _name, _fn in _UNARY.items():
    register_op(_name, _fn)
globals().update(_UNARY)


@register_op("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    """Reference: elemwise_unary_op_basic.cc hard_sigmoid (alpha*x+beta clipped)."""
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


# ---------------------------------------------------------------------------
# binary elemwise + broadcast_* (reference: elemwise_binary_broadcast_op_*.cc)
# ---------------------------------------------------------------------------

_BINARY = {
    "elemwise_add": jnp.add,
    "elemwise_sub": jnp.subtract,
    "elemwise_mul": jnp.multiply,
    "elemwise_div": jnp.divide,
    "broadcast_add": jnp.add,
    "broadcast_plus": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_minus": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "hypot": jnp.hypot,
}

_BINARY_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}


def _cmp(fn):
    # reference comparison ops return the lhs dtype (0/1 valued), not bool
    def wrapped(lhs, rhs):
        return fn(lhs, rhs).astype(getattr(lhs, "dtype", jnp.float32))
    wrapped.__name__ = fn.__name__
    return wrapped


for _name, _fn in _BINARY.items():
    register_op(_name, _fn)
    globals()[_name] = _fn
for _name, _fn in _BINARY_CMP.items():
    globals()[_name] = register_op(_name, _cmp(_fn))


@register_op("add_n")
def add_n(*args):
    """Sum of n arrays (reference: elemwise_sum.cc add_n)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register_op("smooth_l1")
def smooth_l1(data, scalar=1.0):
    """Reference: elemwise_unary_op_basic.cc smooth_l1 with sigma=scalar."""
    s2 = scalar * scalar
    a = jnp.abs(data)
    return jnp.where(a < 1.0 / s2, 0.5 * s2 * jnp.square(data), a - 0.5 / s2)


# ---------------------------------------------------------------------------
# reductions (reference: broadcast_reduce_op_value.cc — axis/keepdims/exclude)
# ---------------------------------------------------------------------------


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(i for i in range(ndim) if i not in axis)
    return axis


def _reduce(jfn, name):
    def fn(data, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(axis, data.ndim, exclude)
        return jfn(data, axis=ax, keepdims=keepdims)
    fn.__name__ = name
    return register_op(name, fn)


sum = _reduce(jnp.sum, "sum")  # noqa: A001
nansum = _reduce(jnp.nansum, "nansum")
prod = _reduce(jnp.prod, "prod")
nanprod = _reduce(jnp.nanprod, "nanprod")
mean = _reduce(jnp.mean, "mean")
max = _reduce(jnp.max, "max")  # noqa: A001
min = _reduce(jnp.min, "min")  # noqa: A001
sum_axis = register_op("sum_axis", sum)
max_axis = register_op("max_axis", max)
min_axis = register_op("min_axis", min)


@register_op("norm")
def norm(data, ord=2, axis=None, keepdims=False):  # noqa: A002
    """Reference: broadcast_reduce_norm_value.cc (L1/L2 over axis or all)."""
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@register_op("argmax")
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)  # reference returns float indices


@register_op("argmin")
def argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register_op("argmax_channel")
def argmax_channel(data):
    """Reference: broadcast_reduce_op_index.cc — argmax over axis 1."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# ordering (reference: ordering_op.cc)
# ---------------------------------------------------------------------------


@register_op("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register_op("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype=jnp.float32):
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(dtype)


# ---------------------------------------------------------------------------
# indexing (reference: indexing_op.cc, ravel.cc, init_op.cc)
# ---------------------------------------------------------------------------


@register_op("take")
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    n = a.shape[axis]
    if mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # wrap
        idx = idx % n
    return jnp.take(a, idx, axis=axis)


@register_op("batch_take")
def batch_take(a, indices):
    """Per-row gather (reference: indexing_op.cc batch_take): out[i] = a[i, idx[i]]."""
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register_op("gather_nd")
def gather_nd(data, indices):
    """Reference: indexing_op.cc gather_nd. indices (M, ...) selects along the
    first M dims of data."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register_op("scatter_nd")
def scatter_nd(data, indices, shape):
    """Reference: indexing_op.cc scatter_nd (last write wins; here add-free set)."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register_op("ravel_multi_index")
def ravel_multi_index(data, shape):
    """Reference: ravel.cc. data (ndim, n) of coords -> flat indices (n,)."""
    idx = data.astype(jnp.int32)
    out = jnp.zeros(idx.shape[1:], jnp.int32)
    for i, s in enumerate(shape):
        out = out * s + idx[i]
    return out.astype(jnp.float32)


@register_op("unravel_index")
def unravel_index(data, shape):
    idx = data.astype(jnp.int32)
    coords = []
    for s in reversed(shape):
        coords.append(idx % s)
        idx = idx // s
    return jnp.stack(coords[::-1]).astype(jnp.float32)


@register_op("diag")
def diag(data, k=0, axis1=0, axis2=1):
    if data.ndim == 1:
        return jnp.diag(data, k=k)
    return jnp.diagonal(data, offset=k, axis1=axis1, axis2=axis2)


@register_op("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


# ---------------------------------------------------------------------------
# shape manipulation (reference: matrix_op.cc)
# ---------------------------------------------------------------------------


def legacy_reshape_shape(src, target, reverse=False):
    """Resolve MXNet Reshape special codes (matrix_op-inl.h InferReshapeShape):
    0 copy-dim, -1 infer, -2 copy-rest, -3 merge-two, -4 split (a,b)."""
    src = list(src)
    target = list(target)
    if reverse:
        src = src[::-1]
        target = target[::-1]
    out = []
    i = 0  # position in src
    j = 0
    while j < len(target):
        t = target[j]
        if t == 0:
            out.append(src[i])
            i += 1
        elif t == -1:
            out.append(-1)
            i += 1
        elif t == -2:
            out.extend(src[i:])
            i = len(src)
        elif t == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif t == -4:
            # NB: under reverse=True the operands are read from the REVERSED
            # target, exactly like the reference (matrix_op-inl.h
            # InferReshapeShape reverses param_shape_vec then reads ++i).
            if j + 2 >= len(target):
                raise ValueError(
                    "-4 needs two following entries in the (possibly "
                    f"reversed) target shape, got {target[j:]}")
            a, b = target[j + 1], target[j + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            if a * b != src[i]:
                raise ValueError(
                    f"split dims ({a}, {b}) do not divide source dim "
                    f"{src[i]}")
            out.extend([a, b])
            i += 1
            j += 2
        else:
            out.append(t)
            i += 1
        j += 1
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        total = 1
        for d in src:
            total *= d
        out[out.index(-1)] = total // known
    if reverse:
        out = out[::-1]
    return tuple(out)


@register_op("reshape")
def reshape(data, shape=None, reverse=False):
    return jnp.reshape(data, legacy_reshape_shape(data.shape, shape, reverse))


@register_op("reshape_like")
def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None):
    if lhs_begin is None and rhs_begin is None:
        return jnp.reshape(lhs, rhs.shape)
    lb = lhs_begin or 0
    le = lhs_end if lhs_end is not None else lhs.ndim
    rb = rhs_begin or 0
    re = rhs_end if rhs_end is not None else rhs.ndim
    new = lhs.shape[:lb] + rhs.shape[rb:re] + lhs.shape[le:]
    return jnp.reshape(lhs, new)


@register_op("flatten")
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register_op("transpose")
def transpose(data, axes=None):
    return jnp.transpose(data, axes=axes or None)


@register_op("expand_dims")
def expand_dims(data, axis):
    return jnp.expand_dims(data, axis)


@register_op("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register_op("slice")
def slice(data, begin, end, step=None):  # noqa: A001
    """Reference: matrix_op.cc slice — None entries mean full range."""
    import builtins
    step = step or (None,) * len(begin)
    idx = tuple(builtins.slice(b, e, s) for b, e, s in zip(begin, end, step))
    idx = idx + (builtins.slice(None),) * (data.ndim - len(idx))
    return data[idx]


@register_op("slice_axis")
def slice_axis(data, axis, begin, end):
    import builtins
    if end is None:
        end = data.shape[axis]
    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register_op("slice_like")
def slice_like(data, shape_like, axes=None):
    import builtins
    idx = [builtins.slice(None)] * data.ndim
    axes = axes if axes else range(builtins.min(data.ndim, shape_like.ndim))
    for ax in axes:
        idx[ax] = builtins.slice(0, shape_like.shape[ax])
    return data[tuple(idx)]


@register_op("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


@register_op("repeat")
def repeat(data, repeats, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register_op("tile")
def tile(data, reps):
    return jnp.tile(data, reps)


@register_op("reverse")
def reverse(data, axis=0):
    return jnp.flip(data, axis=axis)


flip = register_op("flip", reverse)


@register_op("histogram")
def histogram(data, bins=None, bin_cnt=None, range=None):  # noqa: A002
    """Reference histogram op (tensor/histogram.cc): int bin count needs
    an explicit range; an array `bins` gives the edges. Returns
    (counts int64, bin_edges)."""
    import numbers

    import numpy as onp

    if bins is not None and not isinstance(bins, numbers.Integral):
        cnt, edges = jnp.histogram(data, bins=bins)
        return cnt.astype(jnp.int64), edges
    n = bin_cnt if bin_cnt is not None else (bins or 10)
    if range is None:
        raise ValueError(
            "histogram with an integer bin count requires range= "
            "(reference histogram.cc contract)")
    # edges from static (n, range) at float64 on the host so they match
    # numpy's bit-for-bit, then cast to the input dtype (histogram.cc
    # computes edges at the input's precision)
    edges = jnp.asarray(
        onp.linspace(range[0], range[1], int(n) + 1), data.dtype)
    cnt, _ = jnp.histogram(data, bins=edges)
    return cnt.astype(jnp.int64), edges


@register_op("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] — row-wise pick with (float) indices
    (reference: src/operator/tensor/broadcast_reduce_op_index.cc legacy
    op used by RL/ranking examples)."""
    idx = rhs.astype(jnp.int32)
    return jnp.take_along_axis(lhs, idx[:, None], axis=1)[:, 0]


@register_op("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """Functional lhs[i, rhs[i]] = mhs[i] (reference: the mutating
    legacy op; XLA scatter here)."""
    idx = rhs.astype(jnp.int32)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


@register_op("shape_array")
def shape_array(data):
    # int64 is the reference contract (matrix_op.cc shape_array)
    return jnp.asarray(data.shape, jnp.int64)


@register_op("size_array")
def size_array(data):
    return jnp.asarray([data.size], jnp.int64)


@register_op("cast")
def cast(data, dtype):
    return data.astype(dtype)


@register_op("swapaxes")
def swapaxes(data, dim1=0, dim2=1):
    return jnp.swapaxes(data, dim1, dim2)


@register_op("depth_to_space")
def depth_to_space(data, block_size):
    """Reference: depth_to_space in matrix_op.cc (DCR mode, NCHW)."""
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register_op("space_to_depth")
def space_to_depth(data, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("stack")
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register_op("concat")
def concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


@register_op("split")
def split(data, num_outputs, axis=1, squeeze_axis=False):
    """Reference: SliceChannel (slice_channel.cc)."""
    outs = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs)


@register_op("pad")
def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    """Reference: pad.cc — pad_width is the flat (before, after) per-dim list."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1])
          for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    return jnp.pad(data, pw, mode="edge")


@register_op("broadcast_to")
def broadcast_to(data, shape):
    shape = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, shape)


@register_op("broadcast_axis")
def broadcast_axis(data, axis=None, size=None):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


broadcast_axes = register_op("broadcast_axes", broadcast_axis)


# ---------------------------------------------------------------------------
# dot family (reference: dot.cc, la_op gemm lives in ops/linalg.py)
# ---------------------------------------------------------------------------


@register_op("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2) if lhs.ndim > 1 else lhs
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2) if rhs.ndim > 1 else rhs
    return jnp.dot(lhs, rhs)


@register_op("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register_op("khatri_rao")
def khatri_rao(*args):
    """Column-wise Kronecker product (reference: contrib krprod.cc)."""
    out = args[0]
    for b in args[1:]:
        out = jnp.einsum("ik,jk->ijk", out, b).reshape(-1, out.shape[1])
    return out


# ---------------------------------------------------------------------------
# cumulative / windowed
# ---------------------------------------------------------------------------


@register_op("cumsum")
def cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis, dtype=dtype)


# ---------------------------------------------------------------------------
# loss-output ops with reference gradient semantics (custom_vjp)
# ---------------------------------------------------------------------------


@register_op("BlockGrad")
def stop_gradient(data):
    """Reference: elemwise_unary_op_basic.cc BlockGrad/stop_gradient."""
    return lax.stop_gradient(data)


register_op("stop_gradient", stop_gradient)


@jax.custom_vjp
def _make_loss(data, grad_scale):
    return data


def _make_loss_fwd(data, grad_scale):
    return data, (data, grad_scale)


def _make_loss_bwd(res, g):  # noqa: ARG001
    data, grad_scale = res
    return jnp.full_like(data, grad_scale), None


_make_loss.defvjp(_make_loss_fwd, _make_loss_bwd)


@register_op("make_loss")
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):  # noqa: ARG001
    """Reference: make_loss.cc — forward identity, backward = grad_scale
    (independent of upstream gradient)."""
    return _make_loss(data, grad_scale)


register_op("MakeLoss", make_loss)


@jax.custom_vjp
def _softmax_output(data, label, grad_scale, ignore_label, use_ignore):
    return jax.nn.softmax(data, axis=1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore):
    out = jax.nn.softmax(data, axis=1)
    return out, (out, label, grad_scale, ignore_label, use_ignore)


def _softmax_output_bwd(res, g):  # noqa: ARG001
    out, label, grad_scale, ignore_label, use_ignore = res
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, out.shape[1], dtype=out.dtype)
    if out.ndim > 2:  # (N, C, ...) — move class axis
        onehot = jnp.moveaxis(onehot, -1, 1)
    grad = out - onehot
    if use_ignore:
        keep = (lab != int(ignore_label)).astype(out.dtype)
        keep = keep.reshape((out.shape[0],) + (1,) * (out.ndim - 1)) \
            if out.ndim == 2 else jnp.expand_dims(keep, 1)
        grad = grad * keep
    return grad * grad_scale, None, None, None, None


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register_op("SoftmaxOutput")
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1,
                   use_ignore=False, multi_output=False,  # noqa: ARG001
                   normalization="null", **kwargs):  # noqa: ARG001
    """Reference: softmax_output.cc — forward softmax, backward (p - onehot)
    regardless of upstream gradient (it IS the loss layer)."""
    return _softmax_output(data, label, grad_scale, ignore_label, use_ignore)


register_op("softmax_output", softmax_output)


def _regression_op(fwd_fn, grad_fn):
    """Reference: regression_output.cc — the output IS the loss layer, so the
    backward is grad_fn(pred, label) * grad_scale, ignoring upstream grads."""

    @jax.custom_vjp
    def op(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        out = fwd_fn(data)
        return out, (out, label, grad_scale)

    def bwd(res, g):  # noqa: ARG001
        out, label, grad_scale = res
        return grad_fn(out, label) * grad_scale, None, None

    op.defvjp(fwd, bwd)
    return op


_linear_reg = _regression_op(lambda x: x, lambda p, y: p - y)
_logistic_reg = _regression_op(jax.nn.sigmoid, lambda p, y: p - y)
_mae_reg = _regression_op(lambda x: x, lambda p, y: jnp.sign(p - y))


@register_op("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    return _linear_reg(data, label, grad_scale)


@register_op("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    return _logistic_reg(data, label, grad_scale)


@register_op("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    return _mae_reg(data, label, grad_scale)


@register_op("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Reference: svm_output.cc — forward identity; backward hinge-loss grad."""

    @jax.custom_vjp
    def _svm(data, label):
        return data

    def _fwd(data, label):
        return data, (data, label)

    def _bwd(res, g):  # noqa: ARG001
        x, lab = res
        onehot = jax.nn.one_hot(lab.astype(jnp.int32), x.shape[1],
                                dtype=x.dtype)
        y = 2.0 * onehot - 1.0  # +1 for true class, -1 otherwise
        viol = (margin - y * x) > 0
        if use_linear:
            grad = jnp.where(viol, -y * regularization_coefficient, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * regularization_coefficient
                             * (margin - y * x) * y, 0.0)
        return grad.astype(x.dtype), None

    _svm.defvjp(_fwd, _bwd)
    return _svm(data, label)


@register_op("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Reference: loss_binary_op.cc — scalar summed CE."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked)


@register_op("trace")
def trace(data, offset=0, axis1=0, axis2=1):
    """Reference: np_trace_op.cc."""
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@register_op("broadcast_like")
def broadcast_like(lhs, rhs):
    """Reference: broadcast_reduce_op_value.cc broadcast_like."""
    return jnp.broadcast_to(lhs, rhs.shape)


@register_op("arange_like")
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """Reference: tensor/init_op.cc _contrib_arange_like — each value is
    emitted `repeat` times before advancing by `step`."""
    n = data.shape[axis] if axis is not None else data.size
    count = -(-n // repeat) if repeat > 1 else n
    out = jnp.arange(count, dtype=jnp.float32) * step + start
    if repeat > 1:
        out = jnp.repeat(out, repeat)[:n]
    if axis is None:
        return out.reshape(data.shape)
    return out


@register_op("relu6")
def relu6(data):
    return jnp.clip(data, 0.0, 6.0)



@register_op("mish")
def mish(data):
    return data * jnp.tanh(jax.nn.softplus(data))


@register_op("silu")
def silu(data):
    return jax.nn.silu(data)


@register_op("im2col")
def im2col(data, kernel, stride=None, dilate=None, pad=None):
    """Sliding-window patch extraction (reference: src/operator/nn/im2col.h
    semantics, registered as `im2col` in matrix ops): (N, C, H, W) ->
    (N, C*prod(kernel), L) column matrix. Lowered via XLA's
    conv_general_dilated_patches — MXU/VPU friendly, no gather loops."""
    from jax import lax as _lax

    nd = data.ndim - 2
    if isinstance(kernel, int):
        kernel = (kernel,) * nd
    stride = stride or (1,) * nd
    dilate = dilate or (1,) * nd
    pad = pad or (0,) * nd
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilate, int):
        dilate = (dilate,) * nd
    if isinstance(pad, int):
        pad = (pad,) * nd
    patches = _lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate))
    n = patches.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


@register_op("col2im")
def col2im(data, output_size, kernel, stride=None, dilate=None, pad=None):
    """Inverse of im2col: scatter-add columns back onto the image
    (reference: col2im in src/operator/nn/im2col.h). Implemented as the
    vjp of im2col — exact adjoint by construction."""
    import jax as _jax

    nd = len(output_size)
    if isinstance(kernel, int):
        kernel = (kernel,) * nd
    c = data.shape[1] // 1
    for k in kernel:
        c //= k
    img_shape = (data.shape[0], c) + tuple(output_size)
    _, vjp = _jax.vjp(
        lambda img: im2col(img, kernel, stride=stride, dilate=dilate,
                           pad=pad), jnp.zeros(img_shape, data.dtype))
    return vjp(data)[0]
