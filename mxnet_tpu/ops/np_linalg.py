"""Reference-convention numpy-linalg impls, shared by the mx.np.linalg
frontend AND the `_npi_*` op registry (a hybridized/serialized graph that
resolves `_npi_svd` must produce the same numerics as the imperative
call).

Conventions per the reference docstrings (python/mxnet/numpy/linalg.py):
  * svd (linalg.py:729): gesvd ``(ut, s, v)``, ``v: (..., M, N)`` —
    numpy's *reduced* SVD, not the full_matrices default.
  * eigh/eigvalsh (linalg.py:1336,1466): bool ``upper``, triangle
    actually honored (jnp's symmetrize_input default would average it
    away).
  * matrix_rank/pinv (linalg.py:35,510): ``rtol``/``hermitian`` kwargs.
  * lstsq (linalg.py:438): default ``rcond='warn'`` = legacy
    machine-eps cutoff (numpy rcond=-1), numpy-style residuals.
  * eig/eigvals (linalg.py:1398-1447): real-in/real-out host LAPACK
    geev via pure_callback (TPU-safe under jit); no gradient, like the
    reference (src/operator/numpy/linalg/np_eig.cc registers no
    backward) — forward works under autograd, backward raises.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as _onp

__all__ = ["svd", "eigh", "eigvalsh", "matrix_rank", "lstsq",
           "eig", "eigvals"]


def svd(a):
    return tuple(jnp.linalg.svd(a, full_matrices=False))


def eigh(a, upper=False):
    return tuple(jnp.linalg.eigh(a, UPLO="U" if upper else "L",
                                 symmetrize_input=False))


def eigvalsh(a, upper=False):
    return jnp.linalg.eigvalsh(a, UPLO="U" if upper else "L",
                               symmetrize_input=False)


def matrix_rank(M, rtol=None, hermitian=False):
    s = jnp.abs(jnp.linalg.eigvalsh(M)) if hermitian \
        else jnp.linalg.svdvals(M)
    if rtol is None:
        cut = (jnp.max(s, axis=-1, keepdims=True)
               * max(M.shape[-2:]) * jnp.finfo(s.dtype).eps)
    else:
        # array-api allows per-matrix rtol of shape (...,): append the
        # reduced axis so it broadcasts against s:(..., K)
        cut = (jnp.max(s, axis=-1, keepdims=True)
               * jnp.asarray(rtol)[..., None])
    return jnp.sum(s > cut, axis=-1)


def lstsq(a, b, rcond="warn"):
    if isinstance(rcond, str):
        if rcond == "warn":
            rcond = -1  # reference default = legacy machine-eps cutoff
        else:
            # the packed FFI ships attrs as strings — a numeric string
            # is a real tolerance, not the legacy sentinel
            try:
                rcond = float(rcond)
            except ValueError:
                raise ValueError(
                    f"rcond must be a number, None, or 'warn'; got "
                    f"{rcond!r}") from None
    return tuple(jnp.linalg.lstsq(a, b, rcond=rcond, numpy_resid=True))


def _geev(compute_v, a):
    a = jnp.asarray(a)
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(jnp.float32)
    w_shape = jax.ShapeDtypeStruct(a.shape[:-1], a.dtype)
    if compute_v:
        def host(x):
            w, v = _onp.linalg.eig(_onp.asarray(x))
            return (w.real.astype(x.dtype), v.real.astype(x.dtype))

        return tuple(jax.pure_callback(
            host, (w_shape, jax.ShapeDtypeStruct(a.shape, a.dtype)),
            a, vmap_method="sequential"))

    def host(x):
        return _onp.linalg.eigvals(_onp.asarray(x)).real.astype(x.dtype)

    return jax.pure_callback(host, w_shape, a, vmap_method="sequential")


# custom_vjp so the forward traces under autograd/jax.vjp (pure_callback
# has no JVP rule); the backward itself raises, matching the reference's
# missing np_eig gradient.
@jax.custom_vjp
def eig(a):
    return _geev(True, a)


def _eig_fwd(a):
    return eig(a), None


def _eig_bwd(_res, _g):
    raise NotImplementedError(
        "np.linalg.eig has no gradient (reference np_eig.cc registers "
        "no backward)")


eig.defvjp(_eig_fwd, _eig_bwd)


@jax.custom_vjp
def eigvals(a):
    return _geev(False, a)


def _eigvals_fwd(a):
    return eigvals(a), None


def _eigvals_bwd(_res, _g):
    raise NotImplementedError(
        "np.linalg.eigvals has no gradient (reference np_eig.cc "
        "registers no backward)")


eigvals.defvjp(_eigvals_fwd, _eigvals_bwd)
