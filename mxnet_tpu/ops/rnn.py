"""Fused multi-layer RNN operator over the cuDNN-canonical flat parameter
blob (reference: src/operator/rnn.cc NNVM_REGISTER_OP(RNN), rnn-inl.h
GetRnnParamSize:176 / GetRnnBiasSize:208, rnn_impl.h
LstmForwardInferenceSingleLayer — wx then wh per layer/direction, all
biases bx,bh packed after every weight).

TPU re-design: each (layer, direction) is a `lax.scan` over time — the
per-step x@W dot is hoisted out of the scan (one big (T*N, I)x(I, G*H)
matmul on the MXU, like the reference's single pre-GEMM), leaving only the
recurrent h@R dot inside the scan body.  Gate order matches the reference
(LSTM [i, f, g, o], GRU [r, z, n]) so parameter blobs translate directly.

The op computes inference-mode semantics (`p` dropout between layers is a
training-time concern handled by gluon.rnn's layers); outputs mirror the
reference: `out` or (out, state_h[, state_cell]) when state_outputs=True.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

__all__ = ["rnn_fused", "rnn_param_size", "slice_rnn_params"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _battr(v):
    """Parse a boolean attr that may arrive as a serialized string (symbol
    JSON round-trips attrs as text; must agree with the nout lambdas in
    symbol/register.py)."""
    if isinstance(v, str):
        return v not in ("False", "0", "None", "false", "")
    return bool(v)


def rnn_param_size(num_layers, input_size, state_size, bidirectional=False,
                   mode="lstm", projection_size=None):
    """Total flat parameter count (reference rnn-inl.h GetRnnParamSize)."""
    D = 2 if bidirectional else 1
    G = _GATES[mode]
    size = G * state_size * D
    P = projection_size
    if P:
        size1 = (input_size + P + 2) * size
        size2 = (P * D + P + 2) * size
        total = size1 + (num_layers - 1) * size2
        total += P * state_size * num_layers * D
    else:
        size1 = (input_size + state_size + 2) * size
        size2 = (state_size * D + state_size + 2) * size
        total = size1 + (num_layers - 1) * size2
    return int(total)


def slice_rnn_params(w, mode, num_layers, input_size, state_size,
                     bidirectional=False, projection_size=None):
    """Split the flat blob into per-(layer, direction) weight dicts.

    Layout (reference rnn-inl.h / rnn_impl.h): for each layer, for each
    direction: wx (G*H, in_l), wh (G*H, P or H)[, whr (P, H)]; then, for
    each layer/direction again: bx (G*H,), bh (G*H,).
    """
    D = 2 if bidirectional else 1
    G = _GATES[mode]
    H = state_size
    P = projection_size or 0
    R = P or H                      # recurrent width
    out = []
    off = 0

    def take(n, shape):
        nonlocal off
        v = w[off:off + n].reshape(shape)
        off += n
        return v

    for layer in range(num_layers):
        in_l = input_size if layer == 0 else R * D
        for _d in range(D):
            blk = {"wx": take(G * H * in_l, (G * H, in_l)),
                   "wh": take(G * H * R, (G * H, R))}
            if P:
                blk["whr"] = take(P * H, (P, H))
            out.append(blk)
    for i in range(num_layers * D):
        out[i]["bx"] = take(G * H, (G * H,))
        out[i]["bh"] = take(G * H, (G * H,))
    return out


def _cell_step(mode, clip=None):
    def step_rnn_relu(h, c, pre_x, pre_h):  # noqa: ARG001
        h_new = jax.nn.relu(pre_x + pre_h)
        return h_new, c

    def step_rnn_tanh(h, c, pre_x, pre_h):  # noqa: ARG001
        h_new = jnp.tanh(pre_x + pre_h)
        return h_new, c

    def step_lstm(h, c, pre_x, pre_h):  # noqa: ARG001
        i, f, g, o = jnp.split(pre_x + pre_h, 4, axis=-1)
        i, f, o = (jax.nn.sigmoid(v) for v in (i, f, o))
        c_new = f * c + i * jnp.tanh(g)
        if clip is not None:
            # cuDNN-style cell clipping: c is clipped every step, BEFORE
            # h is computed from it (reference rnn-inl.h state_clip)
            c_new = jnp.clip(c_new, clip[0], clip[1])
        return o * jnp.tanh(c_new), c_new

    def step_gru(h, c, pre_x, pre_h):  # noqa: ARG001
        ir, iz, in_ = jnp.split(pre_x, 3, axis=-1)
        hr, hz, hn = jnp.split(pre_h, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(in_ + r * hn)
        return (1 - z) * n + z * h, c

    return {"rnn_relu": step_rnn_relu, "rnn_tanh": step_rnn_tanh,
            "lstm": step_lstm, "gru": step_gru}[mode]


def _run_direction(x, h0, c0, blk, mode, reverse, clip=None):
    """One (layer, direction): x (T, N, in) -> (y (T, N, R), h_T, c_T)."""
    step = _cell_step(mode, clip)
    # hoist the input projection out of the scan: one big MXU matmul
    pre_x = jnp.einsum("tni,gi->tng", x, blk["wx"]) + blk["bx"]
    if reverse:
        pre_x = pre_x[::-1]
    whr = blk.get("whr")

    def body(carry, px):
        h, c = carry
        pre_h = h @ blk["wh"].T + blk["bh"]
        h_new, c_new = step(h, c, px, pre_h)
        if whr is not None:                       # LSTMP projection
            h_new = h_new @ whr.T
        return (h_new, c_new), h_new

    (h_t, c_t), ys = jax.lax.scan(body, (h0, c0), pre_x)
    if reverse:
        ys = ys[::-1]
    return ys, h_t, c_t


def rnn_fused(data, parameters, state, state_cell=None, *, state_size,
              num_layers, mode="lstm", bidirectional=False, p=0.0,
              state_outputs=False, projection_size=None,
              lstm_state_clip_min=None, lstm_state_clip_max=None,
              **ignored):  # noqa: ARG001
    """RNN op: data (T, N, I), parameters flat (S,), state (L*D, N, R)
    [, state_cell (L*D, N, H) for lstm] -> out (T, N, D*R)
    [+ (state_h, state_cell) when state_outputs].

    State index layout matches the reference: idx = layer * D + direction.
    """
    mode = str(mode)
    if mode not in _GATES:
        raise ValueError(f"unknown RNN mode {mode!r}")
    state_outputs = _battr(state_outputs)
    bidirectional = _battr(bidirectional)
    x = jnp.asarray(data)
    w = jnp.asarray(parameters).reshape(-1)
    hx = jnp.asarray(state)
    D = 2 if bidirectional else 1
    L = int(num_layers)
    H = int(state_size)
    P = int(projection_size) if projection_size else 0
    T, N, I = x.shape
    blks = slice_rnn_params(w, mode, L, I, H, bidirectional, P or None)

    if mode == "lstm":
        if state_cell is None:
            raise ValueError("lstm mode needs state_cell")
        cx = jnp.asarray(state_cell)
    else:
        cx = jnp.zeros((L * D, N, H), x.dtype)

    clip = None
    if mode == "lstm" and lstm_state_clip_min is not None:
        clip = (float(lstm_state_clip_min), float(lstm_state_clip_max))
    hy, cy = [], []
    for layer in range(L):
        ys = []
        for d in range(D):
            idx = layer * D + d
            y, h_t, c_t = _run_direction(
                x, hx[idx], cx[idx], blks[idx], mode, reverse=bool(d),
                clip=clip)
            ys.append(y)
            hy.append(h_t)
            cy.append(c_t)
        x = ys[0] if D == 1 else jnp.concatenate(ys, axis=-1)

    out = x
    if not state_outputs:
        return out
    state_h = jnp.stack(hy)
    if mode == "lstm":
        return out, state_h, jnp.stack(cy)
    return out, state_h


register_op("RNN", rnn_fused)
