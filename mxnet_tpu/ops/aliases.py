"""Reference-internal op-name aliases.

The reference's generated frontends call ops by their NNVM-internal
spellings — `_npi_add`, `_contrib_box_iou`, `_plus_scalar`, `_image_resize`,
`mp_sgd_update` (python/mxnet/ndarray/register.py codegen over the 595-name
registry). Users touch the public spellings, but reference-era extensions,
exported symbol graphs, and the packed FFI resolve the internal ones; this
module registers each internal name onto the SAME implementation the public
spelling uses, so both vocabularies land in one registry.

Skipped on purpose (backend-specific ops with no TPU meaning, not stubs):
`_sg_onednn_*` (oneDNN subgraph fusions — XLA fuses instead), `_TensorRT`,
`_FusedOp*` (NVRTC pointwise fusion), `_contrib_tvm_*`, `_contrib_intgemm_*`
(CPU int8 gemm — XLA int8 dot path is contrib.quantization).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import _OPS, register_op

__all__ = ["install_aliases"]


def _swap(fn):
    return lambda a, b, **kw: fn(b, a, **kw)


def _install_round1():
    """Round 1: npi/npx/contrib/image/optimizer internals."""
    if "_npi_add" in _OPS:
        return

    from .. import numpy as mxnp
    from ..contrib import dgl as cdgl
    from ..contrib import ops as cops
    from . import nn as _nn  # noqa: F401 - ensures base ops registered
    from .registry import get_op

    def reg(name, fn):
        if name not in _OPS and fn is not None:
            register_op(name, fn)

    def raw(fn):
        """Unwrap a frontend function into a jax-level callable."""
        return getattr(fn, "__wrapped__", fn)

    # ---- legacy elemwise/scalar internals (src/operator/tensor/
    # elemwise_binary_*scalar*.cc) -------------------------------------
    j = jnp
    scalar_map = {
        "_plus_scalar": j.add, "_minus_scalar": j.subtract,
        "_rminus_scalar": _swap(j.subtract), "_mul_scalar": j.multiply,
        "_div_scalar": j.divide, "_rdiv_scalar": _swap(j.divide),
        "_mod_scalar": j.mod, "_rmod_scalar": _swap(j.mod),
        "_power_scalar": j.power, "_rpower_scalar": _swap(j.power),
        "_maximum_scalar": j.maximum, "_minimum_scalar": j.minimum,
        "_hypot_scalar": j.hypot,
        "_equal_scalar": j.equal, "_not_equal_scalar": j.not_equal,
        "_greater_scalar": j.greater,
        "_greater_equal_scalar": j.greater_equal,
        "_lesser_scalar": j.less, "_lesser_equal_scalar": j.less_equal,
        "_logical_and_scalar": j.logical_and,
        "_logical_or_scalar": j.logical_or,
        "_logical_xor_scalar": j.logical_xor,
        "_equal": j.equal, "_not_equal": j.not_equal,
        "_greater": j.greater, "_greater_equal": j.greater_equal,
        "_lesser": j.less, "_lesser_equal": j.less_equal,
        "_logical_and": j.logical_and, "_logical_or": j.logical_or,
        "_logical_xor": j.logical_xor,
        "_mod": j.mod, "_copy": j.asarray, "_grad_add": j.add,
        "_eye": j.eye, "_histogram": j.histogram,
        "_zeros_without_dtype": j.zeros,
        "_scatter_set_nd": None,  # covered by scatter_nd in registry
        "_square_sum": lambda x, **kw: j.sum(j.square(x), **kw),
        "_identity_with_attr_like_rhs": lambda lhs, rhs: lhs,
        "_np_reshape": lambda x, newshape, **kw: j.reshape(x, newshape),
        "_split_v2": j.split,
    }
    for name, fn in scalar_map.items():
        reg(name, fn)

    # ---- _npi_* numpy internals (src/operator/numpy/, 139 names) -----
    npi_direct = [
        "add", "subtract", "multiply", "true_divide", "mod", "power",
        "floor_divide", "copysign", "arctan2", "hypot", "ldexp",
        "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
        "bitwise_left_shift", "bitwise_right_shift", "gcd", "lcm",
        "fmax", "fmin", "fmod", "logaddexp", "all", "any", "arange",
        "argmax", "argmin", "around", "atleast_1d", "atleast_2d",
        "atleast_3d", "average", "bincount", "blackman", "hamming",
        "hanning", "broadcast_to", "column_stack", "copy", "cross",
        "cumsum", "deg2rad", "rad2deg", "delete", "diag", "diagflat",
        "diagonal", "diff", "dot", "dsplit", "dstack", "ediff1d",
        "einsum", "eye", "flip", "full", "full_like", "hsplit",
        "hstack", "identity", "indices", "interp", "kron", "linspace", "geomspace",
        "logspace", "log", "matmul", "max", "mean", "min", "moveaxis",
        "nan_to_num", "ones", "pad", "percentile", "polyval", "prod",
        "repeat", "roll", "rollaxis", "rot90", "squeeze", "std", "sum",
        "tensordot", "trace", "transpose", "tri", "tril", "triu",
        "tril_indices", "unique", "var", "vstack", "where", "zeros",
        "split",
    ]
    for nm in npi_direct:
        fn = getattr(mxnp, nm, None) or getattr(jnp, nm, None)
        reg(f"_npi_{nm}", raw(fn) if fn is not None else None)
    # scalar/reversed-scalar spellings share the tensor implementation
    for nm, fn in {
        "add": j.add, "subtract": j.subtract, "multiply": j.multiply,
        "true_divide": j.divide, "mod": j.mod, "power": j.power,
        "floor_divide": j.floor_divide, "copysign": j.copysign,
        "arctan2": j.arctan2, "ldexp": None, "gcd": j.gcd,
        "lcm": j.lcm, "fmax": j.fmax, "fmin": j.fmin, "fmod": j.fmod,
        "logaddexp": j.logaddexp, "bitwise_and": j.bitwise_and,
        "bitwise_or": j.bitwise_or, "bitwise_xor": j.bitwise_xor,
        "bitwise_left_shift": j.left_shift,
        "bitwise_right_shift": j.right_shift,
    }.items():
        if fn is None:
            continue
        reg(f"_npi_{nm}_scalar", fn)
        reg(f"_npi_r{nm}_scalar", _swap(fn))
    reg("_npi_rtrue_divide_scalar", _swap(j.divide))
    reg("_npi_rsubtract_scalar", _swap(j.subtract))
    reg("_npi_rpower_scalar", _swap(j.power))
    reg("_npi_rmod_scalar", _swap(j.mod))
    reg("_npi_rfloor_divide_scalar", _swap(j.floor_divide))
    reg("_npi_rfmod_scalar", _swap(j.fmod))
    reg("_npi_rldexp_scalar", None)
    reg("_npi_rarctan2_scalar", _swap(j.arctan2))
    reg("_npi_rcopysign_scalar", _swap(j.copysign))

    # linalg (src/operator/numpy/linalg/) — reference-convention impls
    # shared with mx.np.linalg (ops/np_linalg.py) so graph-resolved
    # `_npi_svd` etc. match the imperative frontend numerics
    from . import np_linalg as _npla

    la = {
        "cholesky": jnp.linalg.cholesky, "eig": _npla.eig,
        "eigh": _npla.eigh, "eigvals": _npla.eigvals,
        "eigvalsh": _npla.eigvalsh, "svd": _npla.svd,
        "qr": jnp.linalg.qr, "solve": jnp.linalg.solve,
        "pinv": jnp.linalg.pinv, "lstsq": _npla.lstsq,
        "tensorinv": jnp.linalg.tensorinv,
        "tensorsolve": jnp.linalg.tensorsolve,
        "matrix_rank": _npla.matrix_rank, "norm": jnp.linalg.norm,
    }
    for nm, fn in la.items():
        reg(f"_npi_{nm}", fn)
    reg("_npi_pinv_scalar_rcond", jnp.linalg.pinv)
    reg("_npi_matrix_rank_none_tol", _npla.matrix_rank)

    # random (src/operator/numpy/random/): stateful frontend fns
    rnd = mxnp.random
    for nm in ("normal", "uniform", "gamma", "exponential", "laplace",
               "gumbel", "logistic", "pareto", "rayleigh", "weibull",
               "bernoulli", "choice", "multinomial"):
        reg(f"_npi_{nm}", getattr(rnd, nm, None))
    reg("_npi_normal_n", getattr(rnd, "normal", None))
    reg("_npi_uniform_n", getattr(rnd, "uniform", None))
    reg("_npi_powerd", getattr(rnd, "power", None))

    # ---- _npx_* extensions -------------------------------------------
    from .. import numpy_extension as npx

    for nm in ("cond", "foreach", "while_loop", "reshape", "nonzero",
               "index_add", "index_update", "constraint_check"):
        reg(f"_npx_{nm}", raw(getattr(npx, nm, None)))

    # ---- _contrib_* --------------------------------------------------
    contrib_map = {
        "AdaptiveAvgPooling2D": cops.adaptive_avg_pooling,
        "BilinearResize2D": cops.bilinear_resize_2d,
        "BatchNormWithReLU": None,  # layer-level: nn.BatchNormReLU
        "MultiBoxPrior": cops.multibox_prior,
        "MultiBoxTarget": cops.multibox_target,
        "MultiBoxDetection": cops.multibox_detection,
        "ROIAlign": cops.roi_align,
        "SyncBatchNorm": None,      # layer-level: nn.SyncBatchNorm
        "allclose": cops.allclose,
        "bipartite_matching": cops.bipartite_matching,
        "boolean_mask": cops.boolean_mask,
        "box_iou": cops.box_iou, "box_nms": cops.box_nms,
        "div_sqrt_dim": cops.div_sqrt_dim,
        "dynamic_reshape": cops.dynamic_reshape,
        "edge_id": cdgl.edge_id,
        "dgl_adjacency": cdgl.dgl_adjacency,
        "dgl_csr_neighbor_uniform_sample":
            cdgl.dgl_csr_neighbor_uniform_sample,
        "dgl_csr_neighbor_non_uniform_sample":
            cdgl.dgl_csr_neighbor_non_uniform_sample,
        "dgl_subgraph": cdgl.dgl_subgraph,
        "dgl_graph_compact": cdgl.dgl_graph_compact,
        "getnnz": cops.getnnz,
        "gradientmultiplier": cops.gradientmultiplier,
        "hawkesll": cops.hawkes_ll,
        "index_array": cops.index_array,
        "index_copy": cops.index_copy,
        "interleaved_matmul_selfatt_qk":
            cops.interleaved_matmul_selfatt_qk,
        "interleaved_matmul_selfatt_valatt":
            cops.interleaved_matmul_selfatt_valatt,
        "interleaved_matmul_encdec_qk":
            cops.interleaved_matmul_encdec_qk,
        "interleaved_matmul_encdec_valatt":
            cops.interleaved_matmul_encdec_valatt,
        "quadratic": cops.quadratic,
        "round_ste": cops.round_ste, "sign_ste": cops.sign_ste,
    }
    for nm, fn in contrib_map.items():
        reg(f"_contrib_{nm}", fn)

    # quantization internals (contrib/quantization.py jitted pieces)
    from ..contrib import quantization as q

    for nm, fn in {
        "quantize": getattr(q, "quantize", None),
        "quantize_v2": getattr(q, "quantize_v2", None),
        "dequantize": getattr(q, "dequantize", None),
        "requantize": getattr(q, "requantize", None),
        "calibrate_entropy": getattr(q, "_entropy_threshold", None),
    }.items():
        reg(f"_contrib_{nm}", fn)

    # ---- _image_* (src/operator/image/) ------------------------------
    from ..gluon.data.vision import transforms as T
    from ..image import image as img

    reg("_image_resize", raw(getattr(img, "imresize", None)))
    image_map = {
        "_image_crop": getattr(img, "fixed_crop", None),
        "_image_to_tensor": lambda x: jnp.transpose(
            jnp.asarray(x, jnp.float32) / 255.0, (2, 0, 1)),
        "_image_normalize": lambda x, mean, std: (
            (jnp.asarray(x) - jnp.asarray(mean)[:, None, None])
            / jnp.asarray(std)[:, None, None]),
        "_image_random_crop": getattr(img, "random_crop", None),
        "_image_random_resized_crop": getattr(img, "random_size_crop",
                                              None),
    }
    for nm, fn in image_map.items():
        reg(nm, fn)
    del T

    # ---- optimizer update internals ----------------------------------
    # mp_* (multi-precision: fp32 master weights) and multi_*/preloaded_*
    # (multi-tensor batches) share the single-tensor rules; on TPU the
    # batching win comes from jit fusing the update loop, so the batched
    # spellings dispatch per-tensor to the same registered rule.
    def _mp(name):
        base = get_op(name) if name in _OPS else None
        if base is None:
            return None

        def mp_update(weight, grad, *states_and_w32, **kw):
            *states, weight32 = states_and_w32
            out = base(weight32, grad, *states, **kw)
            if isinstance(out, tuple):
                new_w32 = out[0]
                return (new_w32.astype(weight.dtype), *out[1:], new_w32)
            return out.astype(weight.dtype), out

        return mp_update

    for nm in ("sgd_update", "sgd_mom_update", "nag_mom_update",
               "adamw_update", "lamb_update_phase1", "adabelief_update"):
        if nm in _OPS:
            reg(f"mp_{nm}", _mp(nm))
    reg("_mp_adamw_update", _OPS.get("mp_adamw_update"))
    reg("_mp_adabelief_update", _OPS.get("mp_adabelief_update"))
    reg("mp_lamb_update_phase1", _OPS.get("mp_lamb_update_phase1"))
    reg("mp_lamb_update_phase2", _OPS.get("lamb_update_phase2"))
    reg("_adabelief_update", _OPS.get("adabelief_update"))

    def _multi(base_name, n_states, preloaded=False):
        base = _OPS.get(base_name)
        if base is None:
            return None

        def multi_update(*args, num_weights=None, lrs=None, wds=None,
                         **kw):
            args = list(args)
            if preloaded:
                # preloaded_* convention: lrs/wds are the two TRAILING
                # tensor arguments (src/operator/contrib/
                # preloaded_multi_sgd-inl.h)
                wds = args.pop()
                lrs = args.pop()
            group = n_states + 2
            n = int(num_weights) if num_weights else len(args) // group
            outs = []
            for i in range(n):
                tensors = args[i * group:(i + 1) * group]
                kwi = dict(kw)
                if lrs is not None:
                    kwi["lr"] = lrs[i] if hasattr(lrs, "__len__") else lrs
                if wds is not None:
                    kwi["wd"] = wds[i] if hasattr(wds, "__len__") else wds
                outs.append(base(*tensors, **kwi))
            return tuple(outs)

        return multi_update

    for base_name, n_states, spellings in (
            ("sgd_update", 0, ["multi_sgd_update"]),
            ("sgd_mom_update", 1, ["multi_sgd_mom_update"]),
            ("mp_sgd_update", 1, ["multi_mp_sgd_update"]),
            ("mp_sgd_mom_update", 2, ["multi_mp_sgd_mom_update"]),
            ("adamw_update", 2, ["_multi_adamw_update"]),
            ("mp_adamw_update", 3, ["_multi_mp_adamw_update"]),
            ("adabelief_update", 2, ["_multi_adabelief_update"]),
            ("mp_adabelief_update", 3, ["_multi_mp_adabelief_update"]),
            ("lamb_update_phase1", 2, ["_multi_lamb_update"]),
            ("mp_lamb_update_phase1", 3, ["_multi_mp_lamb_update"]),
    ):
        fn = _multi(base_name, n_states)
        for sp in spellings:
            reg(sp, fn)
    for base_name, n_states, sp in (
            ("sgd_update", 0, "preloaded_multi_sgd_update"),
            ("sgd_mom_update", 1, "preloaded_multi_sgd_mom_update"),
            ("mp_sgd_update", 1, "preloaded_multi_mp_sgd_update"),
            ("mp_sgd_mom_update", 2,
             "preloaded_multi_mp_sgd_mom_update"),
    ):
        reg(sp, _multi(base_name, n_states, preloaded=True))
    reg("multi_lars", cops.multi_lars)
    reg("reset_arrays", cops.reset_arrays)
    reg("multi_sum_sq", cops.multi_sum_sq)

    # remaining odds and ends
    from ..ndarray import sparse as _sparse

    reg("cast_storage", _sparse.cast_storage)
    reg("_sparse_retain", getattr(_sparse, "retain", None))
    reg("amp_cast", lambda x, dtype: jnp.asarray(x).astype(dtype))
    def _amp_multicast(*xs, num_outputs=None, cast_narrow=False):  # noqa: ARG001
        dts = [jnp.asarray(v).dtype for v in xs]
        if cast_narrow:
            target = min(dts, key=lambda d: jnp.dtype(d).itemsize)
        else:
            target = jnp.result_type(*dts)
        return tuple(jnp.asarray(x).astype(target) for x in xs)

    reg("amp_multicast", _amp_multicast)
    reg("_rnn_param_concat",
        lambda *xs, dim=0, **kw: jnp.concatenate(
            [jnp.asarray(x).reshape(-1) for x in xs]))


def _install_round2():
    """Second alias round: sldwin attention, box codec, optimizer rules,
    and the remaining _npi/_npx odds and ends."""
    import jax.numpy as j

    from .. import numpy as mxnp
    from .. import numpy_extension as npx
    from ..contrib import ops as cops
    from ..gluon import loss as gloss

    def reg(name, fn):
        if name not in _OPS and fn is not None:
            register_op(name, fn)

    def raw(fn):
        return getattr(fn, "__wrapped__", fn)

    for nm in ("sldwin_atten_score", "sldwin_atten_mask_like",
               "sldwin_atten_context", "box_decode", "box_encode"):
        fn = getattr(cops, nm)
        reg(f"_contrib_{nm}", fn)
        reg(f"_npx_{nm}", fn)
    reg("_contrib_arange_like", raw(npx.arange_like))
    reg("_contrib_group_adagrad_update", _OPS.get("group_adagrad_update"))
    reg("_sparse_adagrad_update", _OPS.get("adagrad_update"))
    reg("_adabelief_update", _OPS.get("adabelief_update"))
    reg("_mp_adabelief_update", _OPS.get("adabelief_update"))
    reg("_multi_lans_update", _OPS.get("lans_update_phase1"))
    reg("_multi_mp_lans_update", _OPS.get("lans_update_phase1"))

    # CTCLoss op spelling over the loss implementation. Padding value is
    # 0 for blank_label='first', -1 for 'last' (ctc_loss-inl.h:346); the
    # blank class is 0 or alphabet_size-1 respectively (:370).
    def ctc_loss(data, label, data_lengths=None, label_lengths=None,
                 use_data_lengths=False, use_label_lengths=False,
                 blank_label="first"):  # noqa: ARG001
        first = blank_label == "first"
        alphabet = data.shape[-1]  # NDArray or jax array alike
        lossfn = gloss.CTCLoss(layout="TNC", label_layout="NT",
                               padding_value=0 if first else -1,
                               blank_id=0 if first else alphabet - 1)
        return lossfn(data, label,
                      data_lengths if use_data_lengths else None,
                      label_lengths if use_label_lengths else None)

    reg("CTCLoss", ctc_loss)
    reg("ctc_loss", ctc_loss)
    reg("GroupNorm", _OPS.get("group_norm"))

    # _npi odds and ends
    reg("_npi_insert_scalar", raw(getattr(mxnp, "insert", None)))
    reg("_npi_insert_slice", raw(getattr(mxnp, "insert", None)))
    reg("_npi_insert_tensor", raw(getattr(mxnp, "insert", None)))
    # reference ldexp allows FLOAT exponents (x1 * 2**x2) — share the
    # mx.np impl, not jnp.ldexp which rejects them
    _ldexp = getattr(mxnp, "ldexp")
    reg("_npi_ldexp_scalar", raw(_ldexp))
    reg("_npi_rldexp_scalar", _swap(raw(_ldexp)))
    # reference conventions (symbol/numpy/_symbol.py:7600-7612):
    # lscalar: where(cond, scalar, y) called as (cond, y, scalar);
    # rscalar: where(cond, x, scalar) called as (cond, x, scalar)
    reg("_npi_where_lscalar",
        lambda cond, y, scalar=0.0: j.where(cond, scalar, y))
    reg("_npi_where_rscalar",
        lambda cond, x, scalar=0.0: j.where(cond, x, scalar))
    reg("_npi_where_scalar2",
        lambda cond, x=0.0, y=0.0: j.where(cond, x, y))
    def _fill_diagonal(a, val=0.0, wrap=False):  # noqa: ARG001
        arr = j.asarray(a)
        n = min(arr.shape[-2:]) if arr.ndim >= 2 else arr.shape[0]
        idx = j.diag_indices(n, ndim=min(arr.ndim, 2))
        return arr.at[idx].set(val)

    reg("_npi_fill_diagonal", _fill_diagonal)
    reg("_npi_diag_indices_from",
        lambda a: j.stack(j.diag_indices_from(j.asarray(a))))
    reg("_npi_share_memory", lambda a, b: j.zeros((1,), j.bool_))
    reg("_npi_repeats", j.repeat)
    reg("_npi_tensordot_int_axes", j.tensordot)
    reg("_npi_advanced_indexing", lambda x, idx: j.asarray(x)[idx])
    reg("_npi_advanced_indexing_multiple",
        lambda x, *idx: j.asarray(x)[tuple(idx)])
    reg("_npi_boolean_mask_assign_scalar",
        lambda data, mask, value=0.0: j.where(
            j.asarray(mask, bool), value, j.asarray(data)))
    reg("_npi_boolean_mask_assign_tensor",
        lambda data, mask, value: j.place(
            j.asarray(data), j.asarray(mask, bool), j.asarray(value),
            inplace=False)
        if hasattr(j, "place") else j.where(
            j.asarray(mask, bool), j.asarray(value), j.asarray(data)))
    reg("_npx_index_add", raw(npx.index_add))
    reg("_npx_index_update", raw(npx.index_update))
    reg("_npx_nonzero", raw(npx.nonzero))
    reg("_npx_constraint_check", raw(npx.constraint_check))

    # negative-binomial sampling (src/operator/random/sample_op.cc)
    rnd = mxnp.random

    def sample_nb(k=1, p=0.5, shape=None, **kw):  # noqa: ARG001
        fn = getattr(rnd, "negative_binomial", None)
        return fn(k, p, size=shape) if fn is not None else None

    def sample_gnb(mu=1.0, alpha=1.0, shape=None, **kw):  # noqa: ARG001
        # gamma-poisson mixture (the reference's generalized NB)
        import jax as _jax

        from .. import _random as _rng

        key1, key2 = _jax.random.split(_rng.next_key())
        shp = shape if shape is not None else ()
        r = 1.0 / alpha
        g = _jax.random.gamma(key1, r, shp) * (alpha * mu)
        return _jax.random.poisson(key2, g, shp)

    reg("_sample_negative_binomial", sample_nb)
    reg("_sample_generalized_negative_binomial", sample_gnb)

    # functional slice-assign / scatter-set (the eager NDArray setitem
    # internals, src/operator/tensor/matrix_op.cc _slice_assign)
    def _slice_from(begin, end, step=None):
        step = step or [None] * len(begin)
        return tuple(slice(b, e, s)
                     for b, e, s in zip(begin, end, step))

    reg("_slice_assign",
        lambda lhs, rhs, begin, end, step=None: j.asarray(lhs).at[
            _slice_from(begin, end, step)].set(j.asarray(rhs)))
    reg("_slice_assign_scalar",
        lambda data, scalar=0.0, begin=(), end=(), step=None:
        j.asarray(data).at[_slice_from(begin, end, step)].set(scalar))
    reg("_scatter_set_nd", raw(npx.index_update))





def _install_round3():
    """Third round: quantized int8 op spellings + the last contrib names."""
    import jax.numpy as j

    from ..contrib import quantization as q
    from ..ops import vision as _vision

    def reg(name, fn):
        if name not in _OPS and fn is not None:
            register_op(name, fn)

    for nm in ("quantized_act", "quantized_flatten", "quantized_pooling",
               "quantized_elemwise_add", "quantized_elemwise_mul",
               "quantized_concat", "quantized_embedding",
               "quantized_batch_norm", "quantized_conv",
               "quantized_fully_connected"):
        reg(f"_contrib_{nm}", getattr(q, nm, None))
    reg("_contrib_calibrate_entropy",
        getattr(q, "optimal_threshold", None))

    # BatchNormWithReLU / SyncBatchNorm op spellings: the op-level math is
    # batch_norm (+relu); cross-device sync is SPMD's job (layer docs)
    bn = _OPS.get("batch_norm")
    if bn is not None:
        def bn_relu(*args, **kw):
            out = bn(*args, **kw)
            if isinstance(out, tuple):
                return (j.maximum(out[0], 0), *out[1:])
            return j.maximum(out, 0)

        reg("_contrib_BatchNormWithReLU", bn_relu)
        reg("_contrib_SyncBatchNorm", bn)





def _install_round4():
    from ..contrib import ops as cops

    for name, fn in (("_contrib_RROIAlign", cops.rroi_align),
                     ("_contrib_mrcnn_mask_target",
                      cops.mrcnn_mask_target)):
        if name not in _OPS:
            register_op(name, fn)





def _install_round5():
    """Round 3 of the build: the last legacy NNVM spellings — fused RNN,
    per-row `_sample_*` + `_random_pdf_*` families, `_linalg_*` twins of the
    la_op table, legacy init ops, control-flow entries over lax, the opencv
    `_cv*` image internals and the `Custom` dispatcher. After this round
    every non-backward `NNVM_REGISTER_OP` name in the reference resolves
    except the backend-specific skips listed in the module docstring plus
    graph-executor internals (`_CachedOp`, `_FusedOp*`, `__name$`)."""
    from . import rnn as _rnn_mod  # noqa: F401 - registers "RNN"
    from .random_legacy import install_legacy_random

    install_legacy_random()

    def reg(name, fn):
        if name not in _OPS and fn is not None:
            register_op(name, fn)

    # ---- _linalg_* twins (src/operator/tensor/la_op.cc registers both
    # the public linalg_* and internal _linalg_* spellings) ---------------
    for key in list(_OPS):
        if key.startswith("linalg_"):
            reg("_" + key, _OPS[key])

    # ---- legacy init ops (src/operator/tensor/init_op.cc) ---------------
    def _dt(dtype):
        return "float32" if dtype in (None, "None") else dtype

    reg("_zeros", lambda shape, dtype=None, **kw:
        jnp.zeros(shape, _dt(dtype)))
    reg("_ones", lambda shape, dtype=None, **kw:
        jnp.ones(shape, _dt(dtype)))
    reg("_full", lambda shape, value=0.0, dtype=None, **kw:
        jnp.full(shape, value, _dt(dtype)))
    reg("_linspace", lambda start=0.0, stop=1.0, num=50, endpoint=True,
        dtype=None, **kw:
        jnp.linspace(start, stop, int(num), endpoint=bool(endpoint),
                     dtype=_dt(dtype)))

    def _legacy_arange(start=0.0, stop=None, step=1.0, repeat=1,
                       dtype=None, **kw):  # noqa: ARG001
        base = jnp.arange(start, stop, step, dtype=_dt(dtype))
        return jnp.repeat(base, int(repeat)) if int(repeat) > 1 else base

    reg("_arange", _legacy_arange)

    # ---- legacy binary broadcasts + misc elemwise -----------------------
    reg("_maximum", jnp.maximum)
    reg("_minimum", jnp.minimum)
    reg("_power", jnp.power)
    reg("_hypot", jnp.hypot)
    reg("_copyto", lambda x, **kw: jnp.asarray(x))

    import jax as _jax

    reg("_NoGradient", lambda x, **kw: _jax.lax.stop_gradient(x))

    # ---- masked softmax family (src/operator/nn/softmax.cc
    # masked_softmax / masked_log_softmax) --------------------------------
    def _masked(log):
        def fn(data, mask, axis=-1, temperature=1.0, **kw):  # noqa: ARG001
            t = temperature if temperature else 1.0
            m = jnp.asarray(mask).astype(bool)
            x = jnp.where(m, jnp.asarray(data) / t, -jnp.inf)
            if log:
                return _jax.nn.log_softmax(x, axis=axis)
            y = _jax.nn.softmax(x, axis=axis)
            return jnp.where(m, y, 0.0)

        return fn

    reg("masked_softmax", _masked(log=False))
    reg("masked_log_softmax", _masked(log=True))

    # ---- control flow (src/operator/control_flow.cc _foreach/_while_loop/
    # _cond -> the npx lax-backed versions) -------------------------------
    from ..numpy_extension import control_flow as _cf

    reg("_foreach", _cf.foreach)
    reg("_while_loop", _cf.while_loop)
    reg("_cond", _cf.cond)

    # ---- opencv internals (src/io/image_io.cc _cvimread/_cvimdecode/
    # _cvimresize/_cvcopyMakeBorder) --------------------------------------
    from ..image import image as _img

    reg("_cvimread", _img.imread)
    reg("_cvimdecode", _img.imdecode)
    reg("_cvimresize", _img.imresize)
    reg("_cvcopyMakeBorder", _img.copyMakeBorder)

    # ---- Custom op dispatcher (src/operator/custom/custom.cc) -----------
    from ..operator import Custom as _custom

    reg("Custom", _custom)

    # ---- misc remaining spellings ---------------------------------------
    reg("_ravel_multi_index", _OPS.get("ravel_multi_index"))
    reg("_unravel_index", _OPS.get("unravel_index"))
    reg("_adamw_update", _OPS.get("adamw_update"))
    reg("_npi_logical_and", _OPS.get("broadcast_logical_and"))
    reg("_npi_logical_or", _OPS.get("broadcast_logical_or"))
    reg("_npi_logical_xor", _OPS.get("broadcast_logical_xor"))

    # ---- _npi_/_npx_ unary spellings the macro-generated reference
    # table covers but round 1's explicit list missed ---------------------
    for nm, fn in [
        ("_npi_sqrt", jnp.sqrt), ("_npi_cbrt", jnp.cbrt),
        ("_npi_exp", jnp.exp), ("_npi_expm1", jnp.expm1),
        ("_npi_log1p", jnp.log1p), ("_npi_log2", jnp.log2),
        ("_npi_log10", jnp.log10), ("_npi_tanh", jnp.tanh),
        ("_npi_sinh", jnp.sinh), ("_npi_cosh", jnp.cosh),
        ("_npi_square", jnp.square), ("_npi_absolute", jnp.abs),
        ("_npi_negative", jnp.negative), ("_npi_sign", jnp.sign),
        ("_npi_sin", jnp.sin), ("_npi_cos", jnp.cos),
        ("_npi_tan", jnp.tan), ("_npi_arcsin", jnp.arcsin),
        ("_npi_arccos", jnp.arccos), ("_npi_arctan", jnp.arctan),
        ("_npi_arcsinh", jnp.arcsinh), ("_npi_arccosh", jnp.arccosh),
        ("_npi_arctanh", jnp.arctanh), ("_npi_ceil", jnp.ceil),
        ("_npi_floor", jnp.floor), ("_npi_trunc", jnp.trunc),
        ("_npi_rint", jnp.rint),
        # fix(x) == trunc(x) (jnp.fix is deprecated, removal in v0.10)
        ("_npi_fix", jnp.trunc),
        ("_npi_reciprocal", lambda x, **kw: 1.0 / x),
        ("_npi_maximum", jnp.maximum), ("_npi_minimum", jnp.minimum),
        ("_npi_degrees", jnp.degrees), ("_npi_radians", jnp.radians),
        ("_npi_logical_not", jnp.logical_not),
    ]:
        reg(nm, fn)

    import jax.nn as _jnn

    reg("_npx_relu", lambda x, **kw: _jnn.relu(x))
    reg("_npx_sigmoid", lambda x, **kw: _jnn.sigmoid(x))

    # ---- NNVM attr spelling for scalar ops ------------------------------
    # Symbol graphs carry the scalar operand as an attr (`scalar=3.0`,
    # reference elemwise_binary_scalar_op: DMLC_DECLARE_FIELD(scalar));
    # round 1 registered plain positional jnp binaries. Wrap every
    # `*_scalar` entry to accept both spellings.
    def _scalar_kwarg(fn):
        def wrapped(data, *pos, scalar=None, is_int=None, **kw):  # noqa: ARG001
            if scalar is not None:
                # attr spelling: scalar slots in after the tensor operands
                return fn(data, *pos, scalar, **kw)
            return fn(data, *pos, **kw)

        wrapped.__wrapped_scalar__ = True
        return wrapped

    for nm in [k for k in _OPS if k.endswith("_scalar")]:
        f = _OPS[nm]
        if f is not None and not getattr(f, "__wrapped_scalar__", False):
            _OPS[nm] = _scalar_kwarg(f)


def install_aliases():
    """Populate the registry with every internal spelling. Idempotent."""
    if "_npi_add" in _OPS:
        return
    _install_round1()
    _install_round2()
    _install_round3()
    _install_round4()
    _install_round5()
