"""Neural-net primitive ops as pure jax functions (NCHW default, NHWC fast path).

TPU re-design of src/operator/nn/ (convolution, fully_connected, pooling,
batch_norm, layer_norm, softmax, activation, dropout...): each op is a pure
function lowered by XLA — conv → MXU convolution HLO, pooling →
reduce_window, norms → fused VPU chains. cuDNN/oneDNN dispatch layers are
unnecessary.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import env as _env
from ..base import normalize_dtype
from .registry import register_op

# ---------------------------------------------------------------------------
# dense / linear
# ---------------------------------------------------------------------------


@register_op("fully_connected")
def dense(x, weight, bias=None, flatten=True, num_hidden=None,
          no_bias=None):  # noqa: ARG001 - reference-signature parity
    """y = x @ W^T + b (reference: src/operator/nn/fully_connected.cc).

    weight layout (out_units, in_units) matches the reference so checkpoints
    map 1:1. With flatten=True input is reshaped to (N, -1) first.
    num_hidden is accepted for reference-call-signature parity; the
    weight shape is authoritative. no_bias=True drops the bias even if
    one is passed (reference semantics).
    """
    if no_bias:
        bias = None
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------

def _spec(ndim):
    # NC + spatial; kernel OI + spatial
    sp = "DHW"[-ndim:] if ndim <= 3 else None
    return ("NC" + sp, "OI" + sp, "NC" + sp)


def _layout_spec(layout):
    """Map a reference layout string (NCHW/NHWC/NCW/NWC/NCDHW/NDHWC) to
    (lhs_spec, rhs_spec, ndim). Channels-last puts C in the lane dimension —
    the MXU-preferred physical layout on TPU; kernel follows the reference
    convention: O,I,*k channels-first, O,*k,I channels-last
    (src/operator/nn/convolution-inl.h layout handling)."""
    sp = layout.replace("N", "").replace("C", "")
    nd = len(sp)
    if layout[1] == "C":  # channels-first
        return "NC" + sp, "OI" + sp, nd
    return "N" + sp + "C", "O" + sp + "I", nd


@register_op("convolution")
def conv(x, weight, bias=None, stride=None, pad=None, dilate=None, groups=1,
         layout=None, kernel_layout=None):
    """N-d convolution; layout NCHW (default) or NHWC family.

    weight (O, I/g, *k) channels-first, (O, *k, I/g) channels-last — matching
    the reference's per-layout weight shapes. Reference:
    src/operator/nn/convolution.cc. Lowers to a single XLA
    conv_general_dilated → MXU; channels-last keeps C in lanes.

    `kernel_layout` overrides the weight spec alone (e.g. "HWIO") — the
    persistent-relayout path (passes/layout.py) feeds physically
    transposed weights while the data layout stays whatever `layout`
    says; output shape and numerics are unchanged.
    """
    nd = x.ndim - 2
    if layout is None:
        lhs_spec, rhs_spec = _spec(nd)[:2]
        channels_last = False
    else:
        lhs_spec, rhs_spec, lnd = _layout_spec(layout)
        assert lnd == nd, f"layout {layout} does not match input ndim {x.ndim}"
        channels_last = layout[-1] == "C"
    if kernel_layout is not None:
        rhs_spec = kernel_layout
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd
    dilate = dilate or (1,) * nd
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(pad, int):
        pad = (pad,) * nd
    if isinstance(dilate, int):
        dilate = (dilate,) * nd
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs_spec, rhs_spec, lhs_spec))
    y = lax.conv_general_dilated(
        x,
        weight,
        window_strides=tuple(stride),
        padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )
    if bias is not None:
        y = y + (bias if channels_last
                 else bias.reshape((1, -1) + (1,) * nd))
    return y


@register_op("deconvolution")
def conv_transpose(x, weight, bias=None, stride=None, pad=None, dilate=None,
                   output_padding=None, groups=1, layout=None):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).

    weight (I, O/g, *k) channels-first / (I, *k, O/g) channels-last like the
    reference; implemented as the gradient of conv via conv_general_dilated
    with an IO spatial kernel spec and lhs dilation.
    """
    nd = x.ndim - 2
    stride = stride or (1,) * nd
    pad = pad or (0,) * nd
    dilate = dilate or (1,) * nd
    output_padding = output_padding or (0,) * nd
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(pad, int):
        pad = (pad,) * nd
    if isinstance(dilate, int):
        dilate = (dilate,) * nd
    if isinstance(output_padding, int):
        output_padding = (output_padding,) * nd
    sp = "DHW"[-nd:]
    channels_last = layout is not None and layout[-1] == "C"
    if channels_last:
        lhs_spec, rhs_spec = "N" + sp + "C", "I" + sp + "O"
    else:
        lhs_spec, rhs_spec = "NC" + sp, "IO" + sp
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape, (lhs_spec, rhs_spec, lhs_spec)
    )
    k = weight.shape[1:-1] if channels_last else weight.shape[2:]
    # padding for transpose conv uses the DILATED kernel extent
    # (k-1)*dilate + 1: eff_k - 1 - p on both sides, + output_padding low
    padding = [
        ((ki - 1) * di - pi, (ki - 1) * di - pi + opi)
        for ki, pi, di, opi in zip(k, pad, dilate, output_padding)
    ]
    # the transpose of cross-correlation convolves with the ROT-180 kernel
    # (reference deconvolution.cc backward-as-forward; conv_general_dilated
    # itself computes cross-correlation, so flip the spatial dims)
    spatial_axes = tuple(range(1, 1 + nd)) if channels_last \
        else tuple(range(2, 2 + nd))
    weight = jnp.flip(weight, spatial_axes)
    y = lax.conv_general_dilated(
        x,
        weight,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=tuple(stride),
        rhs_dilation=tuple(dilate),
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        y = y + (bias if channels_last
                 else bias.reshape((1, -1) + (1,) * nd))
    return y


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


@register_op("pooling")
def pool(x, kernel, pool_type="max", stride=None, pad=None, global_pool=False,
         count_include_pad=True, layout=None, ceil_mode=False,
         pooling_convention=None):
    """Max/avg/lp pooling via reduce_window (reference: nn/pooling.cc).

    layout: None/channels-first ("NCHW"...) pools x[2:]; channels-last
    ("NHWC"...) pools x[1:-1]. ceil_mode (the reference's
    pooling_convention='full') rounds output sizes UP by padding extra
    rows/cols on the high side of each spatial dim."""
    same_mode = False
    if pooling_convention is not None:
        if pooling_convention == "full":
            ceil_mode = True
        elif pooling_convention == "same":
            same_mode = True
        elif pooling_convention != "valid":
            raise ValueError(
                f"unknown pooling_convention {pooling_convention!r}; "
                "expected valid/full/same")
    nd = x.ndim - 2
    channels_last = layout is not None and layout[-1] == "C"
    sp = slice(1, -1) if channels_last else slice(2, None)
    if global_pool:
        kernel = x.shape[sp]
        stride = (1,) * nd
        pad = (0,) * nd
    if isinstance(kernel, int):
        kernel = (kernel,) * nd
    stride = stride or kernel
    if isinstance(stride, int):
        stride = (stride,) * nd
    pad = pad or (0,) * nd
    if isinstance(pad, int):
        pad = (pad,) * nd
    pad_pairs = [(p, p) for p in pad]
    if same_mode and not global_pool:
        # output = ceil(n / stride); pad split low/high like the
        # reference's same convention
        spatial = x.shape[sp]
        for i, (n, k, st) in enumerate(zip(spatial, kernel, stride)):
            out_same = -(-n // st)
            total = max((out_same - 1) * st + k - n, 0)
            pad_pairs[i] = (total // 2, total - total // 2)
    if ceil_mode and not global_pool:
        spatial = x.shape[sp]
        for i, (n, k, st, p) in enumerate(
                zip(spatial, kernel, stride, pad)):
            span = n + 2 * p - k
            out_full = -(-span // st) + 1          # ceil
            extra = (out_full - 1) * st + k - (n + 2 * p)
            pad_pairs[i] = (p, p + max(0, extra))
    if channels_last:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        padding = ((0, 0),) + tuple(pad_pairs) + ((0, 0),)
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
        padding = ((0, 0), (0, 0)) + tuple(pad_pairs)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0 if jnp.issubdtype(x.dtype, jnp.floating)
                              else 0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones(x.shape[sp], x.dtype)
        ones = ones[None, ..., None] if channels_last else ones[None, None]
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return s / counts
    if pool_type == "lp":
        p = 2.0
        s = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides,
                              padding)
        return s ** (1.0 / p)
    raise ValueError(f"unknown pool_type {pool_type}")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def _bn_shapes(x, axis):
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    n = x.size // x.shape[axis]
    return reduce_axes, tuple(bshape), n


def _bn_ew_dtype(x):
    """Element-wise dtype for the O(N·H·W·C) BN tensors. Default: f32
    (today's measured-correct config). MXTPU_BN_COMPUTE=bf16 keeps the
    big elementwise chains in the activation dtype and promotes only the
    REDUCTION accumulators to f32 (jnp.sum dtype=) — the r4 HLO audit's
    staged experiment: the program hands XLA ~2.9k f32 elementwise ops
    whose only f32-ness is stat math; if any fail to fuse on TPU they
    double HBM traffic. A/B on chip before changing the default.
    The Pallas BN kernels (kernels/norm.py) read the same knob, so the
    elementwise-dtype experiment stays a single switch either way."""
    if _env.get("MXTPU_BN_COMPUTE") == "bf16":
        return x.dtype
    return jnp.float32


def _bn_train_impl(x, gamma, beta, shift, eps, axis):
    """One reduction pass (sum + sum-of-squares multi-output-fused by XLA,
    reading the activation once) + one fused elementwise normalize.

    The sums are taken over (x - shift) with shift = the moving mean — a
    per-channel constant that costs nothing (it fuses into the same pass)
    but removes the catastrophic cancellation of the textbook
    E[x²]−E[x]² form once the running mean tracks the data scale
    (var is shift-invariant mathematically)."""
    reduce_axes, bshape, n = _bn_shapes(x, axis)
    ew = _bn_ew_dtype(x)
    s = lax.stop_gradient(shift.astype(ew)).reshape(bshape)
    xf = x.astype(ew) - s
    # accumulate in f32 regardless of the elementwise dtype
    xf32 = xf.astype(jnp.float32)
    s1 = jnp.sum(xf, reduce_axes, dtype=jnp.float32)
    s2 = jnp.sum(xf32 * xf32, reduce_axes, dtype=jnp.float32)
    mean_c = s1 / n
    var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
    mean = mean_c + s.astype(jnp.float32).reshape(s1.shape)
    inv = lax.rsqrt(var + eps)
    scale = (gamma.astype(jnp.float32) * inv).reshape(bshape)
    # xf is already centered on s, so normalize against the centered mean
    offset = (beta.astype(jnp.float32)
              - mean_c * gamma.astype(jnp.float32) * inv).reshape(bshape)
    out = (xf * scale.astype(ew) + offset.astype(ew)).astype(x.dtype)
    return out, mean, var, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_train(x, gamma, beta, shift, eps, axis):
    out, mean, var, _ = _bn_train_impl(x, gamma, beta, shift, eps, axis)
    return out, mean, var


def _bn_train_fwd(x, gamma, beta, shift, eps, axis):
    out, mean, var, inv = _bn_train_impl(x, gamma, beta, shift, eps, axis)
    return (out, mean, var), (x, gamma, beta, shift, mean, inv)


def _bn_train_bwd(eps, axis, res, cts):
    """Closed-form BN backward: ONE pass producing both reductions
    (dbeta, dgamma multi-output-fused) + one fused elementwise pass for dx —
    instead of autodiff's per-stat reduction chains through mean/var."""
    dy, dmean_ct, dvar_ct = cts
    x, gamma, beta, shift, mean, inv = res
    reduce_axes, bshape, n = _bn_shapes(x, axis)
    ew = _bn_ew_dtype(x)
    dyf = dy.astype(ew)
    # center on the saved shift BEFORE any low-precision subtraction,
    # like the forward: in bf16 mode, mean.astype(bf16) has granularity
    # ~mean/256, so (x - mean) directly would wreck xhat for
    # large-mean activations; (x - shift) - (mean - shift) keeps both
    # operands on the data's centered scale (mean - shift is computed
    # in f32 and is small once the moving mean tracks the data)
    s = lax.stop_gradient(shift.astype(jnp.float32)).reshape(bshape)
    xf = x.astype(ew) - s.astype(ew)
    mean_c = (mean.reshape(bshape) - s).astype(ew)
    xhat = (xf - mean_c) * inv.astype(ew).reshape(bshape)
    # reductions always accumulate f32 (dtype=), whatever the elementwise
    dbeta = jnp.sum(dyf, reduce_axes, dtype=jnp.float32)
    dgamma = jnp.sum(dyf * xhat, reduce_axes, dtype=jnp.float32)
    g32 = gamma.astype(jnp.float32)
    dx = (g32 * inv).astype(ew).reshape(bshape) * (
        dyf - (dbeta.astype(ew).reshape(bshape)
               + xhat * dgamma.astype(ew).reshape(bshape)) / n)
    # cotangents of the batch-stat outputs (aux moving-stat path; usually
    # zero) — cheap broadcast terms that fuse into the dx pass
    dx = dx + (dmean_ct.astype(ew).reshape(bshape) / n
               + dvar_ct.astype(ew).reshape(bshape) * 2.0
               * (xf - mean_c) / n)
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(beta.dtype), jnp.zeros_like(shift))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm")
def batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, training=True, use_global_stats=False, axis=1):
    """Batch normalization (reference: nn/batch_norm.cc).

    Returns (out, new_mean, new_var). The stateful moving-stat update is done
    by the caller (BatchNorm layer / state sink), keeping this function pure.
    Training mode uses a custom_vjp so fwd reads the activation once (fused
    sum/sum² stats) and bwd is the closed-form two-pass kernel.
    """
    axis = axis % x.ndim  # normalize negative axis (-1 = channels-last)
    if training and not use_global_stats:
        bn = _bn_train
        try:
            from ..kernels import dispatch as _kdispatch
            if _kdispatch.mode() != "off":
                from ..kernels import norm as _knorm
                bn = _knorm.bn_train
        except ImportError:
            pass
        out, mean, var = bn(x, gamma, beta, moving_mean,
                            float(eps), axis)
        new_mean = moving_mean * momentum + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_var = moving_var * momentum + var.astype(moving_var.dtype) * (1 - momentum)
        return out, new_mean, new_var
    _, bshape, _ = _bn_shapes(x, axis)
    mean, var = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = (gamma.astype(jnp.float32) * inv).reshape(bshape)
    shift = (beta.astype(jnp.float32)
             - mean.astype(jnp.float32) * gamma.astype(jnp.float32)
             * inv).reshape(bshape)
    out = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
    return out, moving_mean, moving_var


def _ln_impl(x, gamma, beta, eps, axis):
    """Single-pass stats (sum/sum² multi-output-fused): shifted
    var = E[(x−x₀)²]−E[x−x₀]² with x₀ = the row's first element, which is
    on the data's scale and so removes the cancellation of the raw
    E[x²]−E[x]² form (variance is shift-invariant mathematically)."""
    xf = x.astype(jnp.float32)
    n = x.shape[axis]
    x0 = lax.stop_gradient(
        lax.slice_in_dim(xf, 0, 1, axis=axis % x.ndim))
    xc = xf - x0
    s1 = jnp.sum(xc, axis=axis, keepdims=True)
    s2 = jnp.sum(xc * xc, axis=axis, keepdims=True)
    mean_c = s1 / n
    var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
    mean = mean_c + x0
    inv = lax.rsqrt(var + eps)
    xhat = (xf - mean) * inv
    out = xhat
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    if gamma is not None:
        out = out * gamma.astype(jnp.float32).reshape(bshape)
    if beta is not None:
        out = out + beta.astype(jnp.float32).reshape(bshape)
    return out.astype(x.dtype), mean, inv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x, gamma, beta, eps, axis):
    return _ln_impl(x, gamma, beta, eps, axis)[0]


def _ln_fwd(x, gamma, beta, eps, axis):
    out, mean, inv = _ln_impl(x, gamma, beta, eps, axis)
    return out, (x, gamma, beta, mean, inv)


def _ln_bwd(eps, axis, res, dy):
    """Closed-form LN backward: one fused pass per tensor instead of
    autodiff's reduction chains through mean/var."""
    x, gamma, beta, mean, inv = res
    bshape = [1] * x.ndim
    bshape[axis] = x.shape[axis]
    n = x.shape[axis]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean) * inv
    a = (dyf * gamma.astype(jnp.float32).reshape(bshape)
         if gamma is not None else dyf)
    m1 = jnp.sum(a, axis=axis, keepdims=True) / n
    m2 = jnp.sum(a * xhat, axis=axis, keepdims=True) / n
    dx = (inv * (a - m1 - xhat * m2)).astype(x.dtype)
    param_axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    dgamma = (jnp.sum(dyf * xhat, axis=param_axes).astype(gamma.dtype)
              if gamma is not None else None)
    dbeta = (jnp.sum(dyf, axis=param_axes).astype(beta.dtype)
             if beta is not None else None)
    return dx, dgamma, dbeta


_ln.defvjp(_ln_fwd, _ln_bwd)


@register_op("layer_norm")
def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    """Layer normalization (reference: nn/layer_norm.cc).

    custom_vjp: fwd reads x once (fused sum/sum² stats); bwd is the
    closed-form kernel (dx in one fused pass, dgamma/dbeta multi-output)."""
    return _ln(x, gamma, beta, float(eps), axis)


@register_op("group_norm")
def group_norm(x, gamma, beta, num_groups, eps=1e-5):
    """Group normalization over NC+spatial (reference: nn/group_norm.cc)."""
    n, c = x.shape[:2]
    g = num_groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    out = xg.reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if gamma is not None:
        out = out * gamma.reshape(bshape)
    if beta is not None:
        out = out + beta.reshape(bshape)
    return out


@register_op("instance_norm")
def instance_norm(x, gamma, beta, eps=1e-5):
    """Instance norm = group norm with one group per channel."""
    return group_norm(x, gamma, beta, num_groups=x.shape[1], eps=eps)


@register_op("rms_norm")
def rms_norm(x, gamma, axis=-1, eps=1e-6):
    """RMSNorm — modern-transformer extension beyond the reference set."""
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
    out = (x.astype(jnp.float32) * lax.rsqrt(ms + eps)).astype(x.dtype)
    if gamma is not None:
        out = out * gamma
    return out


@register_op("lrn")
def lrn(x, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response normalization (reference: nn/lrn.cc)."""
    sq = jnp.square(x)
    half = nsize // 2
    sq_pad = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (x.ndim - 2))
    acc = sum(
        lax.dynamic_slice_in_dim(sq_pad, i, x.shape[1], axis=1)
        for i in range(nsize)
    )
    return x / (knorm + alpha / nsize * acc) ** beta


# ---------------------------------------------------------------------------
# softmax family
# ---------------------------------------------------------------------------


@register_op("softmax")
def softmax(x, axis=-1, length=None, temperature=None):
    """Softmax with optional sequence-length masking (reference: nn/softmax.cc)."""
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        mask = jnp.arange(x.shape[axis]) < jnp.expand_dims(length, -1)
        shape = [1] * x.ndim
        shape[0] = x.shape[0]
        shape[axis] = x.shape[axis]
        x = jnp.where(mask.reshape(shape), x, -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis)


@register_op("softmin")
def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    # reference gelu (mshadow_op.h) is the exact erf form; the tanh
    # approximation is opt-in under its own name
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "erf_gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "hard_swish": jax.nn.hard_swish,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "identity": lambda x: x,
}


@register_op("activation")
def activation(x, act_type="relu"):
    """Activation dispatch (reference: nn/activation.cc act_type enum)."""
    try:
        return _ACTS[act_type](x)
    except KeyError:
        raise ValueError(f"unknown act_type '{act_type}'") from None


@register_op("leaky_relu")
def leaky_relu(x, gamma=None, act_type="leaky", slope=0.25):
    """LeakyReLU family (reference: leaky_relu.cc: leaky/prelu/elu/selu/gelu)."""
    if act_type == "leaky":
        return jnp.where(x >= 0, x, slope * x)
    if act_type == "prelu":
        ndim = x.ndim
        if gamma.ndim == 1 and ndim > 2:
            gamma = gamma.reshape((1, -1) + (1,) * (ndim - 2))
        return jnp.where(x >= 0, x, gamma * x)
    if act_type == "elu":
        return jnp.where(x >= 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "rrelu":
        return jnp.where(x >= 0, x, slope * x)  # eval-mode rrelu
    raise ValueError(f"unknown act_type '{act_type}'")


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


@register_op("dropout")
def dropout(x, key, p=0.5, training=True, axes=None):
    """Inverted dropout (reference: nn/dropout.cc). Key is explicit — the
    stateful facade supplies it (mx._random.next_key / trace provider)."""
    if not training or p <= 0.0:
        return x
    shape = list(x.shape)
    if axes:
        # `axes` are the axes the mask is SHARED along (reference
        # nn/dropout.cc axes param): mask broadcasts over them.
        for ax in axes:
            shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))


# ---------------------------------------------------------------------------
# indexing / embedding / misc NN ops
# ---------------------------------------------------------------------------


@register_op("embedding")
def embedding(indices, weight, input_dim=None, output_dim=None,
              dtype=None, sparse_grad=False):  # noqa: ARG001
    """Embedding lookup (reference: tensor/indexing_op.cc Embedding;
    frontend signature numpy_extension/_op.py:976 carries
    input_dim/output_dim/dtype/sparse_grad).

    Gather on MXU-friendly layout; gradient is a dense scatter-add (the
    reference's row_sparse grad path is deliberately dense here — see
    ndarray.py module doc on sparse). input_dim/output_dim are shape
    hints validated against the weight; sparse_grad is honored at the
    gluon layer (Parameter row hints), not here.
    """
    if input_dim is not None and weight.shape[0] != input_dim:
        raise ValueError(
            f"embedding input_dim {input_dim} != weight rows "
            f"{weight.shape[0]}")
    if output_dim is not None and weight.shape[-1] != output_dim:
        raise ValueError(
            f"embedding output_dim {output_dim} != weight cols "
            f"{weight.shape[-1]}")
    out = jnp.take(weight, indices.astype(jnp.int32), axis=0)
    if dtype is not None:
        out = out.astype(normalize_dtype(dtype))
    return out


@register_op("one_hot")
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype=jnp.float32):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=dtype)
    if on_value != 1.0 or off_value != 0.0:
        oh = oh * (on_value - off_value) + off_value
    return oh


@register_op("pick")
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    """Pick elements along axis by index (reference: tensor/broadcast_reduce_op_index.cc)."""
    index = index.astype(jnp.int32)
    if mode == "clip":
        index = jnp.clip(index, 0, x.shape[axis] - 1)
    else:
        index = index % x.shape[axis]
    picked = jnp.take_along_axis(x, jnp.expand_dims(index, axis), axis=axis)
    return picked if keepdims else jnp.squeeze(picked, axis=axis)


@register_op("topk")
def topk(x, k=1, axis=-1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    """Top-k (reference: tensor/ordering_op.cc; `dtype` controls the
    INDEX dtype like the reference frontend). Uses lax.top_k on last
    axis."""
    xm = jnp.moveaxis(x, axis, -1)
    if is_ascend:
        vals, idx = lax.top_k(-xm, k)
        vals = -vals
    else:
        vals, idx = lax.top_k(xm, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)

    def cast_idx(i):
        # `dtype` applies only to RETURNED indices (None = native int32);
        # mask/value paths keep exact int indices — a float32 index is
        # only exact below 2^24 and the cast is wasted work there
        return i if dtype is None else i.astype(normalize_dtype(dtype))

    if ret_typ == "indices":
        return cast_idx(idx)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, cast_idx(idx)
    if ret_typ == "mask":
        # 0/1 mask of the selected cells in the input's shape
        # (reference ordering_op.cc ReturnType kReturnMask)
        lastax_idx = jnp.moveaxis(idx, axis, -1)  # (..., k) over xm
        mask = jax.nn.one_hot(lastax_idx, xm.shape[-1],
                              dtype=x.dtype).sum(-2)
        return jnp.moveaxis(mask, -1, axis)
    raise ValueError(f"unknown ret_typ {ret_typ}")


@register_op("sequence_mask")
def sequence_mask(x, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """Mask sequences beyond their length (reference: sequence_mask.cc)."""
    if not use_sequence_length or sequence_length is None:
        return x
    steps = jnp.arange(x.shape[axis])
    # x: (T, N, ...) if axis==0 else (N, T, ...)
    if axis == 0:
        mask = steps[:, None] < sequence_length[None, :]
    else:
        mask = steps[None, :] < sequence_length[:, None]
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return jnp.where(mask, x, jnp.asarray(value, x.dtype))


@register_op("sequence_last")
def sequence_last(x, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(x, -1, axis=axis)
    idx = (sequence_length - 1).astype(jnp.int32)
    if axis == 0:
        return jnp.take_along_axis(
            x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0
        ).squeeze(0)
    return jnp.take_along_axis(
        x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
    ).squeeze(1)


@register_op("sequence_reverse")
def sequence_reverse(x, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(x, axis=axis)
    t = x.shape[axis]
    steps = jnp.arange(t)
    # reversed index within each sequence, identity beyond length
    if axis != 0:
        raise NotImplementedError("sequence_reverse supports axis=0 (T,N,...)")
    lengths = sequence_length.astype(jnp.int32)
    rev = jnp.where(steps[:, None] < lengths[None, :],
                    lengths[None, :] - 1 - steps[:, None], steps[:, None])
    return jnp.take_along_axis(x, rev.reshape(rev.shape + (1,) * (x.ndim - 2)),
                               axis=0)


@register_op("l2_normalization")
def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


@register_op("upsampling")
def upsample(x, scale=2, sample_type="nearest"):
    """Spatial upsampling (reference: nn/upsampling.cc)."""
    n, c, h, w = x.shape
    if sample_type == "nearest":
        return jax.image.resize(x, (n, c, h * scale, w * scale), "nearest")
    return jax.image.resize(x, (n, c, h * scale, w * scale), "bilinear")


@register_op("moments")
def moments(x, axes=None, keepdims=False):
    mean = jnp.mean(x, axis=axes, keepdims=keepdims)
    var = jnp.var(x, axis=axes, keepdims=keepdims)
    return mean, var
