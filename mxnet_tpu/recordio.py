"""RecordIO file format (reference: python/mxnet/recordio.py +
src/recordio.cc / tools/im2rec.cc).

Binary-compatible with dmlc RecordIO: each record is
  [magic:4B][lrec:4B][payload][pad to 4B]
where lrec's upper 3 bits are a continuation flag (0=whole record) and the
lower 29 bits the payload length. IRHeader packing (label/id) matches
mx.recordio.pack so .rec datasets written by the reference load unchanged.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_LFLAG_BITS = 29
_LEN_MASK = (1 << _LFLAG_BITS) - 1


def _native_mod():
    from . import _native

    return _native if _native.available() else None


class MXRecordIO:
    """Sequential reader/writer.

    Backed by the native C++ reader/writer (native/mxtpu_runtime.cc,
    buffered stdio — the src/recordio.cc equivalent) when libmxtpu is
    available; pure-python struct fallback otherwise. Both speak the same
    bytes."""

    def __init__(self, uri, flag="r"):
        self.uri = uri
        self.flag = flag
        self._native = None
        self.open()

    def open(self):
        nat = _native_mod()
        if self.flag == "w":
            if nat:
                self._native = nat.NativeRecordWriter(self.uri)
                self._fh = None
            else:
                self._fh = open(self.uri, "wb")
        elif self.flag == "r":
            if nat:
                self._native = nat.NativeRecordReader(self.uri)
                self._fh = None
            else:
                self._fh = open(self.uri, "rb")
        else:
            raise ValueError("flag must be 'r' or 'w'")
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._native is not None:
                self._native.close()
                self._native = None
            else:
                self._fh.close()
            self.is_open = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._native is not None:
            return self._native.tell()
        return self._fh.tell()

    def seek(self, pos):
        assert self.flag == "r", "seek is reader-only (reference parity)"
        if self._native is not None:
            self._native.seek(pos)
        else:
            self._fh.seek(pos)

    def write(self, buf):
        assert self.flag == "w"
        if isinstance(buf, str):
            buf = buf.encode()
        if self._native is not None:
            self._native.write(bytes(buf))
            return
        n = len(buf)
        self._fh.write(struct.pack("<II", _MAGIC, n & _LEN_MASK))
        self._fh.write(buf)
        pad = (4 - n % 4) % 4
        if pad:
            self._fh.write(b"\x00" * pad)

    def read(self):
        assert self.flag == "r"
        if self._native is not None:
            return self._native.read()
        head = self._fh.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError(f"invalid record magic {magic:#x} in {self.uri}")
        n = lrec & _LEN_MASK
        data = self._fh.read(n)
        pad = (4 - n % 4) % 4
        if pad:
            self._fh.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with a .idx sidecar
    (reference: MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri=None, flag="r", key_type=int):
        if uri is None:  # single-arg form: derive idx from rec path
            uri = idx_path
            idx_path = os.path.splitext(uri)[0] + ".idx"
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.flag == "w" and getattr(self, "is_open", False):
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def __len__(self):
        return len(self.keys)

    def write_idx(self, idx, buf):
        pos = self.tell()
        self.write(buf)
        self.idx[idx] = pos
        self.keys.append(idx)

    def read_idx(self, idx):
        if idx not in self.idx:
            idx = self.keys[idx]
        self.seek(self.idx[idx])
        return self.read()


IndexedRecordIO = MXIndexedRecordIO

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack IRHeader + payload (reference: recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id,
                          header.id2)
    else:
        label = _np.asarray(header.label, dtype=_np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
        hdr += label.tobytes()
    if isinstance(s, str):
        s = s.encode()
    return hdr + s


def unpack(s):
    """Unpack to (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[: flag * 4], _np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (requires pillow for jpeg; .npy always works)."""
    if img_fmt == ".npy":
        import io as _io

        buf = _io.BytesIO()
        _np.save(buf, _np.asarray(img))
        return pack(header, buf.getvalue())
    try:
        import io as _io

        from PIL import Image

        buf = _io.BytesIO()
        Image.fromarray(_np.asarray(img)).save(
            buf, format="JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG",
            quality=quality)
        return pack(header, buf.getvalue())
    except ImportError as e:
        raise RuntimeError("pack_img needs pillow; use img_fmt='.npy'") from e


def unpack_img(s, iscolor=-1):  # noqa: ARG001
    header, payload = unpack(s)
    if payload[:6] == b"\x93NUMPY":
        import io as _io

        return header, _np.load(_io.BytesIO(payload))
    try:
        import io as _io

        from PIL import Image

        return header, _np.asarray(Image.open(_io.BytesIO(payload)))
    except ImportError as e:
        raise RuntimeError("unpack_img needs pillow for jpeg/png") from e
