"""ShardingPass — stamps the plan's placement onto a captured program.

Placement in this stack is carried by the OPERANDS ("computation
follows data": params/grads/optimizer state live as NamedSharding
arrays after :meth:`ShardingPlan.apply`, batches are placed by
``shard_batch``/TrainStep's ``_whole``), and the whole-step mesh path
wraps its body in ``shard_map`` with the gradient psum already traced
in via ``collectives.psum_tree_flat_traced``.  What the captured jaxpr
itself lacks is the CONSTRAINT: nothing pins the program's inputs and
outputs to the plan, so a refactor that drops a device_put — or a
block seam that never sees TrainStep's placement code — silently
degrades to replicated transfers.

This pass closes that hole at the pass-pipeline seam.  At priority 30
it runs after layout (25) — specs describe logical dims, and this
program's params are already in their physical layout — and before the
numerics interposer.  For each seam kind it:

  * block / whole_step: records the plan on ``ctx.notes["sharding"]``
    (mesh shape, batch axis, rule count — what diagnose.py --passes
    and tests assert on) and, for block seams carrying batch-major
    inputs, stamps ``ctx.in_shardings``/``ctx.out_shardings`` so the
    ``jax.jit`` that compiles the rewritten program enforces the
    plan's placement instead of inheriting whatever the operands had;
  * the jaxpr itself is returned UNCHANGED — sharding is a placement
    property, not an equation rewrite, so the rewritten program stays
    structurally identical to the unsharded one (same dedup key, same
    retrace behavior).

The whole-step seam deliberately keeps ``in_shardings`` unset: its
argument list mixes python scalars (lrs/wds/ts) with pytrees, where
pjit's prefix-matching of shardings is version-fragile, and TrainStep
already places every operand explicitly in ``_whole``.  The stamp
there is the note + telemetry only, which is also what keeps
``mesh=None`` trivially bitwise: no plan, no pass, no note.
"""
from __future__ import annotations

from ..telemetry import instruments as _telemetry
from ..passes.manager import GraphPass

__all__ = ["ShardingPass"]


class ShardingPass(GraphPass):
    """Plan-placement stamp (see module docstring)."""

    name = "sharding"
    priority = 30
    kinds = ("block", "whole_step")

    def __init__(self, plan=None):
        # plan may be None when force-added via MXTPU_PASSES=sharding;
        # the context's plan (set by Trainer/TrainStep) wins when both
        # are present so one pass object serves multi-trainer processes
        self._plan = plan

    def applies(self, ctx):
        return super().applies(ctx) and \
            (ctx.plan is not None or self._plan is not None)

    def run(self, closed_jaxpr, ctx):
        plan = ctx.plan if ctx.plan is not None else self._plan
        mesh = plan.mesh
        ctx.notes["sharding"] = {
            "mesh": dict(mesh.shape),
            "batch_axis": plan.batch_axis,
            "rules": len(plan.rules),
            "kind": ctx.kind,
        }
        if ctx.kind == "block" and ctx.in_shardings is None:
            from jax.sharding import NamedSharding, PartitionSpec

            # block programs take batch-major activations: constrain
            # every input/output to the plan's data spec so the
            # compiled executable refuses silently-replicated operands
            shd = NamedSharding(mesh, plan.data_spec())
            ctx.in_shardings = shd
            ctx.out_shardings = shd
        _telemetry.record_sharding_stamp(ctx.label or "?", ctx.kind)
        return closed_jaxpr
