"""Hybrid-parallelism subsystem: WHERE every array lives.

``passes/`` owns the trace→compile seam; this package owns placement —
one :class:`ShardingPlan` (mesh axes + per-parameter PartitionSpec
rules) threaded through Trainer, TrainStep, kvstore and checkpoint so
`Trainer(..., kvstore='tpu_dist', mesh=(('dp', -1),))` trains the
donated one-dispatch whole-step program data-parallel, and
tensor-sharded plans ride XLA's GSPMD partitioner.  docs/sharding.md
is the user-facing tour; ``mesh=None`` (and MXTPU_SHARDING=off) keeps
every code path bitwise-identical to the unsharded framework.
"""
from .layouts import (DEFAULT_LAYOUT, RECIPES, SpecLayout,  # noqa: F401
                      block_roles, plan_recipe, role_from_name,
                      zero_state_spec)
from .plan import (ShardingError, ShardingPlan, last_applied,  # noqa: F401
                   mode, parse_axes, resolve_plan)
from .shard_pass import ShardingPass  # noqa: F401

__all__ = ["ShardingError", "ShardingPlan", "ShardingPass",
           "SpecLayout", "DEFAULT_LAYOUT", "RECIPES", "block_roles",
           "plan_recipe", "role_from_name", "zero_state_spec",
           "last_applied", "mode", "parse_axes", "resolve_plan"]
