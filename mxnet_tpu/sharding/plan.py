"""ShardingPlan — the one object that answers "where does every array
live".

A plan is (mesh axes, per-parameter PartitionSpec rules, batch axis):

    plan = ShardingPlan((("dp", -1),))                    # pure DP
    plan = ShardingPlan((("dp", 4), ("tp", 2)),
                        rules=[(r".*dense.*weight", ("tp", None))])
    plan = ShardingPlan.parse("dp=4,tp=2")                # MXTPU_MESH form

Construction never touches devices; :meth:`mesh` builds the
``jax.sharding.Mesh`` lazily (``-1`` sizes infer from the device count,
a fully-specified product smaller than the host's device count takes a
leading subset — ``dp=4`` on an 8-device host is legal). :meth:`apply`
places initialized Gluon parameters (and their grad buffers) via
``parallel.mesh.shard_params`` with :meth:`spec_for` as the spec_fn.

Spec-rule precedence (docs/sharding.md):
  1. ``spec_fn(name, shape)`` — a non-None return wins outright;
  2. the first matching regex in ``rules`` (searched, in order);
  3. the attached :class:`~.layouts.SpecLayout` rule library (plans
     built via :meth:`from_layout` / an MXTPU_MESH naming fsdp/tp
     axes) — placement by structural role, pruned to the mesh and to
     divisible shapes;
  4. replicated (``PartitionSpec()``) — the bitwise-identical default,
     so a plan with no rules is exactly data parallelism.

ZeRO contract: a plan whose mesh carries the layout's fsdp axis also
answers :meth:`state_spec_for` — optimizer state (momentum, variance,
fp32 masters) extends its param's spec by sharding along fsdp on the
first unsharded divisible dim (``layouts.zero_state_spec``), so each
rank owns ~1/N of optimizer memory. ``MXTPU_ZERO=0`` turns this off
(state then mirrors its weight's placement verbatim).

``mode()`` is the ONE normalization of MXTPU_SHARDING — Trainer's plan
resolution and the pass-pipeline injection both read it, so a value
that resolves no plan here also injects no ShardingPass there:

  off   the subsystem is disabled: ``mesh=`` arguments and MXTPU_MESH
        are ignored, nothing is placed — bitwise-identical to main;
  auto  (default) a plan comes from explicit Trainer arguments, else
        from the MXTPU_MESH env spelling;
  plan  explicit arguments only — MXTPU_MESH is ignored, so a launch
        script's env mesh cannot override a hand-built plan.

Checkpoint contract: :meth:`to_manifest`/:meth:`from_manifest`
round-trip the plan as JSON (``spec_fn`` is recorded as a flag only —
callables don't serialize); ``checkpoint/snapshot.py`` stores it in
every manifest and re-places restored arrays onto the RESTORING
trainer's plan, so replicated↔dp↔dp×tp moves are just save + restore.
"""
from __future__ import annotations

import re

from jax.sharding import Mesh, PartitionSpec

from .. import env as _env
from ..parallel.mesh import ShardingError, make_mesh
from ..parallel.mesh import shard_params as _shard_params
from ..telemetry import instruments as _telemetry

__all__ = ["ShardingPlan", "ShardingError", "mode", "parse_axes",
           "resolve_plan", "last_applied"]

# same normalization table discipline as layout/kernels/numerics mode():
# the ONE place MXTPU_SHARDING is interpreted
_MODES = {
    "": "off", "0": "off", "off": "off", "false": "off", "no": "off",
    "none": "off",
    "1": "auto", "auto": "auto", "on": "auto", "true": "auto",
    "yes": "auto",
    "plan": "plan", "explicit": "plan",
}


def mode():
    """Resolved MXTPU_SHARDING mode: 'off' | 'auto' | 'plan'."""
    raw = str(_env.get("MXTPU_SHARDING")).strip().lower()
    try:
        return _MODES[raw]
    except KeyError:
        raise ValueError(
            f"MXTPU_SHARDING={raw!r} is not a recognized mode; expected "
            f"off | auto | plan") from None


def parse_axes(spec):
    """Normalize a mesh-axes spelling to (("name", size), ...).

    Accepts the MXTPU_MESH string form ('dp=-1', 'dp=4,tp=2'), a dict,
    or a sequence of (name, size) pairs. Sizes must be positive ints or
    -1 (infer from device count); anything else raises ShardingError.
    """
    if isinstance(spec, str):
        pairs = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ShardingError(
                    f"mesh axis {part!r} is not 'name=size' "
                    f"(MXTPU_MESH spelling, e.g. 'dp=-1' or 'dp=4,tp=2')")
            name, _, size = part.partition("=")
            try:
                size = int(size.strip())
            except ValueError:
                raise ShardingError(
                    f"mesh axis {part!r}: size {size.strip()!r} is not "
                    f"an integer") from None
            pairs.append((name.strip(), size))
    elif isinstance(spec, dict):
        pairs = list(spec.items())
    else:
        pairs = [(str(n), int(s)) for n, s in spec]
    if not pairs:
        raise ShardingError("mesh spec names no axes")
    seen = set()
    for name, size in pairs:
        if not name:
            raise ShardingError("mesh axis with an empty name")
        if name in seen:
            raise ShardingError(f"mesh axis {name!r} appears twice")
        seen.add(name)
        if size != -1 and size < 1:
            raise ShardingError(
                f"mesh axis {name!r}: size must be a positive int or -1 "
                f"(infer), got {size}")
    return tuple((str(n), int(s)) for n, s in pairs)


def _as_spec(entry):
    """A rule's spec spelling -> PartitionSpec: already a spec, None
    (replicated), or a sequence of axis-name/None entries."""
    if entry is None:
        return PartitionSpec()
    if isinstance(entry, PartitionSpec):
        return entry
    return PartitionSpec(*entry)


# last applied plan + its param table — observability state only
# (tools/diagnose.py --passes reads it); pass injection never consults
# this, it is driven by the PassContext's own plan field
_LAST_APPLIED = [None]


def last_applied():
    """{'plan': manifest, 'params': [...]} of the most recent
    :meth:`ShardingPlan.apply` in this process, or None."""
    return _LAST_APPLIED[0]


class ShardingPlan:
    """Mesh axes + per-parameter placement rules (docs/sharding.md)."""

    def __init__(self, axes, rules=None, spec_fn=None, batch_axis=None,
                 devices=None, layout=None, roles=None):
        self.axes = parse_axes(axes)
        self.rules = tuple(
            (str(pat), _as_spec(spec)) for pat, spec in (rules or ()))
        self.spec_fn = spec_fn
        # SpecLayout rule library (sharding/layouts.py): placement by
        # structural role, consulted AFTER spec_fn and regex rules.
        # ``roles`` (optional) pins {param name: role} from a structural
        # block walk; without it roles resolve from name tokens.
        self.layout = layout
        self.roles = dict(roles) if roles else None
        # the data-parallel axis batches shard over; default: first axis
        self.batch_axis = str(batch_axis) if batch_axis is not None \
            else self.axes[0][0]
        if self.batch_axis not in {n for n, _ in self.axes}:
            raise ShardingError(
                f"batch_axis {self.batch_axis!r} is not a mesh axis "
                f"(mesh has {tuple(n for n, _ in self.axes)})")
        self._devices = list(devices) if devices is not None else None
        self._mesh = None
        self._compiled_rules = [(re.compile(pat), spec)
                                for pat, spec in self.rules]

    # -- construction helpers ---------------------------------------------
    @classmethod
    def parse(cls, spec, **kw):
        """Plan from the MXTPU_MESH axis-spec string ('dp=4,tp=2')."""
        return cls(parse_axes(spec), **kw)

    @classmethod
    def from_layout(cls, axes, net=None, layout=None, **kw):
        """Plan carrying the SpecLayout rule library (sharding/layouts):
        stock-block params place by structural role over the layout's
        data/fsdp/tp axes instead of per-weight regex. ``net`` upgrades
        role resolution from name tokens to the structural block walk;
        regex ``rules=`` still win on conflict."""
        from . import layouts as _layouts

        layout = layout or _layouts.DEFAULT_LAYOUT
        roles = _layouts.block_roles(net) if net is not None else None
        return cls(axes, layout=layout, roles=roles, **kw)

    @classmethod
    def from_env(cls):
        """Plan from MXTPU_MESH, or None when the env names no mesh.

        A mesh naming the layout's model axes (fsdp/tp) attaches the
        default SpecLayout rule library — MXTPU_MESH="dp=2,fsdp=2,tp=2"
        is a full hybrid plan with no code. MXTPU_SPEC_LAYOUT=0 keeps
        env meshes placement-free (axes only, params replicate)."""
        raw = str(_env.get("MXTPU_MESH")).strip()
        if not raw:
            return None
        axes = parse_axes(raw)
        if _env.get("MXTPU_SPEC_LAYOUT"):
            from . import layouts as _layouts

            names = {n for n, _ in axes}
            if names & set(_layouts.DEFAULT_LAYOUT.model_axes()):
                return cls.from_layout(axes)
        return cls(axes)

    @classmethod
    def from_manifest(cls, d):
        """Inverse of :meth:`to_manifest`. The spec_fn flag is restored
        as None — callables don't serialize; rules and axes round-trip
        exactly."""
        if d is None:
            return None
        layout = None
        if d.get("layout"):
            from . import layouts as _layouts

            layout = _layouts.SpecLayout(*d["layout"])
        return cls(
            tuple((str(n), int(s)) for n, s in d["axes"]),
            rules=[(pat, tuple(e if e is None else str(e) for e in spec))
                   for pat, spec in d.get("rules") or ()],
            batch_axis=d.get("batch_axis"),
            layout=layout, roles=d.get("roles"))

    def to_manifest(self):
        """JSON-able plan record for checkpoint manifests: axes with
        their RESOLVED sizes when a mesh was built (so a dp=-1 plan
        saved on 4 devices restores knowing it meant dp=4), raw sizes
        otherwise. The layout round-trips as its axis names, recorded
        roles verbatim — a restoring process rebuilds the exact specs
        (layouts are pure functions of axes + roles + shapes)."""
        axes = self.axes if self._mesh is None else \
            tuple(self._mesh.shape.items())
        return {
            "axes": [[n, int(s)] for n, s in axes],
            "rules": [[pat, [None if e is None else str(e) for e in spec]]
                      for pat, spec in self.rules],
            "batch_axis": self.batch_axis,
            "spec_fn": self.spec_fn is not None,
            "layout": ([self.layout.data_axis, self.layout.fsdp_axis,
                        self.layout.tp_axis]
                       if self.layout is not None else None),
            "roles": self.roles,
            "zero_axis": self.zero_axis(),
        }

    # -- mesh --------------------------------------------------------------
    @property
    def mesh(self):
        """The built jax Mesh (lazy; -1 sizes infer from device count)."""
        if self._mesh is None:
            import jax

            devices = self._devices
            if devices is None:
                devices = list(jax.devices())
                product = 1
                fixed = all(s != -1 for _, s in self.axes)
                for _, s in self.axes:
                    if s != -1:
                        product *= s
                if fixed and product < len(devices):
                    # dp=4 on an 8-device host: take the leading subset
                    devices = devices[:product]
            self._mesh = make_mesh(dict(self.axes), devices)
        return self._mesh

    def axis_sizes(self):
        """{axis: resolved size} — builds the mesh if needed."""
        return dict(self.mesh.shape)

    def process_coords(self):
        """This process's coordinates on the mesh: the position of its
        first local device, as {axis: index}. Single-process meshes are
        at the origin by construction."""
        import jax
        import numpy as _np

        mesh = self.mesh
        local = {id(d) for d in jax.local_devices()}
        ids = _np.vectorize(id)(mesh.devices)
        for idx in _np.ndindex(mesh.devices.shape):
            if ids[idx] in local:
                return {ax: int(i) for ax, i in zip(mesh.axis_names, idx)}
        return {ax: 0 for ax in mesh.axis_names}

    # -- specs -------------------------------------------------------------
    def spec_for(self, name, shape=None):
        """PartitionSpec for one parameter: spec_fn beats the first
        matching rule beats the layout library beats replicated."""
        if self.spec_fn is not None:
            spec = self.spec_fn(name, shape)
            if spec is not None:
                return _as_spec(spec)
        for pat, spec in self._compiled_rules:
            if pat.search(name):
                return spec
        if self.layout is not None:
            from . import layouts as _layouts

            role = (self.roles or {}).get(name)
            if role is None:
                role = _layouts.role_from_name(name, shape)
            if role is not None:
                return self.layout.spec_for_role(
                    role, shape, self.axis_sizes())
        return PartitionSpec()

    def data_spec(self):
        """PartitionSpec for an input batch (leading dim over the data
        axis)."""
        return PartitionSpec(self.batch_axis)

    def shards_params(self, names_shapes):
        """True when any of (name, shape) pairs resolves to a
        non-replicated spec — the tensor/FSDP case. Such plans still
        ride the donated whole-step path (train_step.py compiles the
        step as ONE GSPMD program over this mesh); this predicate picks
        that variant over the replicated-params shard_map body."""
        return any(self.spec_for(n, s) != PartitionSpec()
                   for n, s in names_shapes)

    # -- ZeRO optimizer-state sharding ------------------------------------
    def zero_axis(self):
        """The mesh axis optimizer state shards along (ZeRO), or None.

        The layout's fsdp axis when the mesh carries it (the literal
        axis name ``fsdp`` for layout-less plans), gated by MXTPU_ZERO —
        off means state mirrors its weight's placement verbatim."""
        if not _env.get("MXTPU_ZERO"):
            return None
        fsdp = self.layout.fsdp_axis if self.layout is not None \
            else "fsdp"
        return fsdp if any(n == fsdp for n, _ in self.axes) else None

    def state_spec_for(self, name, shape):
        """PartitionSpec for one optimizer-state leaf mirroring param
        ``name``: the param's own spec, extended along the fsdp axis on
        the first unsharded divisible dim when ZeRO is on. State leaves
        whose shape differs from the weight's (scalar counters) stay
        with the param spec pruned to their rank."""
        spec = self.spec_for(name, shape)
        axis = self.zero_axis()
        if axis is None or shape is None:
            return spec
        from . import layouts as _layouts

        return _layouts.zero_state_spec(spec, shape, self.axis_sizes(),
                                        axis)

    def shards_state(self, names_shapes):
        """True when ZeRO actually shards any state leaf beyond its
        param's own spec (the sharded-bucket layout tpu_dist/checkpoint
        must honor)."""
        if self.zero_axis() is None:
            return False
        return any(self.state_spec_for(n, s) != self.spec_for(n, s)
                   for n, s in names_shapes)

    # -- application -------------------------------------------------------
    def apply(self, params, label="plan"):
        """Place initialized params (+ grads) per this plan; returns the
        mesh. Records the plan table for tools/diagnose.py, bumps
        sharding_plan_applied_total / the per-axis mesh gauges, and
        stamps the mesh shape + this rank's coordinates into the
        flight-recorder identity (tools/fleetctl.py's mesh column)."""
        mesh = self.mesh
        _shard_params(params, mesh, spec_fn=self.spec_for)
        n_dev = mesh.devices.size

        def _factor(spec):
            f = 1
            for entry in spec:
                for ax in (entry if isinstance(entry, tuple)
                           else (entry,)) if entry is not None else ():
                    f *= mesh.shape[ax]
            return max(f, 1)

        table = []
        for name, p in sorted(params.items()):
            spec = self.spec_for(name, p.shape)
            sspec = self.state_spec_for(name, p.shape)
            nbytes = _telemetry.nbytes_of(p.data()._data)
            table.append({"param": name, "spec": str(spec),
                          "bytes_per_device": nbytes // _factor(spec),
                          "state_spec": str(sspec),
                          # per weight-shaped optimizer-state leaf
                          # (momentum, variance, fp32 master) under the
                          # ZeRO layout — diagnose's opt-state column
                          "state_bytes_per_device":
                              nbytes // _factor(sspec)})
        zero = self.zero_axis()
        _LAST_APPLIED[0] = {"plan": self.to_manifest(),
                            "mesh": dict(mesh.shape),
                            "devices": int(n_dev),
                            "zero_axis": zero,
                            "params": table}
        _telemetry.record_sharding_apply(label, dict(mesh.shape),
                                         params=len(table))
        try:
            from ..observability import flight as _flight

            _flight.set_identity(mesh=dict(mesh.shape),
                                 coords=self.process_coords(),
                                 # fleetctl's mesh column: 1/N optimizer
                                 # shard this rank holds under ZeRO
                                 zero_frac=(1.0 / mesh.shape[zero]
                                            if zero else None))
        except Exception:
            pass
        return mesh

    # -- misc --------------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, ShardingPlan)
                and self.axes == other.axes
                and self.rules == other.rules
                and self.batch_axis == other.batch_axis
                and self.spec_fn is other.spec_fn
                and self.layout == other.layout
                and self.roles == other.roles)

    def __hash__(self):
        return hash((self.axes, self.rules, self.batch_axis,
                     self.layout))

    def __repr__(self):
        ax = ",".join(f"{n}={s}" for n, s in self.axes)
        extra = f", rules={len(self.rules)}" if self.rules else ""
        extra += ", spec_fn" if self.spec_fn is not None else ""
        extra += ", layout" if self.layout is not None else ""
        return f"ShardingPlan({ax}{extra})"


def resolve_plan(explicit=None):
    """The one plan-resolution rule Trainer uses (mirrors the
    numerics/kernels/layout one-normalization contract):

      mode 'off'   -> None, always (mesh= and MXTPU_MESH both ignored);
      mode 'auto'  -> the explicit argument, else MXTPU_MESH, else None;
      mode 'plan'  -> the explicit argument only.

    ``explicit`` may be a ShardingPlan, a built jax Mesh (wrapped with
    replicated rules and its own axis names), or any axes spelling
    parse_axes accepts.
    """
    if mode() == "off":
        return None
    plan = explicit
    if plan is not None and not isinstance(plan, ShardingPlan):
        if isinstance(plan, Mesh):
            wrapped = ShardingPlan(dict(plan.shape),
                                   devices=plan.devices.flatten())
            wrapped._mesh = plan
            plan = wrapped
        else:
            plan = ShardingPlan(plan)
    if plan is None and mode() == "auto":
        plan = ShardingPlan.from_env()
    return plan
