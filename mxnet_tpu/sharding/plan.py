"""ShardingPlan — the one object that answers "where does every array
live".

A plan is (mesh axes, per-parameter PartitionSpec rules, batch axis):

    plan = ShardingPlan((("dp", -1),))                    # pure DP
    plan = ShardingPlan((("dp", 4), ("tp", 2)),
                        rules=[(r".*dense.*weight", ("tp", None))])
    plan = ShardingPlan.parse("dp=4,tp=2")                # MXTPU_MESH form

Construction never touches devices; :meth:`mesh` builds the
``jax.sharding.Mesh`` lazily (``-1`` sizes infer from the device count,
a fully-specified product smaller than the host's device count takes a
leading subset — ``dp=4`` on an 8-device host is legal). :meth:`apply`
places initialized Gluon parameters (and their grad buffers) via
``parallel.mesh.shard_params`` with :meth:`spec_for` as the spec_fn.

Spec-rule precedence (docs/sharding.md):
  1. ``spec_fn(name, shape)`` — a non-None return wins outright;
  2. the first matching regex in ``rules`` (searched, in order);
  3. replicated (``PartitionSpec()``) — the bitwise-identical default,
     so a plan with no rules is exactly data parallelism.

``mode()`` is the ONE normalization of MXTPU_SHARDING — Trainer's plan
resolution and the pass-pipeline injection both read it, so a value
that resolves no plan here also injects no ShardingPass there:

  off   the subsystem is disabled: ``mesh=`` arguments and MXTPU_MESH
        are ignored, nothing is placed — bitwise-identical to main;
  auto  (default) a plan comes from explicit Trainer arguments, else
        from the MXTPU_MESH env spelling;
  plan  explicit arguments only — MXTPU_MESH is ignored, so a launch
        script's env mesh cannot override a hand-built plan.

Checkpoint contract: :meth:`to_manifest`/:meth:`from_manifest`
round-trip the plan as JSON (``spec_fn`` is recorded as a flag only —
callables don't serialize); ``checkpoint/snapshot.py`` stores it in
every manifest and re-places restored arrays onto the RESTORING
trainer's plan, so replicated↔dp↔dp×tp moves are just save + restore.
"""
from __future__ import annotations

import re

from jax.sharding import Mesh, PartitionSpec

from .. import env as _env
from ..parallel.mesh import ShardingError, make_mesh
from ..parallel.mesh import shard_params as _shard_params
from ..telemetry import instruments as _telemetry

__all__ = ["ShardingPlan", "ShardingError", "mode", "parse_axes",
           "resolve_plan", "last_applied"]

# same normalization table discipline as layout/kernels/numerics mode():
# the ONE place MXTPU_SHARDING is interpreted
_MODES = {
    "": "off", "0": "off", "off": "off", "false": "off", "no": "off",
    "none": "off",
    "1": "auto", "auto": "auto", "on": "auto", "true": "auto",
    "yes": "auto",
    "plan": "plan", "explicit": "plan",
}


def mode():
    """Resolved MXTPU_SHARDING mode: 'off' | 'auto' | 'plan'."""
    raw = str(_env.get("MXTPU_SHARDING")).strip().lower()
    try:
        return _MODES[raw]
    except KeyError:
        raise ValueError(
            f"MXTPU_SHARDING={raw!r} is not a recognized mode; expected "
            f"off | auto | plan") from None


def parse_axes(spec):
    """Normalize a mesh-axes spelling to (("name", size), ...).

    Accepts the MXTPU_MESH string form ('dp=-1', 'dp=4,tp=2'), a dict,
    or a sequence of (name, size) pairs. Sizes must be positive ints or
    -1 (infer from device count); anything else raises ShardingError.
    """
    if isinstance(spec, str):
        pairs = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ShardingError(
                    f"mesh axis {part!r} is not 'name=size' "
                    f"(MXTPU_MESH spelling, e.g. 'dp=-1' or 'dp=4,tp=2')")
            name, _, size = part.partition("=")
            try:
                size = int(size.strip())
            except ValueError:
                raise ShardingError(
                    f"mesh axis {part!r}: size {size.strip()!r} is not "
                    f"an integer") from None
            pairs.append((name.strip(), size))
    elif isinstance(spec, dict):
        pairs = list(spec.items())
    else:
        pairs = [(str(n), int(s)) for n, s in spec]
    if not pairs:
        raise ShardingError("mesh spec names no axes")
    seen = set()
    for name, size in pairs:
        if not name:
            raise ShardingError("mesh axis with an empty name")
        if name in seen:
            raise ShardingError(f"mesh axis {name!r} appears twice")
        seen.add(name)
        if size != -1 and size < 1:
            raise ShardingError(
                f"mesh axis {name!r}: size must be a positive int or -1 "
                f"(infer), got {size}")
    return tuple((str(n), int(s)) for n, s in pairs)


def _as_spec(entry):
    """A rule's spec spelling -> PartitionSpec: already a spec, None
    (replicated), or a sequence of axis-name/None entries."""
    if entry is None:
        return PartitionSpec()
    if isinstance(entry, PartitionSpec):
        return entry
    return PartitionSpec(*entry)


# last applied plan + its param table — observability state only
# (tools/diagnose.py --passes reads it); pass injection never consults
# this, it is driven by the PassContext's own plan field
_LAST_APPLIED = [None]


def last_applied():
    """{'plan': manifest, 'params': [...]} of the most recent
    :meth:`ShardingPlan.apply` in this process, or None."""
    return _LAST_APPLIED[0]


class ShardingPlan:
    """Mesh axes + per-parameter placement rules (docs/sharding.md)."""

    def __init__(self, axes, rules=None, spec_fn=None, batch_axis=None,
                 devices=None):
        self.axes = parse_axes(axes)
        self.rules = tuple(
            (str(pat), _as_spec(spec)) for pat, spec in (rules or ()))
        self.spec_fn = spec_fn
        # the data-parallel axis batches shard over; default: first axis
        self.batch_axis = str(batch_axis) if batch_axis is not None \
            else self.axes[0][0]
        if self.batch_axis not in {n for n, _ in self.axes}:
            raise ShardingError(
                f"batch_axis {self.batch_axis!r} is not a mesh axis "
                f"(mesh has {tuple(n for n, _ in self.axes)})")
        self._devices = list(devices) if devices is not None else None
        self._mesh = None
        self._compiled_rules = [(re.compile(pat), spec)
                                for pat, spec in self.rules]

    # -- construction helpers ---------------------------------------------
    @classmethod
    def parse(cls, spec, **kw):
        """Plan from the MXTPU_MESH axis-spec string ('dp=4,tp=2')."""
        return cls(parse_axes(spec), **kw)

    @classmethod
    def from_env(cls):
        """Plan from MXTPU_MESH, or None when the env names no mesh."""
        raw = str(_env.get("MXTPU_MESH")).strip()
        if not raw:
            return None
        return cls.parse(raw)

    @classmethod
    def from_manifest(cls, d):
        """Inverse of :meth:`to_manifest`. The spec_fn flag is restored
        as None — callables don't serialize; rules and axes round-trip
        exactly."""
        if d is None:
            return None
        return cls(
            tuple((str(n), int(s)) for n, s in d["axes"]),
            rules=[(pat, tuple(e if e is None else str(e) for e in spec))
                   for pat, spec in d.get("rules") or ()],
            batch_axis=d.get("batch_axis"))

    def to_manifest(self):
        """JSON-able plan record for checkpoint manifests: axes with
        their RESOLVED sizes when a mesh was built (so a dp=-1 plan
        saved on 4 devices restores knowing it meant dp=4), raw sizes
        otherwise."""
        axes = self.axes if self._mesh is None else \
            tuple(self._mesh.shape.items())
        return {
            "axes": [[n, int(s)] for n, s in axes],
            "rules": [[pat, [None if e is None else str(e) for e in spec]]
                      for pat, spec in self.rules],
            "batch_axis": self.batch_axis,
            "spec_fn": self.spec_fn is not None,
        }

    # -- mesh --------------------------------------------------------------
    @property
    def mesh(self):
        """The built jax Mesh (lazy; -1 sizes infer from device count)."""
        if self._mesh is None:
            import jax

            devices = self._devices
            if devices is None:
                devices = list(jax.devices())
                product = 1
                fixed = all(s != -1 for _, s in self.axes)
                for _, s in self.axes:
                    if s != -1:
                        product *= s
                if fixed and product < len(devices):
                    # dp=4 on an 8-device host: take the leading subset
                    devices = devices[:product]
            self._mesh = make_mesh(dict(self.axes), devices)
        return self._mesh

    def axis_sizes(self):
        """{axis: resolved size} — builds the mesh if needed."""
        return dict(self.mesh.shape)

    def process_coords(self):
        """This process's coordinates on the mesh: the position of its
        first local device, as {axis: index}. Single-process meshes are
        at the origin by construction."""
        import jax
        import numpy as _np

        mesh = self.mesh
        local = {id(d) for d in jax.local_devices()}
        ids = _np.vectorize(id)(mesh.devices)
        for idx in _np.ndindex(mesh.devices.shape):
            if ids[idx] in local:
                return {ax: int(i) for ax, i in zip(mesh.axis_names, idx)}
        return {ax: 0 for ax in mesh.axis_names}

    # -- specs -------------------------------------------------------------
    def spec_for(self, name, shape=None):
        """PartitionSpec for one parameter: spec_fn beats the first
        matching rule beats replicated."""
        if self.spec_fn is not None:
            spec = self.spec_fn(name, shape)
            if spec is not None:
                return _as_spec(spec)
        for pat, spec in self._compiled_rules:
            if pat.search(name):
                return spec
        return PartitionSpec()

    def data_spec(self):
        """PartitionSpec for an input batch (leading dim over the data
        axis)."""
        return PartitionSpec(self.batch_axis)

    def shards_params(self, names_shapes):
        """True when any of (name, shape) pairs resolves to a
        non-replicated spec — the tensor-parallel case the whole-step
        shard_map path cannot host (its in_specs replicate params; XLA's
        GSPMD path carries tp instead)."""
        return any(self.spec_for(n, s) != PartitionSpec()
                   for n, s in names_shapes)

    # -- application -------------------------------------------------------
    def apply(self, params, label="plan"):
        """Place initialized params (+ grads) per this plan; returns the
        mesh. Records the plan table for tools/diagnose.py, bumps
        sharding_plan_applied_total / the per-axis mesh gauges, and
        stamps the mesh shape + this rank's coordinates into the
        flight-recorder identity (tools/fleetctl.py's mesh column)."""
        mesh = self.mesh
        _shard_params(params, mesh, spec_fn=self.spec_for)
        n_dev = mesh.devices.size
        table = []
        for name, p in sorted(params.items()):
            spec = self.spec_for(name, p.shape)
            factor = 1
            for entry in spec:
                for ax in (entry if isinstance(entry, tuple)
                           else (entry,)) if entry is not None else ():
                    factor *= mesh.shape[ax]
            nbytes = _telemetry.nbytes_of(p.data()._data)
            table.append({"param": name, "spec": str(spec),
                          "bytes_per_device": nbytes // max(factor, 1)})
        _LAST_APPLIED[0] = {"plan": self.to_manifest(),
                            "mesh": dict(mesh.shape),
                            "devices": int(n_dev),
                            "params": table}
        _telemetry.record_sharding_apply(label, dict(mesh.shape),
                                         params=len(table))
        try:
            from ..observability import flight as _flight

            _flight.set_identity(mesh=dict(mesh.shape),
                                 coords=self.process_coords())
        except Exception:
            pass
        return mesh

    # -- misc --------------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, ShardingPlan)
                and self.axes == other.axes
                and self.rules == other.rules
                and self.batch_axis == other.batch_axis
                and self.spec_fn is other.spec_fn)

    def __hash__(self):
        return hash((self.axes, self.rules, self.batch_axis))

    def __repr__(self):
        ax = ",".join(f"{n}={s}" for n, s in self.axes)
        extra = f", rules={len(self.rules)}" if self.rules else ""
        extra += ", spec_fn" if self.spec_fn is not None else ""
        return f"ShardingPlan({ax}{extra})"


def resolve_plan(explicit=None):
    """The one plan-resolution rule Trainer uses (mirrors the
    numerics/kernels/layout one-normalization contract):

      mode 'off'   -> None, always (mesh= and MXTPU_MESH both ignored);
      mode 'auto'  -> the explicit argument, else MXTPU_MESH, else None;
      mode 'plan'  -> the explicit argument only.

    ``explicit`` may be a ShardingPlan, a built jax Mesh (wrapped with
    replicated rules and its own axis names), or any axes spelling
    parse_axes accepts.
    """
    if mode() == "off":
        return None
    plan = explicit
    if plan is not None and not isinstance(plan, ShardingPlan):
        if isinstance(plan, Mesh):
            wrapped = ShardingPlan(dict(plan.shape),
                                   devices=plan.devices.flatten())
            wrapped._mesh = plan
            plan = wrapped
        else:
            plan = ShardingPlan(plan)
    if plan is None and mode() == "auto":
        plan = ShardingPlan.from_env()
    return plan
