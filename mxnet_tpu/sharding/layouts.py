"""SpecLayout — named placement rules for the stock Gluon blocks.

PR 12's ``ShardingPlan`` made placement expressible (mesh axes +
per-param PartitionSpec regex rules); this module makes it *nameable*:
a :class:`SpecLayout` maps each structural role a parameter can play —
embedding table, qkv/attention projection, FFN in/out matmul, norm
scale, conv filter — onto the ``data``/``fsdp``/``tp`` mesh axes, so a
hybrid plan is spelled ``ShardingPlan.from_layout("dp=2,fsdp=2,tp=2",
net=net)`` (or just ``MXTPU_MESH=dp=2,fsdp=2,tp=2``) instead of a
hand-written regex per weight.

Role resolution prefers STRUCTURE over names: :func:`block_roles` walks
a block tree and classifies each parameter by its owner block's type
(``Embedding``/``Dense``/``Conv*``/norm layers) and shape (a ``Dense``
growing its feature dim is the FFN "up" projection, one shrinking it is
"down"), falling back to :func:`role_from_name` token matching
(``q_proj``/``k_proj``/``v_proj``/``out_proj``/...) for attention
projections and for env-driven plans that never see the net.

Specs degrade safely: :meth:`SpecLayout.spec_for_role` prunes axes the
mesh doesn't carry and drops sharded axes whose product does not divide
the dimension, so an indivisible weight replicates instead of raising.
Precedence inside a plan stays ``spec_fn > regex rules > layout >
replicated`` — existing hand-written rules always win on conflict.

``zero_state_spec`` is the ZeRO companion contract: extend a param's
spec by sharding optimizer state (momentum/variance/fp32 masters) along
the fsdp axis on the first unsharded divisible dim, so each rank owns
1/N of optimizer memory (docs/sharding.md).

:data:`RECIPES` promotes the ``MULTICHIP_r05.json`` dryrun
configurations into user-facing plan recipes
(``plan_recipe("dp4_tp2")``); tests/test_sharding_layouts.py holds each
to the dryrun bar of >= 99.5% partition efficiency on an 8-device mesh.
"""
from __future__ import annotations

from dataclasses import dataclass

from jax.sharding import PartitionSpec

__all__ = ["SpecLayout", "DEFAULT_LAYOUT", "ROLES", "block_roles",
           "role_from_name", "zero_state_spec", "RECIPES", "plan_recipe"]

#: every structural role the library knows how to place
ROLES = ("embedding", "qkv_projection", "attn_output", "ffn_up",
         "ffn_down", "norm", "conv", "bias")

# name tokens that mark a Dense as an attention projection; checked
# against the '.'-separated structured path, lowercased
_QKV_TOKENS = ("q_proj", "k_proj", "v_proj", "qkv", "query", "key",
               "value", "in_proj")
_ATTN_OUT_TOKENS = ("o_proj", "out_proj", "attn_out", "proj_out")


@dataclass(frozen=True)
class SpecLayout:
    """Role -> PartitionSpec over named ``data``/``fsdp``/``tp`` axes.

    The per-role methods return the IDEAL spec (every axis the role can
    use); :meth:`spec_for_role` prunes it against a concrete mesh and a
    concrete shape. Dense weights are ``(out_units, in_units)`` — the
    Gluon convention — so "column parallel" (split the output features,
    no collective in forward) shards dim 0 over tp and "row parallel"
    (split the contraction, psum after) shards dim 1 over tp.
    """

    data_axis: str = "dp"
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"

    def embedding(self):
        """Vocab dim over fsdp x tp jointly; feature dim replicated."""
        return PartitionSpec((self.fsdp_axis, self.tp_axis), None)

    def qkv_projection(self):
        """Column parallel: heads split over tp, fsdp on the in dim."""
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def attn_output(self):
        """Row parallel: the contraction splits over tp (psum after)."""
        return PartitionSpec(self.fsdp_axis, self.tp_axis)

    def ffn_up(self):
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def ffn_down(self):
        return PartitionSpec(self.fsdp_axis, self.tp_axis)

    def norm(self):
        """1-d scale/shift/running stats: fsdp only (tiny, tp-replicated
        so every tp rank can apply them locally)."""
        return PartitionSpec(self.fsdp_axis)

    def conv(self):
        """OIHW filters: output channels over tp x fsdp, spatial whole."""
        return PartitionSpec((self.tp_axis, self.fsdp_axis), None,
                             None, None)

    def bias(self):
        """Biases replicate — sharding O(units) vectors buys nothing and
        every tp shard of the matmul output needs the full slice."""
        return PartitionSpec()

    # -- mesh/shape-aware resolution --------------------------------------
    def spec_for_role(self, role, shape=None, axis_sizes=None):
        """The role's spec pruned to a concrete mesh and shape.

        Axes the mesh doesn't carry are dropped; within one dim, sharded
        axes are then dropped right-to-left until their product divides
        the dim extent (unknown shapes skip the divisibility check — the
        mesh.shard_params divisibility error stays the backstop). A spec
        pruned down to nothing is the replicated spec.
        """
        ideal = getattr(self, role)()
        if axis_sizes is None and shape is None:
            return ideal
        entries = []
        for d, entry in enumerate(ideal):
            if entry is None:
                entries.append(None)
                continue
            axes = list(entry) if isinstance(entry, tuple) else [entry]
            if axis_sizes is not None:
                axes = [a for a in axes if a in axis_sizes]
            if shape is not None and d < len(shape) and \
                    axis_sizes is not None:
                while axes:
                    prod = 1
                    for a in axes:
                        prod *= axis_sizes[a]
                    if prod and shape[d] % prod == 0:
                        break
                    axes.pop()
            if not axes:
                entries.append(None)
            elif len(axes) == 1:
                entries.append(axes[0])
            else:
                entries.append(tuple(axes))
        while entries and entries[-1] is None:
            entries.pop()
        return PartitionSpec(*entries)

    def model_axes(self):
        """The non-batch axes this layout places over."""
        return (self.fsdp_axis, self.tp_axis)


DEFAULT_LAYOUT = SpecLayout()


def _tokens(name):
    return name.lower().replace("_", ".").split(".")


def role_from_name(name, shape=None):
    """Structural role guessed from a parameter's structured name alone
    (the env-driven path, where no block tree is in hand), or None.

    Mirrors SNIPPETS.md [3]'s ``parameter_spec_from_name`` heuristic,
    extended with the Gluon spellings (gamma/beta, conv weights by
    4-d shape).
    """
    low = name.lower()
    toks = set(_tokens(name))
    leaf = name.rsplit(".", 1)[-1].lower()
    if leaf in ("gamma", "beta", "running_mean", "running_var"):
        return "norm"
    if leaf == "bias":
        return "bias"
    if "embedding" in low or "embed" in toks:
        return "embedding"
    if any(t in low for t in _QKV_TOKENS):
        return "qkv_projection"
    if any(t in low for t in _ATTN_OUT_TOKENS):
        return "attn_output"
    if leaf == "weight":
        if shape is not None and len(shape) >= 3:
            return "conv"
        if "conv" in low:
            return "conv"
        if shape is not None and len(shape) == 2:
            return "ffn_up" if shape[0] >= shape[1] else "ffn_down"
    return None


def _block_role(block, pname, param, path):
    """Role of one directly-registered param of a leaf block."""
    from ..gluon import nn as _nn

    shape = getattr(param, "shape", None)
    if isinstance(block, _nn.Embedding):
        return "embedding"
    norm_types = (_nn.BatchNorm, _nn.LayerNorm, _nn.GroupNorm,
                  _nn.InstanceNorm)
    if isinstance(block, norm_types):
        return "norm"
    if pname == "bias":
        return "bias"
    conv_base = getattr(_nn.conv_layers, "_Conv", ())
    if isinstance(block, conv_base):
        return "conv"
    if isinstance(block, _nn.Dense) and pname == "weight":
        low = path.lower()
        if any(t in low for t in _QKV_TOKENS):
            return "qkv_projection"
        if any(t in low for t in _ATTN_OUT_TOKENS):
            return "attn_output"
        if shape is not None and len(shape) == 2 and shape[1] > 0:
            return "ffn_up" if shape[0] >= shape[1] else "ffn_down"
        return "ffn_up"
    return role_from_name(path, shape)


def block_roles(net):
    """{structured param name: role} for a block tree, structure first.

    Walks ``_children`` exactly like ``collect_params`` builds its
    prefixes, classifying each leaf block's own params by block TYPE
    (Embedding/Dense/Conv/norms) with the name heuristic as tiebreak
    for attention projections; params the walk can't place are omitted
    (the plan replicates them).
    """
    roles = {}

    def walk(block, prefix):
        for pname, p in getattr(block, "_reg_params", {}).items():
            path = prefix + pname
            role = _block_role(block, pname, p, path)
            if role is not None:
                roles[path] = role
        for cname, child in getattr(block, "_children", {}).items():
            walk(child, prefix + cname + ".")

    walk(net, "")
    return roles


def zero_state_spec(spec, shape, axis_sizes, fsdp_axis):
    """ZeRO: a state leaf's spec — the param spec extended by sharding
    along ``fsdp_axis`` on the FIRST dim that is unsharded and divisible.

    Params already fsdp-sharded (the layout's matmul weights) keep their
    spec verbatim: their state is already 1/N. Returns ``spec``
    unchanged when no dim qualifies (a scalar, or nothing divides)."""
    if fsdp_axis not in (axis_sizes or {}):
        return spec
    used = set()
    entries = list(spec)
    for entry in entries:
        for ax in (entry if isinstance(entry, tuple) else (entry,)) \
                if entry is not None else ():
            used.add(ax)
    if fsdp_axis in used:
        return spec
    n = axis_sizes[fsdp_axis]
    entries += [None] * (len(shape) - len(entries))
    for d, entry in enumerate(entries):
        if entry is None and shape[d] % n == 0 and shape[d] > 0:
            entries[d] = fsdp_axis
            return PartitionSpec(*entries)
    return spec


# -- promoted MULTICHIP_r05 plan recipes -------------------------------------
# The r05 dryrun validated mesh dp=4 tp=2 (+ ring-attention over tp,
# 8-expert MoE, 8-stage pipeline as parallel/-module companions) at
# >= 99.5% partition efficiency on 8 chips. Each entry here is the
# user-facing spelling of one validated topology: axes + the layout +
# which companion subsystem (if any) completes it.
RECIPES = {
    "dp8": {
        "axes": "dp=-1",
        "layout": False,
        "note": "pure data parallelism; params replicate, the donated "
                "whole-step shard_map path carries the batch",
    },
    "dp4_tp2": {
        "axes": "dp=4,tp=2",
        "layout": True,
        "note": "the MULTICHIP_r05 dryrun mesh: batch over dp, matmul "
                "weights column/row-split over tp by structural role",
    },
    "dp2_fsdp2_tp2": {
        "axes": "dp=2,fsdp=2,tp=2",
        "layout": True,
        "note": "full hybrid: data x fsdp x tensor; optimizer state "
                "ZeRO-shards along fsdp (MXTPU_ZERO)",
    },
    "fsdp4": {
        "axes": "dp=2,fsdp=4",
        "layout": True,
        "note": "ZeRO-heavy: 4-way optimizer-state sharding, ~1/4 "
                "optimizer memory per device (bench opt_state_mb_per_dev)",
    },
    "ring_sp8": {
        "axes": "dp=4,tp=2",
        "layout": True,
        "companion": "parallel.ring_attention over the tp axis "
                     "(sp=ring in the r05 dryrun)",
        "note": "long-context: sequence streams around the tp ring",
    },
    "moe_ep8": {
        "axes": "dp=-1",
        "layout": False,
        "companion": "parallel.moe with experts sharded over the data "
                     "axis (ep=8 in the r05 dryrun)",
        "note": "expert parallelism; router replicates, experts shard",
    },
    "pipeline_pp8": {
        "axes": "dp=-1",
        "layout": False,
        "companion": "parallel.pipeline with 8 stages x 16 microbatches "
                     "(pp=8x16 in the r05 dryrun)",
        "note": "pipeline parallelism via the interleaved 1F1B schedule",
    },
}


def plan_recipe(name, net=None, **kw):
    """A ShardingPlan from a promoted MULTICHIP recipe by name.

    ``net`` (optional) upgrades role resolution from name tokens to the
    structural block walk. Extra kwargs pass through to the plan
    (rules=, batch_axis=, devices=...).
    """
    from .plan import ShardingPlan

    try:
        recipe = RECIPES[name]
    except KeyError:
        raise KeyError(
            f"unknown plan recipe {name!r}; have "
            f"{sorted(RECIPES)}") from None
    if recipe["layout"]:
        return ShardingPlan.from_layout(recipe["axes"], net=net, **kw)
    return ShardingPlan(recipe["axes"], **kw)
