"""Fused BatchNorm-training Pallas kernels: the r5 audit's two worst
regions (BN-statistics forward, BN backward) as hand-fused TPU kernels.

The XLA path (ops/nn.py `_bn_train`) is already a custom-VJP two-pass
design, but XLA materializes the f32-centered population at the
sum/sum² reduce boundary — the audit's single largest source of f32 HBM
traffic.  These kernels keep the statistics in VMEM scratch instead:

  forward   grid (2, M/bm): phase 0 streams x blocks once, accumulating
            Σ(x−shift) and Σ(x−shift)² per channel in f32 scratch;
            phase 1 streams x again, computes mean/var/inv from the
            finished sums and writes the normalized output — two HBM
            reads of x, one write of out, nothing else big.
  backward  same two-phase shape for dbeta/dgamma then dx.

The math mirrors `_bn_train_impl` / `_bn_train_bwd` line for line (same
shifted-variance form, same MXTPU_BN_COMPUTE elementwise dtype, f32
accumulators) — parity is allclose, not bitwise, only because the
blocked reduction order differs from XLA's.

`bn_train` is the drop-in custom_vjp twin of `_bn_train`: same
signature, same residuals, same (dx, dgamma, dbeta, 0·shift) cotangent
contract.  Unsupported shape/dtype (C % 128, rows % 8, non-float) falls back to
the exact XLA implementation inside the same wrapper, recording the
outcome via kernels.dispatch; a channel-axis-not-last site that would
otherwise qualify records "channels_first" — the LayoutPass
(MXTPU_LAYOUT) exists to turn those into kernel hits.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from . import dispatch as _dispatch

__all__ = ["bn_train"]


def _nn():
    from ..ops import nn
    return nn


def _block_rows(m, c):
    """Largest power-of-two row-block dividing m that keeps one (bm, C)
    block (plus its f32 working copies) comfortably inside VMEM."""
    cap = max(8, (1 << 21) // max(1, 4 * c))
    for cand in (1024, 512, 256, 128, 64, 32, 16, 8):
        if cand <= cap and m % cand == 0:
            return cand
    return 8


def _supported(x, axis):
    """None when the kernel pair can run on this site, else the fallback
    outcome name (the docs/kernels.md taxonomy).

    "channels_first" singles out the sites where ONLY the layout — not
    the size or dtype — blocks the kernel: the same tensor with its
    channel axis moved last would qualify.  These are exactly the sites
    the LayoutPass (MXTPU_LAYOUT, passes/layout.py) converts, so the
    fusion-audit coverage numbers distinguish "needs NHWC" from
    "genuinely unkernelable"."""
    if x.ndim < 2:
        return "unsupported_shape"
    if axis != x.ndim - 1:
        c = x.shape[axis] if 0 <= axis < x.ndim else 0
        m = x.size // c if c else 0
        if (c and c % 128 == 0 and c <= 8192 and m >= 8 and m % 8 == 0
                and x.dtype in (jnp.float32, jnp.bfloat16)):
            return "channels_first"
        return "unsupported_shape"
    c = x.shape[-1]
    m = x.size // c if c else 0
    if c == 0 or c % 128 or c > 8192 or m < 8 or m % 8:
        return "unsupported_shape"
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return "unsupported_dtype"
    return None


def _decide(x, axis):
    """(use_kernel, outcome, bytes_saved, xla_bytes, kernel_bytes) for
    one BN training site; the byte scores are None when the ladder
    exits before reaching the analytic model. Records nothing — callers
    record under their kernel name."""
    mode = _dispatch.mode()
    if mode == "off":
        return False, "off", 0, None, None
    reason = _supported(x, axis)
    if reason is not None:
        return False, reason, 0, None, None
    if not _dispatch.platform_ok():
        return False, "platform", 0, None, None
    from ..passes import memory as _memory
    ew = _nn()._bn_ew_dtype(x)
    xla_b, k_b = _memory.norm_region_bytes(x.shape, x.dtype, ew)
    if mode == "force":
        return True, "kernel", max(0, xla_b - k_b), xla_b, k_b
    ok, outcome, saved = _dispatch.auto_accepts(xla_b, k_b)
    return ok, outcome, saved, xla_b, k_b


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, gamma_ref, beta_ref, shift_ref,
                out_ref, mean_ref, var_ref, inv_ref, s1_ref, s2_ref, *,
                ew, n, eps):
    import jax.experimental.pallas as pl

    phase = pl.program_id(0)
    m_idx = pl.program_id(1)

    @pl.when((phase == 0) & (m_idx == 0))
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    sh = shift_ref[...]                       # (1, C) f32
    s_ew = sh.astype(ew)
    xf = x_ref[...].astype(ew) - s_ew         # (bm, C)

    @pl.when(phase == 0)
    def _accumulate():
        xf32 = xf.astype(jnp.float32)
        s1_ref[...] += jnp.sum(xf, axis=0, keepdims=True,
                               dtype=jnp.float32)
        s2_ref[...] += jnp.sum(xf32 * xf32, axis=0, keepdims=True,
                               dtype=jnp.float32)
        # phase 0 visits every out block before phase 1 rewrites it;
        # write zeros so the buffer never round-trips undefined bytes
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(phase == 1)
    def _normalize():
        s1 = s1_ref[...]
        s2 = s2_ref[...]
        mean_c = s1 / n
        var = jnp.maximum(s2 / n - mean_c * mean_c, 0.0)
        inv = lax.rsqrt(var + eps)
        g32 = gamma_ref[...]
        scale = g32 * inv
        offset = beta_ref[...] - mean_c * g32 * inv
        out_ref[...] = (xf * scale.astype(ew)
                        + offset.astype(ew)).astype(out_ref.dtype)
        mean_ref[...] = mean_c + s_ew.astype(jnp.float32)
        var_ref[...] = var
        inv_ref[...] = inv


def _fwd_pallas(x, gamma, beta, shift, eps):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c = x.shape[-1]
    m = x.size // c
    x2 = x.reshape(m, c)
    g32 = gamma.astype(jnp.float32).reshape(1, c)
    b32 = beta.astype(jnp.float32).reshape(1, c)
    sh32 = lax.stop_gradient(shift.astype(jnp.float32)).reshape(1, c)
    ew = _nn()._bn_ew_dtype(x)
    bm = _block_rows(m, c)
    row = pl.BlockSpec((1, c), lambda p, i: (0, 0))
    out, mean, var, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, ew=ew, n=m, eps=eps),
        grid=(2, m // bm),
        in_specs=[
            pl.BlockSpec((bm, c), lambda p, i: (i, 0)),
            row, row, row,
        ],
        out_specs=[
            pl.BlockSpec((bm, c), lambda p, i: (i, 0)),
            row, row, row,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),   # Σ(x−shift)
            pltpu.VMEM((1, c), jnp.float32),   # Σ(x−shift)²
        ],
        interpret=_dispatch.interpret_requested(),
    )(x2, g32, b32, sh32)
    return (out.reshape(x.shape), mean.reshape(c), var.reshape(c),
            inv.reshape(c))


# ---------------------------------------------------------------------------
# backward kernel
# ---------------------------------------------------------------------------


def _bwd_kernel(x_ref, dy_ref, gamma_ref, mean_ref, inv_ref, shift_ref,
                dmean_ref, dvar_ref, dx_ref, dgamma_ref, dbeta_ref,
                db_ref, dg_ref, *, ew, n):
    import jax.experimental.pallas as pl

    phase = pl.program_id(0)
    m_idx = pl.program_id(1)

    @pl.when((phase == 0) & (m_idx == 0))
    def _init():
        db_ref[...] = jnp.zeros_like(db_ref)
        dg_ref[...] = jnp.zeros_like(dg_ref)

    s = shift_ref[...]                            # (1, C) f32
    inv = inv_ref[...]
    xf = x_ref[...].astype(ew) - s.astype(ew)
    mean_c = (mean_ref[...] - s).astype(ew)
    xhat = (xf - mean_c) * inv.astype(ew)
    dyf = dy_ref[...].astype(ew)

    @pl.when(phase == 0)
    def _accumulate():
        db_ref[...] += jnp.sum(dyf, axis=0, keepdims=True,
                               dtype=jnp.float32)
        dg_ref[...] += jnp.sum(dyf * xhat, axis=0, keepdims=True,
                               dtype=jnp.float32)
        dx_ref[...] = jnp.zeros_like(dx_ref)

    @pl.when(phase == 1)
    def _dx():
        dbeta = db_ref[...]
        dgamma = dg_ref[...]
        g32 = gamma_ref[...]
        dx = (g32 * inv).astype(ew) * (
            dyf - (dbeta.astype(ew) + xhat * dgamma.astype(ew)) / n)
        dx = dx + (dmean_ref[...].astype(ew) / n
                   + dvar_ref[...].astype(ew) * 2.0
                   * (xf - mean_c) / n)
        dx_ref[...] = dx.astype(dx_ref.dtype)
        dbeta_ref[...] = dbeta
        dgamma_ref[...] = dgamma


def _bwd_pallas(x, gamma, shift, mean, inv, dy, dmean_ct, dvar_ct):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    c = x.shape[-1]
    m = x.size // c
    x2 = x.reshape(m, c)
    dy2 = dy.reshape(m, c)
    g32 = gamma.astype(jnp.float32).reshape(1, c)
    mean2 = mean.astype(jnp.float32).reshape(1, c)
    inv2 = inv.astype(jnp.float32).reshape(1, c)
    sh32 = lax.stop_gradient(shift.astype(jnp.float32)).reshape(1, c)
    dm2 = dmean_ct.astype(jnp.float32).reshape(1, c)
    dv2 = dvar_ct.astype(jnp.float32).reshape(1, c)
    ew = _nn()._bn_ew_dtype(x)
    bm = _block_rows(m, c)
    row = pl.BlockSpec((1, c), lambda p, i: (0, 0))
    big = pl.BlockSpec((bm, c), lambda p, i: (i, 0))
    dx, dgamma, dbeta = pl.pallas_call(
        functools.partial(_bwd_kernel, ew=ew, n=m),
        grid=(2, m // bm),
        in_specs=[big, big, row, row, row, row, row, row],
        out_specs=[big, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),   # Σ dy
            pltpu.VMEM((1, c), jnp.float32),   # Σ dy·x̂
        ],
        interpret=_dispatch.interpret_requested(),
    )(x2, dy2, g32, mean2, inv2, sh32, dm2, dv2)
    return dx.reshape(x.shape), dgamma.reshape(c), dbeta.reshape(c)


# ---------------------------------------------------------------------------
# the custom_vjp drop-in for ops.nn._bn_train
# ---------------------------------------------------------------------------


def _fwd_impl(x, gamma, beta, shift, eps, axis):
    use_kernel, outcome, saved, xla_b, k_b = _decide(x, axis)
    # the combined fwd+bwd prediction is attributed to the forward
    # dispatch (a site adopts the kernel PAIR or neither)
    _dispatch.record("bn_fwd", outcome, saved, xla_bytes=xla_b,
                     kernel_bytes=k_b)
    if use_kernel:
        return _fwd_pallas(x, gamma, beta, shift, eps)
    return _nn()._bn_train_impl(x, gamma, beta, shift, eps, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def bn_train(x, gamma, beta, shift, eps, axis):
    """Pallas-backed twin of ops.nn._bn_train: (out, mean, var) with the
    identical custom-VJP contract.  Falls back to the XLA implementation
    (same numerics) when the kernel can't run on this site."""
    out, mean, var, _ = _fwd_impl(x, gamma, beta, shift, eps, axis)
    return out, mean, var


def _bn_train_fwd(x, gamma, beta, shift, eps, axis):
    out, mean, var, inv = _fwd_impl(x, gamma, beta, shift, eps, axis)
    return (out, mean, var), (x, gamma, beta, shift, mean, inv)


def _bn_train_bwd(eps, axis, res, cts):
    x, gamma, beta, shift, mean, inv = res
    use_kernel, outcome, _, xla_b, k_b = _decide(x, axis)
    _dispatch.record("bn_bwd", outcome, xla_bytes=xla_b,
                     kernel_bytes=k_b)
    if not use_kernel:
        return _nn()._bn_train_bwd(eps, axis, res, cts)
    dy, dmean_ct, dvar_ct = cts
    dx, dgamma, dbeta = _bwd_pallas(x, gamma, shift, mean, inv, dy,
                                    dmean_ct, dvar_ct)
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            jnp.zeros_like(shift))


bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)
