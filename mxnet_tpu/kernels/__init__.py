"""Hand-fused Pallas TPU kernels for the audited HBM-bandwidth hogs.

Three kernels cover the r5 fusion audit's top external-byte regions:

* ``norm.bn_train`` — one-pass fused batch-norm statistics forward and
  its matching fused backward (custom_vjp twin of ops/nn.py's
  ``_bn_train``);
* ``opt.param_step`` — the fused optimizer ladder (rescale → clip →
  rule → master-copy cast) as one kernel per parameter.

``dispatch`` owns the policy: sites consult it at trace time and fall
back to the XLA path whenever the kernel can't run (wrong platform,
shape, dtype, rule) or — in ``auto`` mode — whenever the
passes/memory.py byte model predicts no bandwidth win.  This package
imports no Pallas machinery at module scope, and the kernel modules
themselves load lazily (PEP 562): a site checking ``dispatch.mode()``
under MXTPU_KERNELS=off imports ``dispatch`` alone — ``norm``/``opt``
never load, which tests/test_kernels.py asserts as part of the
kill-switch contract.
"""
import importlib

__all__ = ["dispatch", "norm", "opt"]


def __getattr__(name):
    if name in __all__:
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
