"""Fused optimizer-ladder Pallas kernel: the PR-4 bucket body — rescale
→ global-norm scale → per-element clip → `cls._rule` → master-copy cast
— as ONE kernel per parameter, one HBM read/write per operand.

The XLA bucket body is already a single fused dispatch, but with
multi-precision the low→f32 grad cast is a widening root (the r5
audit's optimizer-chain region): XLA materializes the f32 grad between
the cast and the update math, an extra read+write of every gradient.
The kernel runs the WHOLE ladder on each VMEM-resident block, so the
f32 grad never exists in HBM.

The optimizer's actual `cls._rule` traces INTO the kernel — the ladder
is generic over any elementwise rule (SGD/NAG/Signum/Adam/AdamW); rules
that couple elements across the tensor (LAMB-style layer norms) are
rejected by the allowlist and fall back.  Hyperparameters (lr, wd, t,
rescale, the rule's own scalars) ride in as one traced SMEM vector, so
LR schedules never retrace — exactly the weak-scalar contract of the
XLA path.  All kernel math is f32 (mp masters, or f32 weights), same op
order as `Optimizer._fused_param_step`; parity is allclose at ~1 ulp —
the kernel body compiles as one fused program (FMA contraction), which
the op-by-op XLA schedule need not match bit-for-bit.

`param_step` is the drop-in twin of `Optimizer._fused_param_step`:
unsupported rule/shape/dtype falls back to it verbatim, recording the
outcome via kernels.dispatch.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import dispatch as _dispatch

__all__ = ["param_step"]

# rules proven elementwise: safe to evaluate per VMEM block
_RULE_ALLOW = frozenset(("SGD", "NAG", "Signum", "Adam", "AdamW"))

_LANE = 128


def _fallback(cls, clip, gn, mp, w, st, g, lr, wd, t, scale, hyper):
    from ..optimizer.optimizer import Optimizer
    return Optimizer._fused_param_step(cls, clip, gn, mp, w, st, g, lr,
                                       wd, t, scale, hyper)


def _supported(cls, mp, w, st, g):
    """None when the ladder kernel can run this parameter, else the
    fallback outcome name."""
    if cls.__name__ not in _RULE_ALLOW:
        return "unsupported_rule"
    size = int(w.size)
    if size < 1024 or size % (8 * _LANE):
        return "unsupported_shape"
    if mp:
        master, inner = st
        if master.dtype != jnp.float32 or master.shape != w.shape:
            return "unsupported_dtype"
        leaves = jax.tree_util.tree_leaves(inner)
    else:
        if w.dtype != jnp.float32 or g.dtype != jnp.float32:
            return "unsupported_dtype"
        leaves = jax.tree_util.tree_leaves(st)
    for leaf in leaves:
        if (getattr(leaf, "shape", None) != w.shape
                or leaf.dtype != jnp.float32):
            return "unsupported_shape"
    return None


def _decide(cls, mp, w, st, g):
    """(use_kernel, outcome, bytes_saved, xla_bytes, kernel_bytes); the
    byte scores are None when the ladder exits before the model."""
    mode = _dispatch.mode()
    if mode == "off":
        return False, "off", 0, None, None
    reason = _supported(cls, mp, w, st, g)
    if reason is not None:
        return False, reason, 0, None, None
    if not _dispatch.platform_ok():
        return False, "platform", 0, None, None
    leaves = jax.tree_util.tree_leaves(st[1] if mp else st)
    from ..passes import memory as _memory
    xla_b, k_b = _memory.optimizer_region_bytes(
        w.size, w.dtype, len(leaves), mp)
    if mode == "force":
        return True, "kernel", max(0, xla_b - k_b), xla_b, k_b
    ok, outcome, saved = _dispatch.auto_accepts(xla_b, k_b)
    return ok, outcome, saved, xla_b, k_b


def _ladder_kernel(scal_ref, w_ref, g_ref, *refs, rule, clip, gn, mp,
                   n_state, hyper_keys, treedef, out_w_dtype):
    state_refs = refs[:n_state]
    outs = refs[n_state:]
    lr = scal_ref[0]
    wd = scal_ref[1]
    t = scal_ref[2]
    rescale = scal_ref[3]
    gscale = scal_ref[4]
    h = {k: scal_ref[5 + j] for j, k in enumerate(hyper_keys)}
    h["t"] = t
    h["rescale_grad"] = rescale
    g = g_ref[...]
    if mp:
        g = g.astype(jnp.float32)
    g = g * rescale
    if gn:
        g = g * gscale
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    st = jax.tree_util.tree_unflatten(
        treedef, [r[...] for r in state_refs])
    nw, ns = rule(w_ref[...], g, st, lr, wd, h)
    ns_leaves = jax.tree_util.tree_leaves(ns)
    if mp:
        outs[0][...] = nw                       # new f32 master
        for r, leaf in zip(outs[1:1 + n_state], ns_leaves):
            r[...] = leaf
        outs[1 + n_state][...] = nw.astype(out_w_dtype)
    else:
        outs[0][...] = nw
        for r, leaf in zip(outs[1:], ns_leaves):
            r[...] = leaf


def _block_rows(m):
    for cand in (1024, 512, 256, 128, 64, 32, 16, 8):
        if m % cand == 0:
            return cand
    return 8


def _ladder_pallas(cls, clip, gn, mp, w, st, g, lr, wd, t, scale, hyper):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if mp:
        master, inner = st
        state_leaves, treedef = jax.tree_util.tree_flatten(inner)
        wv = master
    else:
        state_leaves, treedef = jax.tree_util.tree_flatten(st)
        wv = w
    n_state = len(state_leaves)
    m = w.size // _LANE
    bm = _block_rows(m)

    hyper_keys = tuple(sorted(k for k in hyper
                              if k not in ("rescale_grad", "t")))
    svals = [lr, wd, t, hyper["rescale_grad"],
             scale if gn else 0.0]
    svals += [hyper[k] for k in hyper_keys]
    scal = jnp.stack([jnp.asarray(v, jnp.float32) for v in svals])

    big = pl.BlockSpec((bm, _LANE), lambda i: (i, 0))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    n_out = (2 + n_state) if mp else (1 + n_state)
    out_shape = []
    if mp:
        out_shape.append(jax.ShapeDtypeStruct((m, _LANE), jnp.float32))
    else:
        out_shape.append(jax.ShapeDtypeStruct((m, _LANE), w.dtype))
    out_shape += [jax.ShapeDtypeStruct((m, _LANE), jnp.float32)
                  for _ in range(n_state)]
    if mp:
        out_shape.append(jax.ShapeDtypeStruct((m, _LANE), w.dtype))

    kernel = functools.partial(
        _ladder_kernel,
        rule=cls._rule, clip=clip, gn=gn, mp=mp, n_state=n_state,
        hyper_keys=hyper_keys, treedef=treedef, out_w_dtype=w.dtype)
    outs = pl.pallas_call(
        kernel,
        grid=(m // bm,),
        in_specs=[smem, big, big] + [big] * n_state,
        out_specs=[big] * n_out,
        out_shape=out_shape,
        interpret=_dispatch.interpret_requested(),
    )(scal, wv.reshape(m, _LANE), g.reshape(m, _LANE),
      *[leaf.reshape(m, _LANE) for leaf in state_leaves])

    if mp:
        new_master = outs[0].reshape(w.shape)
        new_inner = jax.tree_util.tree_unflatten(
            treedef, [o.reshape(w.shape) for o in outs[1:1 + n_state]])
        new_w = outs[1 + n_state].reshape(w.shape)
        return new_w, (new_master, new_inner)
    new_w = outs[0].reshape(w.shape)
    new_state = jax.tree_util.tree_unflatten(
        treedef, [o.reshape(w.shape) for o in outs[1:]])
    return new_w, new_state


def param_step(cls, clip, gn, mp, w, st, g, lr, wd, t, scale, hyper):
    """Pallas-backed twin of Optimizer._fused_param_step — one
    parameter's rescale → clip → rule → cast ladder.  Falls back to the
    XLA body (bitwise-identical numerics) when the kernel can't run."""
    use_kernel, outcome, saved, xla_b, k_b = _decide(cls, mp, w, st, g)
    _dispatch.record("opt_" + cls.__name__.lower(), outcome, saved,
                     xla_bytes=xla_b, kernel_bytes=k_b)
    if not use_kernel:
        return _fallback(cls, clip, gn, mp, w, st, g, lr, wd, t, scale,
                         hyper)
    return _ladder_pallas(cls, clip, gn, mp, w, st, g, lr, wd, t, scale,
                          hyper)
