"""Kernel dispatch policy: who decides, and how the decision is audited.

The bandwidth kernels (norm.py, opt.py) never rewrite a traced program —
each eligible CALL SITE (ops/nn.py batch_norm's training branch, the
optimizer's `_fused_step_body` loop) consults this module at trace time
and emits either the Pallas kernel or the existing XLA path into the
program being captured.  That keeps the kill switch trivial and exact:
``MXTPU_KERNELS`` unset/off means no site even looks here, so the
captured programs are bitwise-identical to main with zero extra traces.

The decision ladder (docs/kernels.md has the full table):

  off    site never consulted — the XLA path verbatim;
  force  kernel whenever platform + shape/dtype/rule support allows;
  auto   additionally require the passes/memory.py analytic byte model
         to predict an external-HBM saving — the decision is the byte
         model's, not a hardcode: sites where the model finds no
         widening/reduce root to kill (pure-f32 optimizer chains, tiny
         tensors) keep the XLA path with outcome 'no_savings' /
         'too_small'.

Every consult records ONE `kernel_dispatch_total{kernel,outcome}`
sample per trace (never per step); fallbacks also drop a
``kernel_fallback`` flight-recorder event so postmortems show which
path a program compiled with.
"""
from __future__ import annotations

import jax

from .. import env as _env
from ..telemetry import instruments as _telemetry

__all__ = [
    "mode", "platform_ok", "interpret_requested", "record",
    "auto_accepts", "MIN_AUTO_BYTES", "MIN_AUTO_SAVINGS",
]

# auto mode declines sites below this size — kernel launch overhead and
# tiny-region bookkeeping swamp any bandwidth win
MIN_AUTO_BYTES = 1 << 20
# and sites where the model predicts less than this fractional saving
MIN_AUTO_SAVINGS = 0.15

_MODES = {
    "": "off", "0": "off", "off": "off", "false": "off", "no": "off",
    "none": "off",
    "1": "auto", "auto": "auto", "on": "auto", "true": "auto",
    "yes": "auto",
    "force": "force", "always": "force",
}


def mode():
    """Resolved MXTPU_KERNELS mode: 'off' | 'auto' | 'force'."""
    raw = str(_env.get("MXTPU_KERNELS")).strip().lower()
    try:
        return _MODES[raw]
    except KeyError:
        raise ValueError(
            f"MXTPU_KERNELS={raw!r} is not a recognized mode; expected "
            f"off | auto | force") from None


def interpret_requested():
    """MXTPU_KERNELS_INTERPRET: run kernels in Pallas interpret mode so
    they execute off-TPU (parity tests)."""
    return bool(_env.get("MXTPU_KERNELS_INTERPRET"))


def platform_ok():
    """True when Pallas kernels can actually execute here: a TPU-family
    backend, or interpret mode was requested explicitly."""
    if interpret_requested():
        return True
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def auto_accepts(xla_bytes, kernel_bytes):
    """The `auto` decision on one site, given the analytic byte model's
    (xla, kernel) external-bytes estimates.  Returns (ok, reason,
    bytes_saved): reason is 'kernel' on accept, else the fallback
    outcome name."""
    saved = int(xla_bytes) - int(kernel_bytes)
    if xla_bytes < MIN_AUTO_BYTES:
        return False, "too_small", 0
    if xla_bytes <= 0 or saved <= 0 \
            or saved < MIN_AUTO_SAVINGS * xla_bytes:
        return False, "no_savings", 0
    return True, "kernel", saved


def record(kernel, outcome, bytes_saved=0, xla_bytes=None,
           kernel_bytes=None):
    """Record one trace-time decision (telemetry + flight recorder);
    guarded — a broken observability layer must not fail a trace.
    Sites that reached the byte model also pass their (xla, kernel)
    analytic scores so the measurement plane can audit the prediction
    against measured wall time (observability/measure.note_site)."""
    try:
        _telemetry.record_kernel_dispatch(kernel, outcome, bytes_saved)
    except Exception:
        pass
    if xla_bytes is not None or kernel_bytes is not None:
        try:
            from ..observability import measure as _measure

            _measure.note_site(kernel, outcome, xla_bytes=xla_bytes,
                               kernel_bytes=kernel_bytes,
                               bytes_saved=bytes_saved)
        except Exception:
            pass
