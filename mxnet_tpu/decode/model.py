"""TinyCausalLM — the decode block contract, and its reference model.

The DecodeEngine (engine.py) is duck-typed over a small "decode block"
surface, the generation analog of the ``call_cached_graph`` contract the
one-shot serving engine runs on:

    init_cache(num_slots, max_len)        -> KVCache
    prefill(cache, tokens, slot, length)  -> (cache, last_logits)
    step(cache, tokens, active)           -> (cache, logits)
    full_logits(tokens, length)           -> last_logits   (uncached ref)
    jit_trace_count()                     -> int           (retrace proof)

``prefill`` consumes one prompt padded to a seq-len bucket rung
(``tokens`` is ``(L_bucket,)``; positions past ``length`` are pad) and
writes the slot's K/V; ``step`` is THE steady-state program — fixed
``(num_slots,)`` token vector, one position per active slot — so every
decode iteration of every sequence mix hits one compiled executable.
Slot ids, lengths, and token values are traced scalars/arrays (weak
types, never static arguments), so no value ever retraces.

:class:`TinyCausalLM` implements that contract as a deterministic
single-layer causal-attention LM, built for the parity and retrace
proofs in tests/test_decode.py rather than for quality:

  * parameters are drawn on a coarse dyadic grid (multiples of 1/8) so
    the h/K/V/Q projections are EXACT in f32 regardless of reduction
    order — the cached and uncached paths may matmul at different
    shapes, and exact grids make those bitwise-equal anyway;
  * cached attention and the uncached reference share one ``_attend``
    helper over identical ``(max_len,)``-padded operands with the
    KVCache position-mask contract, so their softmax inputs are
    bitwise-identical;
  * every jitted body bumps a host-side trace counter (and the
    ``jit_trace_total`` telemetry series) exactly the way
    gluon.HybridBlock does — ``jit_trace_count()`` is the zero-retrace
    oracle DecodeEngine.warmup() seals against.

Real transformer blocks plug into the engine by exposing the same five
methods over their own stacked-layer caches. See docs/decode.md.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as _np

from ..telemetry import instruments as _instr
from .cache import KVCache

__all__ = ["TinyCausalLM"]


def _grid(rs, shape, scale=0.125, span=4):
    """Deterministic params on the dyadic grid {-span..span} * scale —
    exactly representable in f32, so matmuls over them are
    order-insensitive (the parity proof's foundation)."""
    return (rs.randint(-span, span + 1, shape) * scale).astype(_np.float32)


class TinyCausalLM:
    """Single-layer causal attention LM over a paged :class:`KVCache`.

    ::

        lm = TinyCausalLM(vocab=64, d_model=16, num_heads=2, max_len=64)
        cache = lm.init_cache(num_slots=4, max_len=64)
        cache, logits = lm.prefill(cache, padded_prompt, slot=0, length=5)
        cache, step_logits = lm.step(cache, last_tokens, active)

    All three traced entry points are jitted once per input SIGNATURE:
    ``prefill`` once per seq-len bucket rung, ``step``/``full_logits``
    exactly once. ``name`` labels the telemetry series.
    """

    def __init__(self, vocab=64, d_model=16, num_heads=2, max_len=64,
                 seed=0, name="TinyCausalLM"):
        if d_model % num_heads:
            raise ValueError(f"d_model {d_model} not divisible by "
                             f"num_heads {num_heads}")
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.num_heads = int(num_heads)
        self.head_dim = self.d_model // self.num_heads
        self.max_len = int(max_len)
        self.name = str(name)
        rs = _np.random.RandomState(seed)
        d, v = self.d_model, self.vocab
        self.params = {
            "embed": jnp.asarray(_grid(rs, (v, d))),
            "pos": jnp.asarray(_grid(rs, (self.max_len, d))),
            "wq": jnp.asarray(_grid(rs, (d, d))),
            "wk": jnp.asarray(_grid(rs, (d, d))),
            "wv": jnp.asarray(_grid(rs, (d, d))),
            "wo": jnp.asarray(_grid(rs, (d, d))),
            "wout": jnp.asarray(_grid(rs, (d, v))),
        }
        self._trace_counts = {}
        self._prefill = jax.jit(self._prefill_body)
        self._step = jax.jit(self._step_body)
        self._full = jax.jit(self._full_body)

    # -- trace accounting (the HybridBlock idiom) --------------------------
    def _bump_trace(self, variant):
        # host side effect inside a jitted body: runs once per trace
        # (one XLA compile), never on cache hits — the retrace signal
        # jit_trace_count() and the jit_trace_total series expose
        self._trace_counts[variant] = \
            self._trace_counts.get(variant, 0) + 1
        _instr.record_trace(self.name, variant)

    def jit_trace_count(self, variant=None):
        """Traces (= XLA compiles) so far: one variant's count, or the
        total across prefill/step/full — DecodeEngine.warmup()'s
        zero-retrace oracle."""
        if variant is not None:
            return self._trace_counts.get(variant, 0)
        return sum(self._trace_counts.values())

    # -- shared attention math (the bitwise-parity contract) ---------------
    def _project(self, h):
        """h (..., d) -> (q, k, v) each (..., heads, head_dim)."""
        p = self.params
        shape = h.shape[:-1] + (self.num_heads, self.head_dim)
        return ((h @ p["wq"]).reshape(shape),
                (h @ p["wk"]).reshape(shape),
                (h @ p["wv"]).reshape(shape))

    def _attend(self, q, k, v, bias):
        """One query against a ``(max_len,)``-padded K/V row.

        q ``(heads, head_dim)``; k/v ``(max_len, heads, head_dim)``;
        bias ``(max_len,)`` additive (0 valid / NEG_INF masked). BOTH
        the cached path and the uncached reference come through here
        with identical shapes, so their reductions are bitwise-equal.
        """
        scale = 1.0 / math.sqrt(self.head_dim)
        scores = jnp.einsum("hd,phd->hp", q, k) * scale + bias[None, :]
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("hp,phd->hd", probs, v)

    def _logits(self, h_last, attn):
        """feature = residual + projected attention -> vocab logits."""
        p = self.params
        feat = h_last + attn.reshape(attn.shape[:-2] + (self.d_model,)) \
            @ p["wo"]
        return feat @ p["wout"]

    def _embed(self, tokens, positions):
        p = self.params
        return p["embed"][tokens] + p["pos"][positions]

    # -- traced bodies -----------------------------------------------------
    def _prefill_body(self, cache, tokens, slot, length):
        """tokens (L_bucket,) int32; returns (cache', logits (vocab,))
        for the prompt's LAST valid position — the logits the first
        generated token is sampled from."""
        self._bump_trace("prefill")
        L = tokens.shape[0]
        h = self._embed(tokens, jnp.arange(L))
        q, k, v = self._project(h)
        cache = cache.prefill(slot, k, v, length)
        q_last = jax.lax.dynamic_index_in_dim(
            q, jnp.asarray(length, jnp.int32) - 1, axis=0, keepdims=False)
        h_last = jax.lax.dynamic_index_in_dim(
            h, jnp.asarray(length, jnp.int32) - 1, axis=0, keepdims=False)
        slot = jnp.asarray(slot, jnp.int32)
        attn = self._attend(q_last, cache.k[slot], cache.v[slot],
                            cache.position_mask()[slot])
        return cache, self._logits(h_last, attn)

    def _step_body(self, cache, tokens, active):
        """THE decode step: tokens (num_slots,) int32 (each slot's last
        sampled token), active (num_slots,) bool. Appends one position
        per active slot and returns (cache', logits (num_slots, vocab)).
        Fixed shapes — compiles exactly once."""
        self._bump_trace("step")
        pos = jnp.minimum(cache.lengths, cache.max_len - 1)
        h = self._embed(tokens, pos)                # (slots, d)
        q, k, v = self._project(h)                  # (slots, heads, hd)
        cache = cache.append(k, v, active)
        attn = jax.vmap(self._attend)(q, cache.k, cache.v,
                                      cache.position_mask())
        return cache, self._logits(h, attn)

    def _full_body(self, tokens, length):
        """The UNCACHED reference: recompute the whole prefix from
        scratch (tokens padded to (max_len,)) and return the last valid
        position's logits. Same padded shapes + position-mask contract
        as the cached path, so greedy decode through the cache must
        reproduce it token for token."""
        self._bump_trace("full")
        h = self._embed(tokens, jnp.arange(self.max_len))
        q, k, v = self._project(h)
        length = jnp.asarray(length, jnp.int32)
        pos = jnp.arange(self.max_len)
        bias = jnp.where(pos < length, 0.0, jnp.asarray(-1e30))
        q_last = jax.lax.dynamic_index_in_dim(q, length - 1, axis=0,
                                              keepdims=False)
        h_last = jax.lax.dynamic_index_in_dim(h, length - 1, axis=0,
                                              keepdims=False)
        attn = self._attend(q_last, k, v, bias)
        return self._logits(h_last, attn)

    # -- the decode-block surface ------------------------------------------
    def init_cache(self, num_slots, max_len=None):
        """A fresh paged pool sized for this model's heads."""
        return KVCache.create(num_slots,
                              self.max_len if max_len is None else max_len,
                              self.num_heads, self.head_dim)

    def prefill(self, cache, tokens, slot, length):
        tokens = jnp.asarray(tokens, jnp.int32)
        return self._prefill(cache, tokens, int(slot), int(length))

    def step(self, cache, tokens, active):
        return self._step(cache, jnp.asarray(tokens, jnp.int32),
                          jnp.asarray(active, bool))

    def full_logits(self, tokens, length):
        """Uncached reference logits for ``tokens[:length]`` (padded or
        not — anything shorter than max_len is zero-padded here)."""
        toks = _np.zeros((self.max_len,), _np.int32)
        toks[:len(tokens)] = _np.asarray(tokens, _np.int32)[:self.max_len]
        return self._full(jnp.asarray(toks), int(length))
