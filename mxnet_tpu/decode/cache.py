"""The KV-cache block contract: paged slots carried through the trace.

Autoregressive decode reuses attention keys/values across steps instead
of recomputing the whole prefix per token — the O(L) -> O(1) step-cost
flip that makes token-by-token serving viable. On XLA that reuse has to
respect the jit cache: a cache whose arrays grow with the sequence would
retrace (and recompile) every step. :class:`KVCache` therefore holds a
FIXED pool —

    k, v     : (num_slots, max_len, num_heads, head_dim)
    lengths  : (num_slots,) int32   — valid prefix per slot

— where one serving *sequence* owns one slot row for its lifetime.
Appends advance the slot's length index via ``dynamic_update_slice`` (a
traced scalar index, never a shape); a retiring sequence frees its slot
by zeroing its length, and the next sequence reuses the same row. Every
array shape is static, so slot churn (join / retire / reuse) touches
only VALUES — the decode step compiles exactly once (the zero-retrace
invariant tests/test_decode.py pins via ``jit_trace_total``).

The cache rides the traced body the way the BatchNorm aux pair does
(ops/nn.py): it is a registered pytree whose leaves flow in and out of
jitted programs as ordinary operands, and every write is wrapped in
``lax.stop_gradient`` so a cache threaded through a differentiated
program contributes no gradient paths (custom-VJP-safe: taping through
a decode step can never try to differentiate a cache update).

Masking contract: position ``p`` of slot ``s`` is valid iff
``p < lengths[s]``. :meth:`position_mask` renders that as an additive
bias (0 valid, ``NEG_INF`` invalid) so cached attention and the
padded-to-``max_len`` uncached reference reduce over bitwise-identical
operands — the token-parity proof in tests/test_decode.py depends on
it. See docs/decode.md.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["KVCache", "NEG_INF"]

#: Additive attention-mask value for invalid cache positions. A finite
#: large-negative (not -inf) so masked lanes stay NaN-free through
#: softmax even when a slot is empty.
NEG_INF = -1e30


class KVCache:
    """Paged key/value pool for one attention site.

    Immutable-functional: every mutator returns a NEW KVCache (the JAX
    idiom — inside a jitted body the "copy" is elided by XLA's buffer
    donation/aliasing, outside it is one small dispatch). Slot-assignment
    bookkeeping (which sequence owns which slot) lives host-side in the
    DecodeEngine; the cache itself only knows per-slot valid lengths.
    """

    __slots__ = ("k", "v", "lengths")

    def __init__(self, k, v, lengths):
        self.k = k
        self.v = v
        self.lengths = lengths

    # -- construction ------------------------------------------------------
    @classmethod
    def create(cls, num_slots, max_len, num_heads, head_dim,
               dtype=jnp.float32):
        """A zeroed pool: ``num_slots`` sequences of up to ``max_len``
        cached positions each."""
        shape = (int(num_slots), int(max_len), int(num_heads),
                 int(head_dim))
        return cls(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                   jnp.zeros((int(num_slots),), jnp.int32))

    # -- static geometry ---------------------------------------------------
    @property
    def num_slots(self):
        return self.k.shape[0]

    @property
    def max_len(self):
        return self.k.shape[1]

    @property
    def num_heads(self):
        return self.k.shape[2]

    @property
    def head_dim(self):
        return self.k.shape[3]

    # -- traced mutators ---------------------------------------------------
    def prefill(self, slot, k_new, v_new, length):
        """Write a sequence's prompt K/V into its slot.

        ``k_new``/``v_new`` are ``(L_bucket, num_heads, head_dim)`` —
        the prompt padded UP to a seq-len bucket rung (positions past
        ``length`` are garbage the mask hides). ``slot`` and ``length``
        are traced scalars, so every (slot, length) pair reuses the one
        compiled program per bucket rung."""
        slot = jnp.asarray(slot, jnp.int32)
        k_new = lax.stop_gradient(k_new)
        v_new = lax.stop_gradient(v_new)
        start = (slot, jnp.int32(0), jnp.int32(0), jnp.int32(0))
        k = lax.dynamic_update_slice(self.k, k_new[None], start)
        v = lax.dynamic_update_slice(self.v, v_new[None], start)
        lengths = self.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
        return KVCache(k, v, lengths)

    def append(self, k_t, v_t, active):
        """Append one token's K/V to every ACTIVE slot at its current
        length; inactive slots are untouched and their lengths hold.

        ``k_t``/``v_t`` are ``(num_slots, num_heads, head_dim)`` (one
        new position per slot — the fixed ``(num_slots, 1)`` decode-step
        shape), ``active`` a ``(num_slots,)`` bool mask. Appends into a
        full slot (length == max_len) are dropped rather than wrapped.
        """
        k_t = lax.stop_gradient(k_t)
        v_t = lax.stop_gradient(v_t)
        active = jnp.asarray(active, bool)
        pos = jnp.minimum(self.lengths, self.max_len - 1)

        def write_row(row, tok, p):
            return lax.dynamic_update_slice(
                row, tok[None], (p, jnp.int32(0), jnp.int32(0)))

        k_written = jax.vmap(write_row)(self.k, k_t, pos)
        v_written = jax.vmap(write_row)(self.v, v_t, pos)
        ok = active & (self.lengths < self.max_len)
        sel = ok[:, None, None, None]
        k = jnp.where(sel, k_written, self.k)
        v = jnp.where(sel, v_written, self.v)
        lengths = self.lengths + ok.astype(jnp.int32)
        return KVCache(k, v, lengths)

    def free(self, slot):
        """Retire a sequence: zero its slot's valid length so the row is
        reusable. Shapes are untouched — freeing (and the next join's
        prefill into the same row) can never retrace."""
        lengths = self.lengths.at[jnp.asarray(slot, jnp.int32)].set(0)
        return KVCache(self.k, self.v, lengths)

    # -- attention helpers -------------------------------------------------
    def position_mask(self, dtype=jnp.float32):
        """(num_slots, max_len) additive bias: 0 where ``p <
        lengths[s]``, NEG_INF elsewhere — the single masking contract
        cached attention and the uncached reference share."""
        pos = jnp.arange(self.max_len)
        valid = pos[None, :] < self.lengths[:, None]
        return jnp.where(valid, jnp.asarray(0.0, dtype),
                         jnp.asarray(NEG_INF, dtype))

    # -- introspection -----------------------------------------------------
    def occupancy(self):
        """Live slots (length > 0) — the decode_slot_occupancy gauge's
        device-side truth."""
        return jnp.sum(self.lengths > 0)

    def __repr__(self):
        return (f"KVCache(slots={self.num_slots}, max_len={self.max_len},"
                f" heads={self.num_heads}, head_dim={self.head_dim})")


def _flatten(c):
    return (c.k, c.v, c.lengths), None


def _unflatten(_, children):
    return KVCache(*children)


jax.tree_util.register_pytree_node(KVCache, _flatten, _unflatten)
