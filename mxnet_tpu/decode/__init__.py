"""Autoregressive KV-cache decode: generation as a first-class workload.

The serving tier's one-shot engines batch fixed-shape requests; this
package adds the token-by-token half (ISSUE 18) — the workload mix the
paper's one-runtime thesis is about:

  * :mod:`~mxnet_tpu.decode.cache` — the paged :class:`KVCache` block
    contract (fixed-slot pool, value-only churn, zero retraces);
  * :mod:`~mxnet_tpu.decode.model` — the decode-block surface
    (init_cache / prefill / step / jit_trace_count) and
    :class:`TinyCausalLM`, its bitwise-testable reference;
  * :mod:`~mxnet_tpu.decode.sampling` — host-side per-sequence
    greedy / temperature / top-k (never touches the jit cache);
  * :mod:`~mxnet_tpu.decode.engine` — :class:`DecodeEngine`, the
    sequence-level continuous batcher with streaming
    :class:`SequenceRequest` handles.

See docs/decode.md for the design tour.
"""
from __future__ import annotations

from .cache import KVCache, NEG_INF
from .engine import DecodeEngine, SequenceRequest
from .model import TinyCausalLM
from .sampling import SamplingParams, sample_token

__all__ = [
    "KVCache", "NEG_INF",
    "TinyCausalLM",
    "SamplingParams", "sample_token",
    "DecodeEngine", "SequenceRequest",
]
