"""DecodeEngine: sequence-level continuous batching over a paged KVCache.

The one-shot :class:`~mxnet_tpu.serving.engine.InferenceEngine` batches
*requests* — each lives for exactly one micro-batch. Generation inverts
that: a sequence occupies the device for its whole lifetime, so the
continuous batcher here joins and retires SEQUENCES against a fixed pool
of KV-cache slots (cache.py):

  * **join**: a free slot's sequence is admitted through the SAME
    priority scheduler the one-shot engine uses (scheduler.py — classes,
    token buckets, Overloaded/RateLimited shedding, deadline expiry all
    apply unchanged; a queued sequence is rows=1 with a constant
    signature). Its prompt pads up to a SEQ-LEN bucket rung
    (buckets.py ``axis="seqlen"``) and prefills the slot row — one
    compiled program per rung;
  * **steady state**: every iteration runs the block's decode step at
    the fixed ``(num_slots, 1)`` shape — inactive slots ride along
    masked — so the entire churn of joins, retirements, and per-sequence
    sampling params touches ONE compiled executable (the zero-retrace
    invariant warmup() proves and ``recompiles_since_warmup`` tracks);
  * **retire**: EOS, the max-token budget, a full slot row
    (``context_full``), or a client-claimed timeout frees the slot with
    a VALUE-only cache write — the next join reuses the row, no retrace;
  * **stream**: each sampled token is pushed to the sequence's handle as
    its step settles; :meth:`SequenceRequest.stream` yields tokens while
    the sequence is still generating (MXTPU_DECODE_STREAM=0 withholds
    them until retirement for whole-completion clients).

Sampling is host-side (sampling.py) so temperature/top-k/seed live
outside the jit cache entirely. Observability rides the serving plane:
reqtrace boundary stamps (joining/prefilled/per-token), TTFT into the
class SLO window via ``slo_latency_s``, decode_* telemetry, and
decode_join/decode_retire flight events. The engine exposes the same
duck-typed surface as InferenceEngine (submit/start/stop/load/
admission_state/stats), so FrontDoor routing, the ModelRegistry, and
opsd /readyz compose unchanged. See docs/decode.md.
"""
from __future__ import annotations

import threading
import time

import numpy as _np

from .. import env as _env
from ..telemetry import instruments as _instr
from ..serving.buckets import bucket_ladder, pad_axis, pick_bucket
from ..serving.engine import warm_and_seal
from ..serving.errors import EngineStopped, Overloaded, RequestTimeout
from ..serving.scheduler import RequestScheduler
from .sampling import SamplingParams, sample_token

__all__ = ["DecodeEngine", "SequenceRequest"]

#: Shared scheduler signature for all decode sequences — every queued
#: sequence is batch-compatible with every other (the shapes that matter
#: are the engine's, not the request's), so scheduler batch fill works
#: across the whole queue.
_DECODE_SIGNATURE = ("decode",)

_REQTRACE = [None]


def _reqtrace():
    """Lazy, cached handle on observability.reqtrace (same layering as
    serving/engine.py: serving loads before observability)."""
    rt = _REQTRACE[0]
    if rt is None:
        from ..observability import reqtrace as rt

        _REQTRACE[0] = rt
    return rt


def _flight(kind, **fields):
    try:
        from ..observability import flight as _fl

        _fl.record(kind, **fields)
    except Exception:
        pass


class SequenceRequest:
    """One generation request: prompt in, a stream of tokens out.

    The scheduler-facing surface matches ServeRequest (cls, rows,
    signature, deadline, t_submit, done, _finish), so decode sequences
    ride the priority scheduler unchanged. The client-facing surface is
    a token stream: :meth:`stream` yields tokens as they settle,
    :meth:`result` blocks for the full completion. The outcome claim is
    atomic exactly like ServeRequest's — first of {engine retirement,
    client timeout, shed, stop} wins.
    """

    __slots__ = ("prompt", "max_new_tokens", "eos_id", "sampling", "rows",
                 "signature", "cls", "t_submit", "deadline", "model",
                 "trace", "outcome", "reason", "slot", "stream_enabled",
                 "slo_latency_s", "t_first_token", "_rng", "_tokens",
                 "_cv", "_error")

    def __init__(self, prompt, max_new_tokens, eos_id, sampling, deadline,
                 cls="interactive"):
        self.prompt = prompt                # host int32 (L,)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.sampling = sampling
        self.rows = 1
        self.signature = _DECODE_SIGNATURE
        self.cls = cls
        self.t_submit = time.monotonic()
        self.deadline = deadline            # queue-wait deadline, or None
        self.model = ""
        self.trace = None
        self.outcome = None                 # ok | timeout | error | shed
        self.reason = None                  # eos | max_tokens | ...
        self.slot = None                    # owned KV slot while active
        self.stream_enabled = True
        self.slo_latency_s = None           # TTFT — what the SLO judges
        self.t_first_token = None
        self._rng = sampling.make_rng()
        self._tokens = []
        self._cv = threading.Condition()
        self._error = None

    # -- engine side -------------------------------------------------------
    def _push(self, token):
        """Append one sampled token; wake streamers (unless streaming is
        withheld — then tokens surface in one burst at retirement)."""
        now = time.monotonic()
        if self.t_first_token is None:
            self.t_first_token = now
            self.slo_latency_s = now - self.t_submit  # SLO judges TTFT
        if self.trace is not None:
            self.trace.stamp("token")
        with self._cv:
            self._tokens.append(int(token))
            if self.stream_enabled:
                self._cv.notify_all()

    def _finish(self, outcome, result=None, error=None, reason=None):
        """Claim the outcome; True iff this call won. The reqtrace/SLO
        terminal chokepoint, same as ServeRequest."""
        with self._cv:
            if self.outcome is not None:
                return False
            self.outcome = outcome
            self.reason = reason or outcome
            self._error = error
            self._cv.notify_all()
        try:
            _reqtrace().finish(self, outcome, error)
        except Exception:
            pass
        return True

    @property
    def done(self):
        return self.outcome is not None

    # -- client side -------------------------------------------------------
    def ttft_ms(self):
        """Time-to-first-token in ms, or None before the first token."""
        if self.t_first_token is None:
            return None
        return (self.t_first_token - self.t_submit) * 1e3

    def tokens(self):
        """Tokens generated so far (a snapshot; grows while active)."""
        with self._cv:
            return list(self._tokens)

    def stream(self, timeout=None):
        """Yield tokens as the engine settles them.

        Yields every token exactly once, in order, ending when the
        sequence retires; raises the typed failure AFTER yielding
        whatever was generated before it. ``timeout`` (seconds) bounds
        each inter-token wait, raising RequestTimeout on expiry. With
        streaming withheld (MXTPU_DECODE_STREAM=0) this blocks until
        retirement, then yields the whole completion.
        """
        i = 0
        while True:
            with self._cv:
                deadline = None if timeout is None \
                    else time.monotonic() + timeout
                while True:
                    live = not self.done
                    gated = self.stream_enabled or not live
                    if gated and len(self._tokens) > i:
                        break
                    if not live:
                        break
                    wait = 0.05 if deadline is None else \
                        min(0.05, deadline - time.monotonic())
                    if wait <= 0:
                        raise RequestTimeout(
                            f"no token within {timeout:.3f}s")
                    self._cv.wait(wait)
                if len(self._tokens) <= i and self.done:
                    break
                tok = self._tokens[i]
            i += 1
            yield tok
        if self.outcome != "ok":
            raise self._error

    def result(self, timeout=None):
        """Block until retirement; return the full token list or raise
        the typed failure. ``timeout`` overrides the request deadline
        (the wait extends to the deadline by default)."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while not self.done:
                wait = 0.1 if deadline is None else \
                    min(0.1, deadline - time.monotonic())
                if wait <= 0:
                    break
                self._cv.wait(wait)
        if not self.done:
            # claim the timeout ourselves; the engine frees the slot
            # (reason "abandoned") when it next touches the sequence
            self._finish("timeout", error=RequestTimeout(
                f"sequence not completed within "
                f"{timeout if timeout is not None else 0:.3f}s"))
        if self.outcome == "ok":
            return self.tokens()
        raise self._error


class DecodeEngine:
    """Continuous-batching autoregressive server over a decode block.

    ::

        lm = decode.TinyCausalLM(max_len=128)
        eng = decode.DecodeEngine(lm, name="lm", num_slots=4)
        eng.warmup()                     # prefill rungs + the step; sealed
        eng.start()
        seq = eng.submit([3, 17, 9], max_new_tokens=32)
        for tok in seq.stream():         # tokens while it generates
            ...
        eng.stop()

    The block is duck-typed (model.py documents the contract):
    ``init_cache`` / ``prefill`` / ``step`` / ``jit_trace_count``.
    Lifecycle, admission, and observability mirror InferenceEngine.
    """

    def __init__(self, block, name="decode", num_slots=None, max_len=None,
                 prefill_buckets=None, max_queue=None, max_wait_ms=None,
                 timeout_ms=None, classes=None, stream=None,
                 drain_timeout_ms=None):
        for attr in ("init_cache", "prefill", "step", "jit_trace_count"):
            if not hasattr(block, attr):
                raise TypeError(
                    f"DecodeEngine needs a decode block (init_cache/"
                    f"prefill/step/jit_trace_count); {type(block)} "
                    f"lacks {attr!r}")
        self._block = block
        self.name = str(name)
        self.num_slots = int(
            num_slots if num_slots is not None
            else _env.get("MXTPU_DECODE_SLOTS"))
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got "
                             f"{self.num_slots}")
        if max_len is not None:
            self.max_len = int(max_len)
        else:  # a block that knows its context window wins over the env
            self.max_len = int(getattr(block, "max_len", None)
                               or _env.get("MXTPU_DECODE_MAX_LEN"))
        if prefill_buckets is None:
            raw = str(_env.get("MXTPU_DECODE_PREFILL_BUCKETS")).strip()
            if raw:
                prefill_buckets = [int(t) for t in raw.split(",") if
                                   t.strip()]
        self.buckets = bucket_ladder(self.max_len, prefill_buckets,
                                     axis="seqlen")
        self.max_queue = int(
            max_queue if max_queue is not None
            else _env.get("MXTPU_SERVE_QUEUE"))
        self.max_wait_s = float(
            max_wait_ms if max_wait_ms is not None
            else _env.get("MXTPU_SERVE_MAX_WAIT_MS")) / 1e3
        self.timeout_s = float(
            timeout_ms if timeout_ms is not None
            else _env.get("MXTPU_SERVE_TIMEOUT_MS")) / 1e3
        self.drain_timeout_s = float(
            drain_timeout_ms if drain_timeout_ms is not None
            else _env.get("MXTPU_SERVE_DRAIN_MS")) / 1e3
        self.stream_enabled = bool(
            stream if stream is not None
            else _env.get("MXTPU_DECODE_STREAM"))
        self._sched = RequestScheduler(self.name, classes=classes,
                                       max_queue=self.max_queue)
        self._cache = block.init_cache(self.num_slots, self.max_len)
        self._free = list(range(self.num_slots))     # loop thread only
        self._active = {}                            # slot -> sequence
        self._last = _np.zeros((self.num_slots,), _np.int32)
        self._mask = _np.zeros((self.num_slots,), bool)
        self._lifecycle = threading.Lock()
        self._stopping = False
        self._thread = None
        self._warm_traces = None
        self._g_occupancy = _instr.decode_slot_occupancy.labels(self.name)

    # -- lifecycle ---------------------------------------------------------
    @property
    def started(self):
        t = self._thread
        return t is not None and t.is_alive()

    def start(self):
        """Start the decode loop thread (idempotent)."""
        with self._lifecycle:
            if self._stopping:
                raise EngineStopped(f"engine {self.name!r} was stopped")
            if not self.started:
                self._thread = threading.Thread(
                    target=self._loop,
                    name=f"mxtpu-decode-{self.name}", daemon=True)
                self._thread.start()
        _flight("decode_start", model=self.name, slots=self.num_slots,
                max_len=self.max_len)
        return self

    def stop(self, drain=True, drain_timeout_ms=None):
        """Stop accepting sequences; by default finish the live ones.

        A graceful stop lets queued AND active sequences run to
        retirement, bounded by ``drain_timeout_ms`` (default
        MXTPU_SERVE_DRAIN_MS). At the bound — or immediately with
        ``drain=False`` — queued sequences fail with
        :class:`EngineStopped` and active ones retire with whatever
        tokens they have (outcome "error", reason "stopped").
        """
        with self._lifecycle:
            first = not self._stopping
            self._stopping = True
        self._sched.stop()
        if not drain:
            self._sched.stop(force=True)
            self._fail_queued()
            self._fail_active()
        elif not self.started:
            # never started (or already exited): nothing will ever
            # serve the queue — dropping now IS the bounded drain
            self._fail_queued()
        else:
            timeout_s = (float(drain_timeout_ms) / 1e3
                         if drain_timeout_ms is not None
                         else self.drain_timeout_s)
            self._thread.join(timeout=timeout_s)
            if self._thread.is_alive():
                self._sched.stop(force=True)
                self._fail_queued()
                self._fail_active()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        if first:
            _flight("decode_stop", model=self.name, drained=bool(drain))
        return self

    def _fail_queued(self):
        for r in self._sched.drain_all():
            if r._finish("error", error=EngineStopped(
                    f"engine {self.name!r} stopped"), reason="stopped"):
                _instr.record_serve_request(self.name, "error")

    def _fail_active(self):
        # claim the outcome; the loop thread observes done-ness and
        # frees the slots (or _loop already exited and the cache dies
        # with the engine)
        for seq in list(self._active.values()):
            seq._finish("error", error=EngineStopped(
                f"engine {self.name!r} stopped mid-generation"),
                reason="stopped")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- warmup ------------------------------------------------------------
    def warmup(self):
        """Pre-compile every prefill rung AND the decode step, then
        prove the cache sealed (shared
        :func:`~mxnet_tpu.serving.engine.warm_and_seal` proof with the
        one-shot engine). Runs against a scratch cache — the live pool
        is untouched. Returns a summary dict."""
        t0 = time.perf_counter()
        scratch = {"cache": self._block.init_cache(self.num_slots,
                                                   self.max_len)}
        toks = _np.zeros((self.num_slots,), _np.int32)
        act = _np.zeros((self.num_slots,), bool)
        act[0] = True

        def drive(rung):
            if rung == "step":
                scratch["cache"], logits = self._block.step(
                    scratch["cache"], toks, act)
            else:
                scratch["cache"], logits = self._block.prefill(
                    scratch["cache"], _np.zeros((int(rung),), _np.int32),
                    0, 1)
            _np.asarray(logits)  # settle — compile fully lands

        rungs = [int(b) for b in self.buckets] + ["step"]
        warm_and_seal(drive, rungs, self._engine_traces,
                      label="decode shapes")
        self._warm_traces = self._engine_traces()
        return {
            "model": self.name,
            "prefill_buckets": list(self.buckets),
            "step_slots": self.num_slots,
            "compile_traces": self._warm_traces,
            "seconds": round(time.perf_counter() - t0, 4),
        }

    def _engine_traces(self):
        """Compile traces of the variants THIS engine drives (prefill +
        step) — a caller running the block's other entry points (e.g.
        ``full_logits`` as a parity reference) must not read as an
        engine retrace."""
        return (self._block.jit_trace_count("prefill")
                + self._block.jit_trace_count("step"))

    def recompiles_since_warmup(self):
        """Block retraces since warmup() sealed the cache — 0 is the
        steady-state invariant; None before warmup."""
        if self._warm_traces is None:
            return None
        return self._engine_traces() - self._warm_traces

    # -- client side -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_id=None,
               temperature=0.0, top_k=0, seed=0, timeout_ms=None,
               priority=None):
        """Enqueue one sequence; returns a :class:`SequenceRequest`.

        ``prompt`` is a 1-D int token list/array, 1 <= len <= the top
        prefill rung. ``max_new_tokens`` bounds generation (the slot's
        context window may retire it earlier with reason
        ``context_full``); ``eos_id`` retires on that token.
        ``temperature``/``top_k``/``seed`` are per-sequence sampling
        params — host-side, so any mix shares the compiled step.
        Admission is the scheduler's: a full queue sheds with
        :class:`Overloaded`, a rate-limited class with RateLimited, a
        stopped engine raises EngineStopped. ``timeout_ms`` bounds the
        QUEUE WAIT (generation, once joined, runs to retirement; a
        client claiming the timeout mid-generation abandons the slot).
        """
        prompt = _np.asarray(prompt, _np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt of {prompt.size} tokens exceeds the top "
                f"prefill bucket {self.buckets[-1]}")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        sampling = SamplingParams(temperature, top_k, seed)
        tmo = self.timeout_s if timeout_ms is None else float(
            timeout_ms) / 1e3
        deadline = (time.monotonic() + tmo) if tmo > 0 else None
        cls = str(priority) if priority is not None \
            else self._sched.default_class
        seq = SequenceRequest(prompt, max_new_tokens, eos_id, sampling,
                              deadline, cls=cls)
        seq.model = self.name
        seq.stream_enabled = self.stream_enabled
        try:
            seq.trace = _reqtrace().maybe_start(
                self.name, cls=cls, rows=1, deadline=deadline)
        except Exception:
            seq.trace = None
        if self._stopping:
            err = EngineStopped(f"engine {self.name!r} is stopped")
            seq._finish("shed", error=err, reason="stopped")
            raise err
        try:
            self._sched.offer(seq)  # sheds with Overloaded / RateLimited
        except Overloaded as e:  # includes RateLimited
            seq._finish("shed", error=e)
            raise
        return seq

    # streaming is the submit contract here — the alias is the name the
    # front door fans out on (FrontDoor.submit_stream tries replicas by
    # this attribute, so one-shot engines never receive sequences)
    submit_stream = submit

    def generate(self, prompt, **kwargs):
        """Submit + stream in one call: yields tokens as they settle.
        Keyword args are :meth:`submit`'s."""
        seq = self.submit(prompt, **kwargs)
        return seq.stream()

    # -- the decode loop ---------------------------------------------------
    def _loop(self):
        while True:
            alive = self._join_ready(block=not self._active)
            if self._active:
                self._step_once()
            elif not alive:
                break
        self._g_occupancy.set(0)

    def _join_ready(self, block):
        """Admit queued sequences into free slots. Blocks only when the
        engine is idle (no active slots — nothing else to do); with
        sequences decoding, a peek at queue depth keeps the loop
        non-blocking. Returns False once the scheduler reports stopped
        AND the queue is drained."""
        if not self._free:
            return True
        if not block and self._sched.depth() == 0:
            return not self._sched._stopping
        batch = self._sched.collect(len(self._free), self.max_wait_s)
        if batch is None:
            return False
        for seq in batch:
            self._join_one(seq)
        return True

    def _join_one(self, seq):
        if seq.done:  # client claimed timeout while queued
            return
        slot = self._free.pop()
        if seq.trace is not None:
            seq.trace.stamp("joining")  # queue phase closes
            seq.trace.annotate(slot=slot)
        length = int(seq.prompt.size)
        bucket = pick_bucket(self.buckets, length)
        padded = pad_axis(seq.prompt, bucket, axis=0, fill="zero")
        t0 = time.perf_counter()
        try:
            self._cache, logits = self._block.prefill(
                self._cache, padded, slot, length)
            logits = _np.asarray(logits)  # settle
        except Exception as e:  # noqa: BLE001 — per-sequence failure
            self._free.append(slot)
            if seq._finish("error", error=e, reason="error"):
                _instr.record_serve_request(
                    self.name, "error",
                    time.monotonic() - seq.t_submit)
            return
        ms = (time.perf_counter() - t0) * 1e3
        _instr.record_decode_prefill(self.name, ms, bucket, slot)
        if seq.trace is not None:
            seq.trace.stamp("prefilled")
            seq.trace.bucket = bucket
        seq.slot = slot
        self._active[slot] = seq
        self._g_occupancy.set(len(self._active))
        self._settle_token(slot, seq, logits, stored=length)

    def _step_once(self):
        """One fixed-shape decode step for every active slot."""
        self._reap_done()
        if not self._active:
            return
        t0 = time.perf_counter()
        try:
            self._cache, logits = self._block.step(
                self._cache, self._last, self._mask)
            logits = _np.asarray(logits)  # settle
        except Exception as e:  # noqa: BLE001 — the step serves every
            # active sequence; its failure fails them all
            for slot in list(self._active):
                self._retire(slot, "error", error=e)
            return
        ms = (time.perf_counter() - t0) * 1e3
        _instr.record_decode_step(self.name, ms, len(self._active))
        lengths = _np.asarray(self._cache.lengths)
        for slot, seq in list(self._active.items()):
            self._settle_token(slot, seq, logits[slot],
                               stored=int(lengths[slot]))

    def _settle_token(self, slot, seq, logits, stored):
        """Sample one token for ``slot`` off settled logits, push it to
        the stream, and either retire the sequence or queue its token
        for the next step. ``stored`` is the slot's cached positions —
        the NEXT step must append the token we just sampled, so the row
        needs stored < max_len to continue."""
        tok = sample_token(logits, seq.sampling, seq._rng)
        seq._push(tok)
        _instr.record_decode_tokens(self.name)
        n = len(seq._tokens)
        if seq.eos_id is not None and tok == seq.eos_id:
            self._retire(slot, "eos")
        elif n >= seq.max_new_tokens:
            self._retire(slot, "max_tokens")
        elif stored >= self.max_len:
            self._retire(slot, "context_full")
        else:
            self._last[slot] = tok
            self._mask[slot] = True

    def _reap_done(self):
        """Free slots whose sequences were finished from outside the
        loop (client-claimed timeout, force-stop)."""
        for slot, seq in list(self._active.items()):
            if seq.done:
                self._retire(slot, "abandoned")

    def _retire(self, slot, reason, error=None):
        """Free the slot (a value-only cache write — never retraces) and
        settle the sequence's outcome."""
        seq = self._active.pop(slot)
        self._cache = self._cache.free(slot)
        self._mask[slot] = False
        self._free.append(slot)
        self._g_occupancy.set(len(self._active))
        ttft = None if seq.t_first_token is None \
            else seq.t_first_token - seq.t_submit
        _instr.record_decode_retire(self.name, reason,
                                    len(seq._tokens), ttft)
        outcome = "ok" if reason in ("eos", "max_tokens",
                                     "context_full") else "error"
        if error is None and outcome == "error":
            error = EngineStopped(
                f"sequence dropped by engine {self.name!r} ({reason})")
        if seq._finish(outcome, error=error, reason=reason):
            _instr.record_serve_request(
                self.name, outcome, time.monotonic() - seq.t_submit)

    # -- observability (the FrontDoor/registry/opsd surface) ---------------
    def queue_depth(self):
        """Sequences waiting for a slot (mirrors serve_queue_depth)."""
        return self._sched.depth()

    def inflight_rows(self):
        """Sequences actively generating (slot owners)."""
        return len(self._active)

    def load(self):
        """Least-loaded routing score for the front door: queued +
        active sequences."""
        return self._sched.depth_rows() + len(self._active)

    def admission_state(self):
        """"ok" / "overloaded" / "stopped" — same /readyz contract as
        InferenceEngine.admission_state."""
        if self._stopping:
            return "stopped"
        if self._sched.at_bound():
            return "overloaded"
        return "ok"

    def _quantile_ms(self, hist, q):
        child = hist.labels(self.name)
        count = child.count
        if not count:
            return None
        target = q * count
        cum = child.cumulative()
        for bound, acc in cum:
            if acc >= target:
                if bound == float("inf"):
                    bound = cum[-2][0] if len(cum) > 1 else 0.0
                return round(float(bound), 3)
        return None

    def stats(self):
        """Live snapshot: slots, queue, retirement reasons, token
        throughput surrogates, TTFT/step quantiles, and the
        zero-recompile invariant."""
        reasons = {
            lv[1]: c.value
            for lv, c in _instr.decode_sequence_total.series()
            if lv[0] == self.name}
        return {
            "model": self.name,
            "started": self.started,
            "slots": self.num_slots,
            "occupied": len(self._active),
            "max_len": self.max_len,
            "prefill_buckets": list(self.buckets),
            "queue_depth": self._sched.depth(),
            "max_queue": self.max_queue,
            "classes": self._sched.class_stats(),
            "sequences": reasons,
            "tokens":
                _instr.decode_tokens_total.labels(self.name).value,
            "ttft_p50_ms": self._quantile_ms(_instr.decode_ttft_ms, .50),
            "ttft_p99_ms": self._quantile_ms(_instr.decode_ttft_ms, .99),
            "step_p50_ms": self._quantile_ms(_instr.decode_step_ms, .50),
            "prefill_p50_ms":
                self._quantile_ms(_instr.decode_prefill_ms, .50),
            "recompiles_since_warmup": self.recompiles_since_warmup(),
            "slo": self._slo_status(),
        }

    def _slo_status(self):
        try:
            return _reqtrace().slo_status().get(self.name)
        except Exception:
            return None
