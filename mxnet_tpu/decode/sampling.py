"""Per-sequence token sampling — host-side, so params can never retrace.

The decode step's device program is sampling-free: it returns raw logits
at the fixed ``(num_slots, vocab)`` shape and the engine samples on the
host, per sequence, from the settled numpy row. Temperature / top-k /
seed therefore live entirely outside the jit cache — two sequences with
different sampling params share every compiled program, which is the
"per-sequence sampling params that never retrace" half of the
zero-retrace invariant (the other half is the paged cache, see
cache.py). Greedy is deterministic argmax (the parity oracle); sampled
modes draw from a per-sequence ``RandomState`` so a (seed, prompt) pair
replays identically regardless of slot placement or batch mix.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["SamplingParams", "sample_token"]


class SamplingParams:
    """One sequence's sampling recipe.

    ``temperature <= 0`` means greedy (argmax; ``top_k``/``seed``
    ignored). ``top_k > 0`` restricts sampling to the k highest logits.
    Validated once at submit time; applied host-side every token.
    """

    __slots__ = ("temperature", "top_k", "seed")

    def __init__(self, temperature=0.0, top_k=0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got "
                             f"{self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")

    @property
    def greedy(self):
        return self.temperature <= 0.0

    def make_rng(self):
        """The sequence-lifetime RNG (None for greedy — no randomness)."""
        return None if self.greedy else _np.random.RandomState(self.seed)

    def __repr__(self):
        if self.greedy:
            return "SamplingParams(greedy)"
        return (f"SamplingParams(temperature={self.temperature}, "
                f"top_k={self.top_k}, seed={self.seed})")


def sample_token(logits, params, rng=None):
    """Draw one token id from a settled ``(vocab,)`` logits row.

    ``rng`` is the sequence's ``make_rng()`` product, threaded by the
    engine so consecutive tokens advance one stream (ignored for
    greedy).
    """
    logits = _np.asarray(logits, _np.float64)
    if params.greedy:
        return int(_np.argmax(logits))
    scaled = logits / params.temperature
    if params.top_k > 0 and params.top_k < scaled.shape[0]:
        kth = _np.partition(scaled, -params.top_k)[-params.top_k]
        scaled = _np.where(scaled >= kth, scaled, -_np.inf)
    scaled = scaled - _np.max(scaled)
    probs = _np.exp(scaled)
    probs /= probs.sum()
    if rng is None:
        rng = params.make_rng()
    return int(rng.choice(probs.shape[0], p=probs))
