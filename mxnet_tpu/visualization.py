"""Network visualization (reference: python/mxnet/visualization.py —
print_summary table and graphviz plot_network over symbol graphs)."""
from __future__ import annotations

import numpy as _np

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Per-node summary table of a symbol graph (reference:
    visualization.py:46). `shape` maps input names to shapes so output
    shapes can be inferred."""
    out_shapes = {}
    if shape is not None:
        order = [s for s in symbol._topo() if s._op != "_group"]
        from .symbol.symbol import _OP_TABLE, _infer_shapes

        import jax

        # deduce shapes of leaves the caller didn't specify (conv/fc
        # params, BN stats...) the same way simple_bind does — the
        # reference runs full InferShape here, so only genuinely
        # undeducible inputs should error
        arg_names = symbol.list_arguments()
        missing = [n for n in arg_names if n not in shape]
        if missing:
            shape = dict(shape)
            arg_shapes, _ = _infer_shapes(
                symbol, {n: shape[n] for n in arg_names if n in shape},
                partial=True)
            deduced = dict(zip(arg_names, arg_shapes))
            for n in missing:
                if deduced.get(n) is not None:
                    shape[n] = deduced[n]

        structs = {}
        for s in order:
            if s._op is None:
                if s._name not in shape:
                    raise ValueError(f"shape for input {s._name} required")
                structs[id(s)] = jax.ShapeDtypeStruct(
                    tuple(shape[s._name]), _np.float32)
            elif s._op == "_const":
                v = _np.asarray(s._attrs["value"])
                structs[id(s)] = jax.ShapeDtypeStruct(v.shape, v.dtype)
            else:
                ins = [structs[id(i)] for i in s._inputs]
                structs[id(s)] = jax.eval_shape(
                    lambda *xs, _f=_OP_TABLE[s._op], _a=s._attrs:
                    _f(list(xs), _a), *ins)
            st = structs[id(s)]
            out_shapes[s._name] = getattr(st, "shape", None) if not \
                isinstance(st, (tuple, list)) else [x.shape for x in st]

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    lines = ["_" * line_length]
    row = ""
    for f, p in zip(fields, positions):
        row += f
        row = row[:p].ljust(p)
    lines.append(row)
    lines.append("=" * line_length)
    total_params = 0
    for s in symbol._topo():
        if s._op in ("_group",):
            continue
        prev = ",".join(i._name for i in s._inputs[:2])
        oshape = out_shapes.get(s._name, "")
        # param count: size of the op's variable inputs that look like
        # learnable params (reference heuristic: weight/bias/gamma/beta)
        nparams = 0
        if s._op is not None:
            for i in s._inputs:
                if i._op is None and any(
                        k in i._name for k in ("weight", "bias", "gamma",
                                               "beta", "_w")) \
                        and i._name in out_shapes:
                    shp = out_shapes[i._name]
                    if shp:
                        nparams += int(_np.prod(shp))
        row = ""
        vals = [f"{s._name} ({s._op or 'Variable'})", str(oshape),
                str(nparams), prev]
        for v, p in zip(vals, positions):
            row += v
            row = row[:p].ljust(p)
        lines.append(row)
        total_params += nparams
        lines.append("_" * line_length)
    lines.append(f"Total params: {total_params}")
    lines.append(f"Total nodes: {len(symbol._topo())}")
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):  # noqa: ARG001
    """Graphviz dot source for the symbol DAG (reference:
    visualization.py:210). Returns the dot source string; rendering needs
    graphviz, which is optional."""
    lines = [f'digraph "{title}" {{', "  rankdir=BT;"]
    order = [s for s in symbol._topo() if s._op != "_group"]
    idx = {id(s): i for i, s in enumerate(order)}

    def _hidden(s):
        return s._op is None and hide_weights and any(
            k in s._name for k in ("weight", "bias", "gamma", "beta",
                                   "mean", "var"))

    emitted = {i for i, s in enumerate(order) if not _hidden(s)}
    for i, s in enumerate(order):
        if i not in emitted:
            continue
        label = s._name if s._op is None else f"{s._name}\\n{s._op}"
        shape_attr = "ellipse" if s._op is None else "box"
        lines.append(f'  n{i} [label="{label}" shape={shape_attr}];')
    for i, s in enumerate(order):
        if i not in emitted:
            continue
        for inp in s._inputs:
            j = idx[id(inp)]
            if j in emitted:
                lines.append(f"  n{j} -> n{i};")
    lines.append("}")
    src = "\n".join(lines)
    try:
        import graphviz

        return graphviz.Source(src)
    except ImportError:
        return src
