"""Typed environment-variable registry (reference: the ~85 documented
MXNET_* vars read via dmlc::GetEnv at point of use + the env_var.md doc
page; per-var typed, self-documenting fields like dmlc::Parameter).

Every knob the framework reads from the environment is declared here with
type, default, and documentation. `mx.env.doc()` renders the env_var.md
analog; `mx.runtime.feature_list()` complements this with build/runtime
features. Reference-era MXNET_* names that have a TPU-native counterpart
are registered under BOTH spellings so ported launch scripts keep working.
"""
from __future__ import annotations

import os

__all__ = ["EnvVar", "register", "get", "all_vars", "doc"]

_REGISTRY = {}


class EnvVar:
    def __init__(self, name, type_, default, help_, aliases=()):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_
        self.aliases = tuple(aliases)

    def read(self):
        for n in (self.name, *self.aliases):
            raw = os.environ.get(n)
            if raw is not None:
                if self.type is bool:
                    return raw.lower() not in ("", "0", "false", "off")
                return self.type(raw)
        return self.default


def register(name, type_, default, help_, aliases=()):
    v = EnvVar(name, type_, default, help_, aliases)
    _REGISTRY[name] = v
    return v


def get(name):
    """Read an env var through its registry entry (typed, with default)."""
    return _REGISTRY[name].read()


def all_vars():
    return dict(_REGISTRY)


def doc():
    """Render the env-var documentation (the env_var.md analog)."""
    lines = ["# Environment variables", ""]
    for v in sorted(_REGISTRY.values(), key=lambda v: v.name):
        alias = f" (aliases: {', '.join(v.aliases)})" if v.aliases else ""
        lines.append(f"* `{v.name}`{alias} — {v.help} "
                     f"(type: {v.type.__name__}, default: {v.default!r})")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the knob corpus
# ---------------------------------------------------------------------------

register(
    "MXNET_ENGINE_TYPE", str, "ThreadedEnginePerDevice",
    "Dependency-engine implementation: ThreadedEnginePerDevice (async, the "
    "default) or NaiveEngine (synchronous — deterministic repro/debugging; "
    "reference: src/engine/engine.cc:32).")
register(
    "MXTPU_DISABLE_NATIVE", bool, False,
    "Disable the native C++ runtime (engine/storage/RecordIO/pipeline) and "
    "fall back to pure-python equivalents.")
register(
    "MXTPU_MP_START", str, "",
    "DataLoader multiprocessing start method override: fork | spawn | "
    "forkserver. Default: fork from a single-threaded parent, else spawn.")
register(
    "MXNET_CPU_WORKER_NTHREADS", int, 1,
    "Default host worker-thread count hint for the native pipeline "
    "(reference: threaded_engine_perdevice.cc:98).")
register(
    "MXNET_EXEC_BULK_EXEC_TRAIN", bool, True,
    "Parity no-op: XLA fuses whole programs — bulking has no separate "
    "switch (reference: bulking env family).")
register(
    "MXNET_ENFORCE_DETERMINISM", bool, False,
    "Prefer deterministic lowering (maps to XLA deterministic reductions "
    "where available; RNG is always counter-based/deterministic).")
register(
    "MXNET_SAFE_ACCUMULATION", bool, True,
    "Accumulate bf16 reductions in fp32 (the framework always does this "
    "on TPU; exposed for reference parity).")
register(
    "MXTPU_BENCH_LAYOUT", str, "NHWC",
    "bench.py conv layout experiment knob: NHWC (channels-last, MXU lane "
    "dim) or NCHW.")
register(
    "MXTPU_BENCH_BATCH", int, 256,
    "bench.py per-chip batch size.")
register(
    "MXTPU_BENCH_HEADLINE_ONLY", bool, False,
    "bench.py: skip the secondary rows (LeNet/BERT/INT8), emit only the "
    "ResNet training+inference numbers.")
register(
    "SCALING_DEVICES", int, 8,
    "benchmark/scaling.py virtual device count for the weak-scaling "
    "partition-efficiency measurement.")
register(
    "MXNET_KVSTORE_BIGARRAY_BOUND", int, 1 << 20,
    "Parity knob: arrays above this element count prefer sharded "
    "(reduce-scatter) allreduce in tpu_dist.")
register(
    "MXTPU_FLASH_ATTENTION", bool, True,
    "Use the Pallas flash-attention kernel inside MultiHeadAttention on "
    "TPU (fused QK^T/softmax/PV, O(S) memory). Off-TPU the jnp reference "
    "runs either way.")
register(
    "MXNET_GPU_MEM_POOL_TYPE", str, "Naive",
    "Parity no-op on TPU: device memory pooling is PJRT's "
    "(reference: pooled_storage_manager.h buckets).")
register(
    "MXTPU_IO_WORKER_NTHREADS", int, 2,
    "Native-runtime IO worker threads (checkpoint writes, RecordIO "
    "prefetch; reference: the IO-priority pool of "
    "threaded_engine_perdevice.cc).")
register(
    "MXTPU_SERVE_MAX_BATCH", int, 32,
    "serving.InferenceEngine default max micro-batch size (top of the "
    "bucket ladder; docs/serving.md).")
register(
    "MXTPU_SERVE_QUEUE", int, 256,
    "serving.InferenceEngine default admission-queue bound; submits "
    "beyond it shed deterministically with serving.Overloaded.")
register(
    "MXTPU_SERVE_MAX_WAIT_MS", float, 2.0,
    "serving.InferenceEngine default batching deadline: a partial batch "
    "launches once its oldest request has waited this long.")
register(
    "MXTPU_SERVE_TIMEOUT_MS", float, 1000.0,
    "serving.InferenceEngine default per-request deadline; requests "
    "not completed in time fail with serving.RequestTimeout.")
register(
    "MXTPU_SERVE_MODE", str, "pipelined",
    "serving.InferenceEngine execution mode: 'pipelined' (assembler + "
    "completer threads, host assembly overlaps device compute) or "
    "'sync' (the serialized PR-3 baseline; docs/serving.md).")
register(
    "MXTPU_SERVE_INFLIGHT", int, 2,
    "serving.InferenceEngine bounded in-flight window: how many "
    "dispatched-but-unsettled micro-batches the assembler may run "
    "ahead (2 = double buffering).")
register(
    "MXTPU_SERVE_DRAIN_MS", float, 10000.0,
    "serving.InferenceEngine.stop(drain=True) default drain bound; the "
    "drain also never outlives the latest queued deadline, and "
    "requests still queued at the bound are force-dropped (counted in "
    "serve_drain_dropped_total).")
register(
    "MXTPU_DECODE_SLOTS", int, 4,
    "decode.DecodeEngine default KV-cache slot count: the fixed "
    "sequence capacity of the paged (num_slots, max_len, ...) pool and "
    "the batch dimension of the steady-state decode step "
    "(docs/decode.md).")
register(
    "MXTPU_DECODE_MAX_LEN", int, 128,
    "decode.DecodeEngine default per-slot context window: prompt + "
    "generated tokens per sequence are capped here (a sequence filling "
    "its slot row retires with reason 'context_full').")
register(
    "MXTPU_DECODE_PREFILL_BUCKETS", str, "",
    "decode.DecodeEngine prefill seq-len bucket ladder as a "
    "comma-separated rung list (e.g. '16,64,128'); empty = the "
    "powers-of-two ladder up to MXTPU_DECODE_MAX_LEN. Every rung is "
    "pre-compiled by warmup(); prompts pad up to the nearest rung.")
register(
    "MXTPU_DECODE_STREAM", bool, True,
    "decode.DecodeEngine streaming default: on, SequenceRequest.stream() "
    "yields each token as its step settles; off, tokens are withheld "
    "until the sequence retires (stream() then yields them in one "
    "burst) — for clients that want whole completions only.")
register(
    "MXTPU_TRACE_SAMPLE", float, 0.0,
    "Head-based request-trace sampling fraction for the serving tier "
    "(observability/reqtrace.py): 0 = off (bit-identical serving path, "
    "zero extra work), 1 = every request, 0.1 = exactly every 10th "
    "(deterministic counter, no RNG). Sampled requests emit phase spans "
    "(admit/queue/assemble/dispatch/device/slice/settle) into the trace "
    "ring, served by opsd GET /traces.")
register(
    "MXTPU_TRACE_RING", int, 1024,
    "Bounded per-process ring of finished request traces "
    "(observability/reqtrace.py); a long-running replica keeps the "
    "newest N traces for /traces and postmortem bundles.")
register(
    "MXTPU_SLO_INTERACTIVE_MS", float, 0.0,
    "Latency objective (ms) for the 'interactive' serving class; 0 "
    "disables SLO tracking for the class. Any class gets an objective "
    "via MXTPU_SLO_<CLASS>_MS (docs/observability.md §6).")
register(
    "MXTPU_SLO_BATCH_MS", float, 0.0,
    "Latency objective (ms) for the 'batch' serving class; 0 disables "
    "SLO tracking for the class.")
register(
    "MXTPU_SLO_TARGET", float, 0.99,
    "SLO success-fraction target: the error budget is 1 - target, and "
    "the serve_slo_burn_rate gauge is the windowed violation fraction "
    "over that budget.")
register(
    "MXTPU_SLO_WINDOW_S", float, 60.0,
    "Rolling window (seconds) SLO burn rates are evaluated over; "
    "violations roll off after this long, which is how a 503'd replica "
    "recovers its /readyz.")
register(
    "MXTPU_SLO_BURN_MAX", float, 1.0,
    "Burn-rate threshold: a class burning hotter than this drops the "
    "replica from opsd /readyz rotation (1.0 = spending the error "
    "budget exactly as fast as the target allows).")
register(
    "MXTPU_SLO_MIN_EVENTS", int, 10,
    "Minimum windowed requests before a class's burn rate can flip "
    "/readyz — keeps one unlucky request from 503ing an idle replica.")
register(
    "MXTPU_FUSED_UPDATE", bool, True,
    "Fused multi-tensor optimizer update: bucket the parameter tree by "
    "(rule, weight dtype, multi-precision) and run ONE donated jit "
    "dispatch per bucket per step, plus the bucketed flat-buffer "
    "allreduce in Trainer.allreduce_grads — collapses O(params) "
    "dispatches to O(buckets). 0 restores the legacy per-parameter "
    "path (docs/performance.md).")
register(
    "MXTPU_FUSED_BUCKET_MB", int, 25,
    "Target flat-buffer size (MB) for the bucketed DDP-style allreduce "
    "in Trainer.allreduce_grads: gradients are concatenated into flat "
    "buffers of roughly this size, one collective dispatch per buffer.")
register(
    "MXTPU_DONATE_UPDATE", bool, True,
    "Donate weight/optimizer-state buffers into optimizer update "
    "dispatches so XLA reuses them in place instead of allocating fresh "
    "HBM. Skipped automatically for any single call where donation "
    "would alias another argument's buffer.")
register(
    "MXTPU_WHOLE_STEP", bool, True,
    "gluon.TrainStep compiled whole-step path: forward + backward + "
    "gradient allreduce + fused optimizer update captured in ONE donated "
    "jit dispatch per training step (params/optimizer state donated, "
    "per-param lr/wd/t as weak scalars — LR schedules never retrace). "
    "0 forces the legacy three-phase record/backward/Trainer.step "
    "sequence; sparse grads, overriding optimizers, clip_global_norm and "
    "multi-copy params fall back automatically (docs/performance.md).")
register(
    "MXTPU_DEVICE_PREFETCH", int, 0,
    "Default DataLoader device_prefetch depth: keep up to N batches "
    "ahead of the consumer already jax.device_put to the accelerator, so "
    "the next batch's host->device transfer overlaps the current step's "
    "compute (double-buffered input pipeline). 0 disables; the "
    "DataLoader(device_prefetch=...) argument overrides per loader.")
register(
    "MXTPU_CKPT_ASYNC", bool, True,
    "CheckpointManager default: write+commit checkpoints on an engine IO "
    "thread so saves overlap training (snapshot capture still happens "
    "inline). 0 makes every save synchronous (docs/checkpointing.md).")
register(
    "MXTPU_CKPT_KEEP_LAST", int, 5,
    "CheckpointManager retention: keep the newest N committed "
    "checkpoints, deleting older ones at each commit. 0 disables "
    "deletion.")
register(
    "MXTPU_CKPT_KEEP_EVERY_N", int, 0,
    "CheckpointManager retention: checkpoints whose step is a multiple "
    "of N are milestones kept forever, exempt from KEEP_LAST deletion. "
    "0 disables milestones.")
register(
    "MXTPU_CKPT_VERIFY", bool, True,
    "Verify per-array crc32 checksums against the manifest on restore; "
    "mismatches raise CheckpointCorrupt (latest-checkpoint restores "
    "then fall back to the previous committed step).")
register(
    "MXTPU_CKPT_MODE", str, "replicated",
    "Distributed checkpoint layout: 'replicated' (rank 0 writes the "
    "full state, others barrier) or 'sharded' (each rank persists its "
    "share plus a fragment manifest; rank 0 merges).")
register(
    "MXTPU_CKPT_PREEMPT_SIGNALS", str, "SIGTERM,SIGUSR1",
    "Comma-separated signals the PreemptionHandler intercepts for the "
    "emergency synchronous snapshot.")
register(
    "MXTPU_CKPT_PREEMPT_EXIT_CODE", int, 0,
    "Process exit code after a successful preemption snapshot (0 = "
    "clean shutdown so supervisors treat the job as resumable, not "
    "crashed).")
register(
    "MXTPU_CKPT_DIR", str, "",
    "Default checkpoint directory for tools and the estimator "
    "CheckpointHandler when none is passed explicitly; empty = require "
    "an explicit directory.")
register(
    "MXTPU_ELASTIC_MAX_RESTARTS", int, 3,
    "Supervisor restart budget (tools/supervisor.py via "
    "elastic.RestartPolicy; docs/elasticity.md): lifetime cap on "
    "restarts after rank deaths before the supervisor gives up and "
    "exits non-zero. -1 = unlimited.")
register(
    "MXTPU_ELASTIC_BACKOFF_S", float, 1.0,
    "Supervisor restart backoff base (seconds): restart N after a rank "
    "death waits base * 2^N, capped at MXTPU_ELASTIC_BACKOFF_MAX_S — "
    "a crash-looping job must not hammer the checkpoint store.")
register(
    "MXTPU_ELASTIC_BACKOFF_MAX_S", float, 30.0,
    "Cap on the supervisor's exponential restart backoff (seconds).")
register(
    "MXTPU_ELASTIC_LR_RESCALE", str, "off",
    "LR rescaling rule when the world size changes at elastic re-entry "
    "(elastic.rescale_lr; docs/elasticity.md): 'off' (default — the "
    "bitwise-safe choice when the GLOBAL batch is held constant across "
    "the migration), 'linear' (lr *= new/old, the Goyal et al. rule "
    "for per-rank batches — global batch shrinks with the world), or "
    "'sqrt' (lr *= sqrt(new/old), the conservative variant). Scheduled "
    "LRs (lr_scheduler) are never touched.")
register(
    "MXTPU_ELASTIC_GENERATION", int, 0,
    "World generation a relaunched rank inherits (stamped by "
    "tools/supervisor.py on every restart): 0 = first launch, +1 per "
    "restart / in-process reenter(). Flows into the flight identity, "
    "opsd /identity, the world_generation gauge, and fleetctl's table.")
register(
    "MXTPU_PASSES", str, "auto",
    "Graph-pass pipeline master switch (mxnet_tpu/passes; "
    "docs/passes.md). 'auto' runs each block's registered passes plus "
    "the env-driven policies; a comma list (e.g. 'amp,remat') "
    "force-adds those named passes to every pipeline; '0' disables ALL "
    "graph passes so every seam compiles its captured program verbatim "
    "— bitwise-identical to the pre-pipeline framework.")
register(
    "MXTPU_REMAT_POLICY", str, "none",
    "Rematerialization policy the remat pass applies to training "
    "graphs: none | dots (sqrt-N segmented jax.checkpoint keeping "
    "matmul/conv outputs) | full (segments save only boundary values) "
    "| auto (estimate the fwd+bwd peak residency per policy via the "
    "passes/memory.py liveness walk + the compile registry and pick "
    "the cheapest one fitting MXTPU_REMAT_BUDGET_MB / device memory).")
register(
    "MXTPU_REMAT_BUDGET_MB", int, 0,
    "HBM budget (MB) the remat 'auto' policy fits the training program "
    "into. 0 = use the device's memory_stats bytes_limit; CPU reports "
    "none, so 'auto' resolves to 'none' there without an explicit "
    "budget.")
register(
    "MXTPU_DIAG_COMPILE", bool, True,
    "Capture per-compile cost/memory analysis (flops, peak HBM, compile "
    "seconds) into the diagnostics compile registry at each block-seam "
    "build; 0 skips capture entirely (docs/diagnostics.md).")
register(
    "MXTPU_DIAG_MEMORY", bool, False,
    "Record the backend-independent liveness peak (passes/memory.py "
    "walk) into every compile-registry entry even when no remat policy "
    "is active; costs an extra trace (plus a grad trace for train "
    "variants) per compile. Any MXTPU_REMAT_POLICY other than 'none' "
    "implies it.")
register(
    "MXTPU_GRAPH_DEDUP", bool, False,
    "Cross-CachedOp structural dedup: canonicalize every block-seam "
    "jaxpr (shapes/dtypes/equation graph, modulo variable names and "
    "constant values) and share ONE compiled executable between "
    "structurally identical blocks (multi-head models, serving "
    "replicas). Reuses count in graph_dedup_hits_total.")
register(
    "MXTPU_BENCH_BUDGET_S", int, 1200,
    "bench.py wall-clock budget (seconds); secondary rows are skipped "
    "with an error row once exceeded so the driver always gets the "
    "headline JSON quickly.")
register(
    "MXTPU_NUMERICS", str, "off",
    "In-graph numerics checking (observability.numerics; "
    "docs/observability.md): 'step' fuses ONE is-finite AND-reduce over "
    "every inexact program output into each compiled program (verdict "
    "delivered asynchronously, read at the step boundary; a trip "
    "bisects the recorded jaxpr to the first non-finite equation and "
    "raises NonFiniteError with op/shape/operand-stats attribution); "
    "'op' re-emits the program with a per-equation is-finite flag "
    "vector for immediate attribution; 'off' (default) compiles "
    "programs untouched.")
register(
    "MXTPU_FLIGHTREC", bool, True,
    "Flight recorder (observability.flight): append structured runtime "
    "events (steps, compiles, collectives, checkpoint commits, serving "
    "sheds, watchdog beats, numerics trips) to a bounded in-memory "
    "ring for postmortem bundles. 0 reduces recording to a single "
    "branch.")
register(
    "MXTPU_FLIGHTREC_CAPACITY", int, 4096,
    "Flight-recorder ring capacity: the postmortem bundle holds the "
    "LAST this-many events.")
register(
    "MXTPU_FLIGHTREC_DIR", str, ".",
    "Directory postmortem bundles are written to "
    "(mxtpu_blackbox.rank<N>.json, one per rank).")
register(
    "MXTPU_FLIGHTREC_FLUSH_STEPS", int, 0,
    "Spill the postmortem bundle asynchronously every N training-step "
    "events, so a SIGKILL'd run still leaves evidence on disk for "
    "tools/blackbox.py. 0 (default) disables periodic spills; crash "
    "paths (watchdog, preemption, crash hooks, numerics trips) dump "
    "regardless.")
register(
    "MXTPU_FLIGHTREC_CRASHDUMP", bool, False,
    "Auto-install the observability crash hooks at import: sys.excepthook "
    "and atexit write a final postmortem bundle; faulthandler dumps "
    "native-fault tracebacks to a per-rank sidecar file.")
register(
    "MXTPU_JOB_ID", str, "",
    "Job identity stamped into flight-recorder events and span records; "
    "(job_id, step) is the cross-rank trace ID tools/blackbox.py aligns "
    "per-rank postmortem bundles on. Empty = 'local'.")
register(
    "MXTPU_KERNELS", str, "off",
    "Hand-fused Pallas bandwidth kernels for the HBM-bound regions the "
    "r5 fusion audit ranked worst (mxnet_tpu/kernels; docs/kernels.md): "
    "'off' (default) never touches a call site — bitwise-identical to "
    "the XLA paths with zero extra traces; 'auto' uses a kernel at a "
    "call site only when the passes/memory.py external-bytes model "
    "predicts it saves HBM traffic over the fused-XLA estimate; 'force' "
    "uses a kernel whenever shape/dtype/rule support allows. Unsupported "
    "sites always fall back to the existing XLA path (fallbacks count "
    "in kernel_dispatch_total and land in the flight recorder).")
register(
    "MXTPU_KERNELS_INTERPRET", bool, False,
    "Run the mxnet_tpu/kernels Pallas kernels in interpret mode so they "
    "execute off-TPU (CPU parity tests). Without it, non-TPU platforms "
    "take the XLA fallback even under MXTPU_KERNELS=force.")
register(
    "MXTPU_LAYOUT", str, "off",
    "Whole-graph channels-last layout pass (passes/layout.py; "
    "docs/layout.md): 'off' (default) never consults the pass — "
    "captured programs and weight buffers are bitwise-identical to main "
    "with zero extra traces; 'auto' rewrites conv-bearing graphs to "
    "NHWC/HWIO only when the passes/memory.py external-bytes model "
    "predicts the saved per-conv relayouts outweigh the boundary "
    "transposes it must insert; 'nhwc' rewrites whenever a "
    "channels-first conv is present. Conv weights are re-laid-out "
    "persistently (one-time OIHW→HWIO device transpose); checkpoints "
    "round-trip the logical NCHW layout either way.")
register(
    "MXTPU_LAYOUT_MIN_BYTES", int, 1 << 20,
    "MXTPU_LAYOUT=auto declines graphs whose channels-first conv "
    "activations (inputs + outputs) total fewer external bytes than "
    "this — relayout bookkeeping swamps any bandwidth win on tiny "
    "graphs (passes/layout.py).")
register(
    "MXTPU_MESH", str, "",
    "Device-mesh axis spec for the sharding subsystem "
    "(mxnet_tpu/sharding; docs/sharding.md), e.g. 'dp=-1' (data "
    "parallel over all devices) or 'dp=4,tp=2'. -1 infers that axis "
    "from the device count. Consulted only when MXTPU_SHARDING=auto "
    "and the Trainer was given no explicit mesh=/sharding_plan=; empty "
    "(default) names no mesh.")
register(
    "MXTPU_SHARDING", str, "auto",
    "Sharding-subsystem mode (mxnet_tpu/sharding; docs/sharding.md): "
    "'off' disables the subsystem entirely — mesh= arguments and "
    "MXTPU_MESH are ignored, the ShardingPass is never injected, and "
    "every code path is bitwise-identical to the unsharded framework; "
    "'auto' (default) builds a plan from explicit Trainer arguments, "
    "else from MXTPU_MESH; 'plan' accepts explicit arguments only "
    "(MXTPU_MESH is ignored, so a launcher's env mesh cannot override "
    "a hand-built plan).")
register(
    "MXTPU_SPEC_LAYOUT", bool, True,
    "SpecLayout rule library for env-driven plans (sharding/layouts.py; "
    "docs/sharding.md): when MXTPU_MESH names the layout's model axes "
    "(fsdp/tp), the resolved plan places stock-block params by "
    "structural role — embeddings, qkv/attention projections, FFN "
    "in/out, norms, conv — over data/fsdp/tp. 0 keeps env meshes "
    "placement-free (axes only, params replicate). Plans built in code "
    "via ShardingPlan.from_layout() carry the library regardless.")
register(
    "MXTPU_ZERO", bool, True,
    "ZeRO optimizer-state sharding (docs/sharding.md): when the plan's "
    "mesh carries the layout's fsdp axis, optimizer state (momentum, "
    "variance, fp32 masters) shards along it on the first unsharded "
    "divisible dim — each rank owns ~1/N of optimizer memory, and the "
    "donated whole-step program reduce-scatters grads / allgathers "
    "updated params in-trace. 0 places state exactly like its weight. "
    "Numerics are identical either way (placement, not math).")
register(
    "MXTPU_OPS_PORT", int, 0,
    "Live ops server (observability.opsd; docs/observability.md): start "
    "a per-process stdlib HTTP server on this port at import, serving "
    "GET /metrics (Prometheus), /healthz, /readyz, /flight, /steps, "
    "/identity and POST /postmortem, /profile?ms=N. 0 (default) creates "
    "no thread or socket. Port 0 is reserved for programmatic "
    "opsd.start(port=0) ephemeral binds (tests).")
register(
    "MXTPU_OPS_HOST", str, "127.0.0.1",
    "Bind address for the live ops server. Loopback by default; set "
    "0.0.0.0 when a fleet supervisor (tools/fleetctl.py) or Prometheus "
    "scrapes ranks across hosts.")
register(
    "MXTPU_OPS_TOKEN", str, "",
    "Optional bearer token for the ops server's mutating POST endpoints "
    "(/postmortem, /profile): when set, requests must carry "
    "'Authorization: Bearer <token>' or get 401. GET endpoints stay "
    "open — they serve the same read-only snapshots a postmortem "
    "bundle contains.")
register(
    "MXTPU_BN_COMPUTE", str, "f32",
    "Element-wise dtype of the O(N·H·W·C) BatchNorm tensors (ops/nn.py "
    "_bn_ew_dtype; the r5 audit's top falsifiable prediction): 'f32' "
    "(default, today's measured-correct config) or 'bf16' — keep the "
    "big elementwise chains in the activation dtype and promote only "
    "the reduction accumulators to f32. Applies to the XLA custom-VJP "
    "path and the Pallas norm kernels alike; A/B on chip before "
    "changing the default.")
register(
    "MXTPU_DIAGNOSTICS", bool, True,
    "Diagnostics span recording (diagnostics/spans.py): per-phase "
    "timing records feeding the step table, watchdog, and postmortem "
    "bundles. 0 makes every span a no-op context manager.")
register(
    "MXTPU_DIAG_RING_CAPACITY", int, 4096,
    "Diagnostics span-ring capacity: the per-process ring keeps the "
    "newest N span records for the step table and postmortem bundles.")
register(
    "MXTPU_TELEMETRY", bool, True,
    "Telemetry registry master switch (telemetry/registry.py): 0 turns "
    "every counter/gauge/histogram record into a single-branch no-op "
    "and /metrics serves an empty page.")
register(
    "MXTPU_MEASURE", str, "off",
    "Measurement plane (observability/measure.py; docs/performance.md "
    "'measured vs modeled'): 'off' (default) never touches a compile — "
    "runs are bitwise-identical with zero extra traces or dispatches; "
    "'on_compile' microbenchmarks every program at its compile-registry "
    "seam (warmed, synchronized wall-clock runs on the live device) and "
    "records it into the CostDB; 'cli' stashes programs for a deferred "
    "measure.sweep() (what tools/costdb.py measure drives).")
register(
    "MXTPU_MEASURE_RUNS", int, 5,
    "Timed executions per measured program (p50/p95 come from these).")
register(
    "MXTPU_MEASURE_WARMUP", int, 1,
    "Untimed warmup executions before the timed runs of each measured "
    "program (absorbs compilation and first-dispatch overhead).")
register(
    "MXTPU_COSTDB_PATH", str, "",
    "CostDB JSON-lines file (observability/costdb.py). Empty = "
    "<MXTPU_FLIGHTREC_DIR>/mxtpu_costdb.jsonl. Writes are atomic "
    "(tmp+fsync+replace) and loads merge newest-wins, so many ranks "
    "may share one path on a common filesystem.")
register(
    "MXTPU_COSTDB_AUTOSAVE", bool, True,
    "Persist the CostDB after every recorded measurement. 0 keeps "
    "measurements in memory until an explicit CostDB.save() "
    "(tools/costdb.py or the postmortem path).")
register(
    "MXTPU_COSTDB_DRIFT_MAX", float, 8.0,
    "Drift-auditor trip threshold: a program whose measured-vs-modeled "
    "bandwidth ratio leaves [1/N, N] against the platform median "
    "raises a cost_drift flight event and flags in /costdb, diagnose "
    "--passes, and the fleetctl drift column.")
