"""`mx.io.io` — the reference keeps the iterator classes in io/io.py and
re-exports them from the package (`from .io import *`); mirror that
spelling for scripts that import the inner module directly."""
from . import (  # noqa: F401
    CSVIter,
    DataBatch,
    DataDesc,
    DataIter,
    ImageRecordIter,
    LibSVMIter,
    MNISTIter,
    NDArrayIter,
    PrefetchingIter,
    ResizeIter,
)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "MNISTIter", "ImageRecordIter", "ResizeIter",
           "PrefetchingIter"]
