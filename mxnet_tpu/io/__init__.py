"""Legacy data-iterator API (reference: python/mxnet/io/ + src/io/ —
MXNET_REGISTER_IO_ITER iterators: MNISTIter, ImageRecordIter, CSVIter,
NDArrayIter...).

TPU re-design: the C++ prefetcher/batchloader threads (iter_prefetcher.h)
are replaced by the DataLoader's prefetching thread pool; these classes keep
the DataIter surface (provide_data/provide_label, DataBatch with pad) for
reference-era training scripts.
"""
from __future__ import annotations

import os
from collections import namedtuple

import numpy as _np

from ..ndarray.ndarray import NDArray
from .. import numpy as mnp

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "MNISTIter", "ImageRecordIter", "ResizeIter",
           "PrefetchingIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    """One batch (reference: io.DataBatch): data/label lists + pad count."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (reference: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        raise NotImplementedError

    def __next__(self):
        return self.next()

    def iter_next(self):
        try:
            self._next_batch = self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._next_batch.data

    def getlabel(self):
        return self._next_batch.label

    def getpad(self):
        return self._next_batch.pad


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.NDArrayIter):
    shuffle, last_batch_handle pad/discard/roll_over."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self._data = self._init_arrays(data, data_name)
        self._label = self._init_arrays(label, label_name)
        self._shuffle = shuffle
        self._last = last_batch_handle
        self._n = self._data[0][1].shape[0]
        self._order = _np.arange(self._n)
        self._cursor = 0
        self._leftover = None  # roll_over remainder from the prior epoch
        self.reset()

    @staticmethod
    def _init_arrays(arrays, default_name):
        if arrays is None:
            return []
        if isinstance(arrays, (list, tuple)):
            arrays = {f"{default_name}{i}" if i else default_name: a
                      for i, a in enumerate(arrays)}
        elif not isinstance(arrays, dict):
            arrays = {default_name: arrays}
        out = []
        for name, a in arrays.items():
            if isinstance(a, NDArray):
                a = a.asnumpy()
            out.append((name, _np.asarray(a)))
        return out

    @property
    def provide_data(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:], a.dtype)
                for n, a in self._data]

    @property
    def provide_label(self):
        return [DataDesc(n, (self.batch_size,) + a.shape[1:], a.dtype)
                for n, a in self._label]

    def reset(self):
        if self._shuffle:
            _np.random.shuffle(self._order)
        self._cursor = 0

    def next(self):
        prefix = None
        if self._leftover is not None:
            # roll_over: last epoch's remainder starts this epoch's batch
            prefix, self._leftover = self._leftover, None
        need = self.batch_size - (len(prefix) if prefix is not None else 0)
        if self._cursor >= self._n and prefix is None:
            raise StopIteration
        end = self._cursor + need
        idx = self._order[self._cursor : end]
        pad = 0
        if end > self._n:
            if self._last == "discard":
                self._cursor = end
                raise StopIteration
            if self._last == "pad":
                pad = end - self._n
                idx = _np.concatenate([idx, self._order[: pad]])
            elif self._last == "roll_over":
                # withhold the short remainder until the next epoch.
                # copy: idx is a view of _order, which reset() may
                # shuffle in place under it
                self._cursor = end
                self._leftover = (_np.concatenate([prefix, idx])
                                  if prefix is not None else idx.copy())
                raise StopIteration
        self._cursor = end
        if prefix is not None:
            idx = _np.concatenate([prefix, idx])
        data = [mnp.array(a[idx]) for _, a in self._data]
        label = [mnp.array(a[idx]) for _, a in self._label]
        return DataBatch(data, label, pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)


class CSVIter(NDArrayIter):
    """CSV file iterator (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size, **kwargs)


class LibSVMIter(DataIter):
    """LibSVM sparse-format iterator (reference: src/io/iter_libsvm.cc).

    Yields CSR batches: `label idx:val idx:val ...` lines → CSRNDArray data
    (densified per batch by consumers that need dense; the sparse dot path
    takes CSR directly).
    """

    def __init__(self, data_libsvm, data_shape, label_shape=None,
                 batch_size=1, **kwargs):  # noqa: ARG002
        super().__init__(batch_size)
        self._num_features = int(data_shape[-1] if hasattr(
            data_shape, "__len__") else data_shape)
        labels, rows = [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                rows.append([(int(kv.split(":")[0]),
                              float(kv.split(":")[1])) for kv in parts[1:]])
        self._labels = _np.asarray(labels, _np.float32)
        self._rows = rows
        self._cursor = 0

    def reset(self):
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def __next__(self):
        from ..ndarray import sparse as _sp

        if self._cursor >= len(self._rows):
            raise StopIteration
        stop = min(self._cursor + self.batch_size, len(self._rows))
        batch_rows = self._rows[self._cursor:stop]
        labels = self._labels[self._cursor:stop]
        pad = self.batch_size - len(batch_rows)
        data, indices, indptr = [], [], [0]
        for r in batch_rows + [batch_rows[-1]] * pad:
            for idx, val in r:
                indices.append(idx)
                data.append(val)
            indptr.append(len(data))
        if pad:
            labels = _np.concatenate([labels, [labels[-1]] * pad])
        csr = _sp.CSRNDArray(
            _np.asarray(data, _np.float32), _np.asarray(indices, _np.int64),
            _np.asarray(indptr, _np.int64),
            (self.batch_size, self._num_features))
        self._cursor = stop
        from .. import numpy as mxnp

        return DataBatch(data=[csr], label=[mxnp.array(labels)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    next = __next__


class MNISTIter(NDArrayIter):
    """MNIST idx-file iterator (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, path_root=None, train=True, **kwargs):  # noqa: ARG002
        from ..gluon.data.vision import MNIST

        root = path_root or os.path.dirname(image or "") or \
            "~/.mxnet/datasets/mnist"
        ds = MNIST(root=root, train=train)
        imgs = ds._data.astype(_np.float32) / 255.0
        imgs = imgs.reshape(len(imgs), -1) if flat else \
            imgs.transpose(0, 3, 1, 2)
        super().__init__(imgs, ds._label.astype(_np.float32), batch_size,
                         shuffle=shuffle)


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference:
    io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self._iter = data_iter
        self._size = size
        self._reset_internal = reset_internal
        self._count = 0

    def reset(self):
        self._count = 0
        if self._reset_internal:
            self._iter.reset()

    def next(self):
        if self._count >= self._size:
            raise StopIteration
        self._count += 1
        try:
            return self._iter.next()
        except StopIteration:
            self._iter.reset()
            return self._iter.next()


class PrefetchingIter(DataIter):
    """Threaded prefetcher over one or more iterators (reference:
    io.PrefetchingIter over iter_prefetcher.h)."""

    def __init__(self, iters, rename_data=None, rename_label=None):  # noqa: ARG002
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self._iters = iters
        self._start_worker()

    def _start_worker(self):
        import queue
        import threading

        self._queue = queue.Queue(maxsize=4)
        self._stop = threading.Event()
        stop, q = self._stop, self._queue

        def worker():
            while not stop.is_set():
                try:
                    batches = [it.next() for it in self._iters]
                except StopIteration:
                    q.put(None)
                    return
                except Exception as e:  # surface at the consumer's next()
                    q.put(e)
                    return
                q.put(batches)

        self._thread = threading.Thread(
            target=worker, name="mxtpu-io-prefetch", daemon=True)
        self._thread.start()

    def next(self):
        if self._stop.is_set():
            raise StopIteration  # already exhausted; producer is gone
        item = self._queue.get()
        if item is None:
            self._stop.set()
            raise StopIteration
        if isinstance(item, Exception):
            self._stop.set()
            raise item
        return item[0] if len(item) == 1 else item

    def reset(self):
        """Stop the producer, reset the wrapped iterators, restart
        (multi-epoch training over the legacy prefetcher — the round-2
        NotImplementedError is gone)."""
        self._stop.set()
        # unblock a producer stuck on a full queue, then wait for it
        while self._thread.is_alive():
            try:
                self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=0.05)
        for it in self._iters:
            it.reset()
        self._start_worker()


from .image_record import ImageRecordIter  # noqa: E402  (needs DataIter above)

from . import io  # noqa: F401,E402  (reference spelling: mx.io.io.*)
