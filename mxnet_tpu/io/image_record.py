"""Threaded, augmenting RecordIO image iterator.

Reference behavior being re-created (not copied):
  - src/io/iter_image_recordio_2.cc:156-158 — ImageRecordIOParser2 decodes
    records with an OMP thread pool and hands batches to a prefetcher.
  - src/io/image_aug_default.cc — DefaultImageAugmenter parameter set and
    application order: resize -> rotate/shear -> pad -> crop (random-resized /
    random / center) -> mirror -> HSL jitter -> cast -> mean/std -> scale.
  - src/io/iter_batchloader.h — round_batch wraps the final partial batch to
    the start of the data and reports the wrapped count as DataBatch.pad.

TPU re-design: host-side decode+augment runs as one task per batch on the
native ordered prefetch pipeline (native/mxtpu_runtime.cc `Pipeline`: C++
worker threads, results pop in submission order, bounded-capacity
back-pressure). PIL's JPEG decode and numpy's slicing release the GIL, so
`preprocess_threads` workers genuinely overlap; the device transfer happens
on the consumer thread so batches land on the accelerator in order.
Determinism: every batch derives its own np.random.RandomState from
(seed, epoch, batch index) — a reshuffled epoch replays exactly given the
same seed, independent of worker timing.
"""
from __future__ import annotations

import numpy as _np

from . import DataBatch, DataDesc, DataIter
from .. import numpy as mnp

__all__ = ["ImageRecordIter"]


def _interp_pil(inter_method, rs=None):
    """Reference inter_method codes (cv2 numbering) to PIL resample —
    shares mx.image's table (one mapping to keep in sync) and adds the
    iterator-only code 10 = random interp per image."""
    from ..image.image import _interp_pil as _base

    if inter_method == 10 and rs is not None:
        return _base(int(rs.randint(0, 5)))
    return _base(int(inter_method))


def _resize(img, w, h, resample):
    from PIL import Image

    if img.shape[:2] == (h, w):
        return img
    mode_img = Image.fromarray(img.squeeze(-1) if img.shape[2] == 1 else img)
    out = _np.asarray(mode_img.resize((w, h), resample))
    if out.ndim == 2:
        out = out[:, :, None]
    return out


def _resize_short(img, size, resample):
    h, w = img.shape[:2]
    if h < w:
        return _resize(img, max(1, w * size // h), size, resample)
    return _resize(img, size, max(1, h * size // w), resample)


def _rgb_to_hls(img):
    """Vectorized RGB->HLS on floats in [0,1] (H in [0,360))."""
    r, g, b = img[..., 0], img[..., 1], img[..., 2]
    maxc = _np.max(img, axis=-1)
    minc = _np.min(img, axis=-1)
    l = (maxc + minc) / 2.0
    delta = maxc - minc
    s = _np.where(delta == 0, 0.0,
                  _np.where(l <= 0.5, delta / _np.maximum(maxc + minc, 1e-12),
                            delta / _np.maximum(2.0 - maxc - minc, 1e-12)))
    d = _np.maximum(delta, 1e-12)
    h = _np.where(maxc == r, ((g - b) / d) % 6.0,
                  _np.where(maxc == g, (b - r) / d + 2.0, (r - g) / d + 4.0))
    h = _np.where(delta == 0, 0.0, h * 60.0)
    return h, l, s


def _hls_to_rgb(h, l, s):
    c = (1.0 - _np.abs(2.0 * l - 1.0)) * s
    hp = (h % 360.0) / 60.0
    x = c * (1.0 - _np.abs(hp % 2.0 - 1.0))
    z = _np.zeros_like(c)
    cond = [(hp < 1), (hp < 2), (hp < 3), (hp < 4), (hp < 5), (hp >= 5)]
    r = _np.select(cond, [c, x, z, z, x, c])
    g = _np.select(cond, [x, c, c, x, z, z])
    b = _np.select(cond, [z, z, x, c, c, x])
    m = l - c / 2.0
    return _np.stack([r + m, g + m, b + m], axis=-1)


class ImageRecordIter(DataIter):
    """RecordIO image iterator with the reference augmenter set and a
    native worker pool (see module docstring for reference file:line map).

    Unknown keyword arguments raise TypeError — reference training scripts
    must either run with identical augmentation semantics or fail loudly,
    never silently train on un-augmented data (VERDICT r2 "weak" #2).
    """

    def __init__(self, path_imgrec, data_shape, batch_size=1,
                 shuffle=False, label_width=1, path_imgidx=None,
                 preprocess_threads=4, prefetch_buffer=4,
                 shuffle_chunk_size=0, shuffle_chunk_seed=0, seed=0,
                 round_batch=True, num_parts=1, part_index=0,
                 verbose=False, dtype="float32", layout="NCHW",
                 # --- augmenter params (image_aug_default.cc order) ---
                 resize=-1, max_random_scale=1.0, min_random_scale=1.0,
                 max_rotate_angle=0, rotate=-1, max_shear_ratio=0.0,
                 pad=0, fill_value=255,
                 rand_crop=False, rand_resized_crop=False,
                 max_random_area=1.0, min_random_area=1.0,
                 max_aspect_ratio=0.0, min_aspect_ratio=None,
                 max_crop_size=-1, min_crop_size=-1,
                 rand_mirror=False, mirror=False,
                 random_h=0, random_s=0, random_l=0,
                 brightness=0.0, contrast=0.0, saturation=0.0,
                 pca_noise=0.0, rand_gray=0.0,
                 mean_img=None, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 mean_a=0.0, std_r=1.0, std_g=1.0, std_b=1.0, std_a=1.0,
                 scale=1.0, inter_method=1,
                 **kwargs):
        if kwargs:
            raise TypeError(
                "ImageRecordIter: unsupported argument(s) "
                f"{sorted(kwargs)} — refusing to silently change training "
                "semantics. Supported args mirror "
                "src/io/image_aug_default.cc; see the class docstring.")
        super().__init__(batch_size)
        from ..recordio import IndexedRecordIO

        self._rec = (IndexedRecordIO(path_imgidx, path_imgrec)
                     if path_imgidx else IndexedRecordIO(path_imgrec))
        self._shape = tuple(data_shape)          # (C, H, W)
        if len(self._shape) != 3:
            raise ValueError(f"data_shape must be (C,H,W), got {data_shape}")
        self._label_width = int(label_width)
        self._shuffle = shuffle
        self._seed = int(seed)
        self._round_batch = round_batch
        self._dtype = _np.dtype(dtype)
        self._verbose = verbose
        if layout not in ("NCHW", "NHWC"):
            raise ValueError(f"layout must be NCHW or NHWC, got {layout}")
        if min_aspect_ratio is not None and max_aspect_ratio <= 0:
            raise ValueError(
                "min_aspect_ratio requires max_aspect_ratio > 0 "
                "(the sampled range is [min_aspect_ratio, "
                "max_aspect_ratio])")
        # NHWC ships batches channels-last: skips the host-side transpose
        # and matches the TPU-native layout the flagship models train in
        # (data_shape stays (C,H,W) for reference-script compatibility)
        self._layout = layout
        del shuffle_chunk_size, shuffle_chunk_seed  # full shuffle supersedes

        # augment config, resolved once
        c = self._shape[0]
        mean = None
        if mean_img is not None:
            mean = _np.load(mean_img).astype(_np.float32)
            if mean.ndim == 3 and mean.shape[0] in (1, 3, 4):
                mean = mean.transpose(1, 2, 0)   # CHW mean file -> HWC
        elif any(v != 0 for v in (mean_r, mean_g, mean_b, mean_a)):
            mean = _np.asarray(
                [mean_r, mean_g, mean_b, mean_a][:c], _np.float32)
        std = None
        if any(v != 1 for v in (std_r, std_g, std_b, std_a)):
            std = _np.asarray([std_r, std_g, std_b, std_a][:c], _np.float32)
        self._aug = dict(
            resize=resize, max_random_scale=max_random_scale,
            min_random_scale=min_random_scale,
            max_rotate_angle=max_rotate_angle, rotate=rotate,
            max_shear_ratio=max_shear_ratio, pad=pad, fill_value=fill_value,
            rand_crop=rand_crop, rand_resized_crop=rand_resized_crop,
            max_random_area=max_random_area, min_random_area=min_random_area,
            max_aspect_ratio=max_aspect_ratio,
            min_aspect_ratio=min_aspect_ratio,
            max_crop_size=max_crop_size, min_crop_size=min_crop_size,
            rand_mirror=rand_mirror, mirror=mirror,
            random_h=random_h, random_s=random_s, random_l=random_l,
            brightness=brightness, contrast=contrast, saturation=saturation,
            pca_noise=pca_noise, rand_gray=rand_gray,
            mean=mean, std=std, scale=scale, inter_method=inter_method)

        # partition (num_parts/part_index: contiguous split, matching the
        # reference's dist-training sharding of the record index)
        n = len(self._rec)
        all_idx = _np.arange(n)
        if num_parts > 1:
            all_idx = _np.array_split(all_idx, num_parts)[part_index]
        self._indices = all_idx
        self._epoch = -1

        from .._native import NATIVE, NativePipeline

        self._pipe = None
        self._threads = int(preprocess_threads)
        self._capacity = int(max(2, prefetch_buffer))
        if NATIVE is not None and preprocess_threads > 0:
            self._pipe = NativePipeline(num_threads=self._threads,
                                        capacity=self._capacity)
        self._pending = 0
        self.reset()

    # ------------------------------------------------------------------
    @property
    def provide_data(self):
        c, h, w = self._shape
        shp = (c, h, w) if self._layout == "NCHW" else (h, w, c)
        return [DataDesc("data", (self.batch_size,) + shp, self._dtype,
                         layout=self._layout)]

    @property
    def provide_label(self):
        shp = ((self.batch_size,) if self._label_width == 1
               else (self.batch_size, self._label_width))
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        # discard any in-flight batches from the previous epoch; a failed
        # task consumed its ticket with the error, so count it drained too
        while self._pending:
            try:
                self._pipe.pop(timeout=60)
                self._pending -= 1
            except TimeoutError:
                # a wedged worker would deadlock close(); abandon the
                # native pipeline (see NativePipeline.abandon) and start
                # a fresh one rather than hanging every future reset
                from .._native import NativePipeline

                self._pipe.abandon()
                self._pipe = NativePipeline(num_threads=self._threads,
                                            capacity=self._capacity)
                self._pending = 0
            except Exception:
                self._pending -= 1
        self._epoch += 1
        order = self._indices.copy()
        if self._shuffle:
            _np.random.RandomState(self._seed + self._epoch).shuffle(order)
        bs = self.batch_size
        n = len(order)
        batches = [order[i:i + bs] for i in range(0, n - bs + 1, bs)]
        rem = n % bs
        self._last_pad = 0
        if rem:
            if self._round_batch and n >= bs:
                wrap = order[: bs - rem]
                batches.append(_np.concatenate([order[n - rem:], wrap]))
                self._last_pad = bs - rem
            elif self._round_batch:      # dataset smaller than one batch
                reps = -(-bs // n)
                batches.append(_np.tile(order, reps)[:bs])
                self._last_pad = bs - rem
        self._batches = batches
        self._submit_cursor = 0
        self._pop_cursor = 0
        self._inline = []
        for _ in range(min(self._capacity, len(batches))):
            self._submit_one()

    # ------------------------------------------------------------------
    def _submit_one(self):
        if self._submit_cursor >= len(self._batches):
            return
        bi = self._submit_cursor
        self._submit_cursor += 1
        idx = self._batches[bi]
        raws = [self._rec.read_idx(int(i)) for i in idx]
        rng_seed = (self._seed * 1000003 + self._epoch * 8191 + bi) % (2**31)
        if self._pipe is not None:
            self._pipe.submit(lambda: self._make_batch(raws, rng_seed))
            self._pending += 1
        else:                                  # no native runtime: inline
            self._inline.append((raws, rng_seed))

    def next(self):
        if self._pop_cursor >= len(self._batches):
            raise StopIteration
        bi = self._pop_cursor
        self._pop_cursor += 1
        if self._pipe is not None:
            try:
                data, labels = self._pipe.pop(timeout=600)
                self._pending -= 1
            except TimeoutError:
                self._pop_cursor = bi    # ticket not consumed; retryable
                raise
            except StopIteration:
                raise RuntimeError("native pipeline closed unexpectedly")
            except Exception:
                # the failed task consumed its ticket along with the error:
                # account for it and keep the pipeline primed so the caller
                # can skip the bad record batch and keep iterating
                self._pending -= 1
                self._submit_one()
                raise
        else:
            raws, rng_seed = self._inline.pop(0)
            try:
                data, labels = self._make_batch(raws, rng_seed)
            except Exception:
                self._submit_one()   # keep the lookahead buffer full
                raise
        self._submit_one()
        pad = self._last_pad if bi == len(self._batches) - 1 else 0
        return DataBatch([mnp.array(data)], [mnp.array(labels)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    # ------------------------------------------------------------------
    # per-batch worker task (runs on a native pipeline thread)
    def _make_batch(self, raws, rng_seed):
        rs = _np.random.RandomState(rng_seed)
        a = self._aug
        imgs = []
        lw = self._label_width
        labels = _np.zeros((len(raws), lw), _np.float32)
        for j, s in enumerate(raws):
            header, img = self._decode(s)
            if img.ndim == 2:
                img = img[:, :, None]
            imgs.append(self._augment(img, rs))
            lab = _np.asarray(header.label, _np.float32).ravel()
            labels[j, : min(lw, lab.size)] = lab[:lw]
        # mean/std/scale + dtype cast vectorized over the whole batch —
        # one big numpy pass beats 128 small ones on the host CPU
        batch = _np.stack(imgs).astype(_np.float32, copy=False)  # NHWC
        if a["mean"] is not None:
            batch -= a["mean"]
        if a["std"] is not None:
            batch /= a["std"]
        if a["scale"] != 1.0:
            batch *= a["scale"]
        if self._layout == "NCHW":
            batch = batch.transpose(0, 3, 1, 2)
        batch = _np.ascontiguousarray(batch, dtype=self._dtype)
        if lw == 1:
            labels = labels[:, 0]
        return batch, labels

    def _decode(self, s):
        """Unpack + decode one record. JPEGs decode via PIL draft() at the
        smallest DCT scale that still covers the resize target — libjpeg
        skips the unneeded inverse-DCT work, a large win on real photos
        (the iter_image_recordio_2.cc parser gets the same effect from
        cv2's JPEG scaled decoding)."""
        import io as _io

        from ..recordio import unpack

        header, payload = unpack(s)
        if payload[:6] == b"\x93NUMPY":
            return header, _np.load(_io.BytesIO(payload))
        from PIL import Image

        im = Image.open(_io.BytesIO(payload))
        target = self._aug["resize"]
        if target > 0 and im.format == "JPEG" and not (
                self._aug["rand_resized_crop"]
                or self._aug["max_crop_size"] > 0):
            # draft never shrinks below the requested bounding size, so the
            # exact shorter-edge resize to `resize` downstream is
            # unaffected. Skipped when resize is unset (crops must come
            # from the full-resolution image, as in the reference) and for
            # area-based crops whose statistics depend on full size.
            im.draft(im.mode, (target, target))
        return header, _np.asarray(im)

    def _fix_channels(self, img):
        c = self._shape[0]
        if img.shape[2] == c:
            return img
        if c == 1:
            return img.mean(axis=2, keepdims=True).astype(img.dtype)
        if img.shape[2] == 1:
            return img.repeat(c, axis=2)
        return img[:, :, :c]

    def _augment(self, img, rs):
        """Apply the DefaultImageAugmenter sequence to one HWC uint8 image."""
        a = self._aug
        c, th, tw = self._shape
        img = self._fix_channels(img)
        interp = _interp_pil(a["inter_method"], rs)

        # 1. resize shorter edge (with optional random scale jitter)
        sc = 1.0
        if a["max_random_scale"] != 1.0 or a["min_random_scale"] != 1.0:
            sc = rs.uniform(a["min_random_scale"], a["max_random_scale"])
        if a["resize"] > 0:
            img = _resize_short(img, max(1, int(round(a["resize"] * sc))),
                                interp)
        elif sc != 1.0:
            h, w = img.shape[:2]
            img = _resize(img, max(1, int(round(w * sc))),
                          max(1, int(round(h * sc))), interp)

        # 2. rotation / shear (one PIL pass each, filled with fill_value)
        angle = None
        if a["rotate"] >= 0:
            angle = float(a["rotate"])
        elif a["max_rotate_angle"] > 0:
            angle = float(rs.uniform(-a["max_rotate_angle"],
                                     a["max_rotate_angle"]))
        shear = None
        if a["max_shear_ratio"] > 0:
            shear = float(rs.uniform(-a["max_shear_ratio"],
                                     a["max_shear_ratio"]))
        if angle or shear:
            from PIL import Image

            fv = a["fill_value"]
            fill = tuple([int(fv)] * 3) if img.shape[2] == 3 else int(fv)
            pimg = Image.fromarray(
                img.squeeze(-1) if img.shape[2] == 1 else img)
            # PIL rotate/transform only accept NEAREST/BILINEAR/BICUBIC
            rinterp = interp if interp in (
                Image.NEAREST, Image.BILINEAR, Image.BICUBIC) \
                else Image.BICUBIC
            if angle:
                pimg = pimg.rotate(angle, resample=rinterp, fillcolor=fill)
            if shear:
                pimg = pimg.transform(
                    pimg.size, Image.AFFINE, (1.0, shear, 0.0, 0.0, 1.0, 0.0),
                    resample=rinterp, fillcolor=fill)
            img = _np.asarray(pimg)
            if img.ndim == 2:
                img = img[:, :, None]

        # 3. pad border
        if a["pad"] > 0:
            p = int(a["pad"])
            img = _np.pad(img, ((p, p), (p, p), (0, 0)), constant_values=
                          a["fill_value"]).astype(img.dtype)

        # 4. crop to (th, tw)
        img = self._crop(img, rs, interp)

        # 5. mirror
        if a["mirror"] or (a["rand_mirror"] and rs.rand() < 0.5):
            img = img[:, ::-1]

        photometric = ((c == 3 and (a["random_h"] or a["random_s"]
                                    or a["random_l"]))
                       or a["brightness"] or a["contrast"]
                       or (a["saturation"] and c == 3)
                       or a["pca_noise"] > 0 or a["rand_gray"] > 0)
        if not photometric:
            # stay uint8 — the float cast happens batch-vectorized
            return img
        img = img.astype(_np.float32)

        # 6. HSL jitter (reference random_h in degrees, random_s/l in
        # 0-255 units, each sampled uniformly in [-x, x])
        if c == 3 and (a["random_h"] or a["random_s"] or a["random_l"]):
            h, l, s = _rgb_to_hls(img / 255.0)
            if a["random_h"]:
                h = h + rs.uniform(-a["random_h"], a["random_h"])
            if a["random_s"]:
                s = _np.clip(s + rs.uniform(-a["random_s"], a["random_s"])
                             / 255.0, 0.0, 1.0)
            if a["random_l"]:
                l = _np.clip(l + rs.uniform(-a["random_l"], a["random_l"])
                             / 255.0, 0.0, 1.0)
            img = _np.clip(_hls_to_rgb(h, l, s), 0.0, 1.0) * 255.0

        # 6b. photometric jitters shared with CreateAugmenter semantics
        if a["brightness"]:
            img *= 1.0 + rs.uniform(-a["brightness"], a["brightness"])
        if a["contrast"]:
            alpha = 1.0 + rs.uniform(-a["contrast"], a["contrast"])
            gray = img.mean() if c == 1 else \
                (img @ _np.asarray([0.299, 0.587, 0.114],
                                   _np.float32)).mean()
            img = img * alpha + gray * (1 - alpha)
        if a["saturation"] and c == 3:
            alpha = 1.0 + rs.uniform(-a["saturation"], a["saturation"])
            gray = (img @ _np.asarray([0.299, 0.587, 0.114],
                                      _np.float32))[..., None]
            img = img * alpha + gray * (1 - alpha)
        if a["pca_noise"] > 0 and c == 3:
            eigval = _np.asarray([55.46, 4.794, 1.148], _np.float32)
            eigvec = _np.asarray([[-0.5675, 0.7192, 0.4009],
                                  [-0.5808, -0.0045, -0.8140],
                                  [-0.5836, -0.6948, 0.4203]], _np.float32)
            alpha = rs.normal(0, a["pca_noise"], 3).astype(_np.float32)
            img = img + eigvec @ (alpha * eigval)
        if a["rand_gray"] > 0 and c == 3 and rs.rand() < a["rand_gray"]:
            img = _np.broadcast_to(
                (img @ _np.asarray([0.299, 0.587, 0.114],
                                   _np.float32))[..., None],
                img.shape).copy()

        # mean / std / scale / cast happen batch-vectorized in _make_batch
        return img

    def _crop(self, img, rs, interp):
        a = self._aug
        _, th, tw = self._shape
        h, w = img.shape[:2]
        if a["rand_resized_crop"]:
            # random-area random-aspect crop, resized to target (the
            # Inception-style crop the reference uses for ImageNet)
            if a["min_aspect_ratio"] is not None:
                ratio_rng = (a["min_aspect_ratio"], a["max_aspect_ratio"])
            elif a["max_aspect_ratio"] > 0:
                ratio_rng = (1.0 / (1.0 + a["max_aspect_ratio"]),
                             1.0 + a["max_aspect_ratio"])
            else:
                ratio_rng = (3 / 4.0, 4 / 3.0)
            area = h * w
            for _ in range(10):
                targ = rs.uniform(a["min_random_area"],
                                  a["max_random_area"]) * area
                ratio = rs.uniform(*ratio_rng)
                cw = int(round((targ * ratio) ** 0.5))
                ch = int(round((targ / ratio) ** 0.5))
                if 0 < cw <= w and 0 < ch <= h:
                    x0 = rs.randint(0, w - cw + 1)
                    y0 = rs.randint(0, h - ch + 1)
                    return _resize(img[y0:y0 + ch, x0:x0 + cw], tw, th,
                                   interp)
            return self._center(img, interp)
        if a["max_crop_size"] > 0 or a["min_crop_size"] > 0:
            # random square crop in [min_crop_size, max_crop_size], then
            # resize to target (reference legacy rand_crop sizing)
            lo = a["min_crop_size"] if a["min_crop_size"] > 0 else 1
            hi = min(a["max_crop_size"] if a["max_crop_size"] > 0
                     else min(h, w), min(h, w))
            cs = int(rs.randint(min(lo, hi), hi + 1))
            x0 = rs.randint(0, w - cs + 1)
            y0 = rs.randint(0, h - cs + 1)
            return _resize(img[y0:y0 + cs, x0:x0 + cs], tw, th, interp)
        if a["rand_crop"]:
            if h < th or w < tw:
                img = _resize_short(img, max(th, tw), interp)
                h, w = img.shape[:2]
            x0 = rs.randint(0, w - tw + 1)
            y0 = rs.randint(0, h - th + 1)
            return img[y0:y0 + th, x0:x0 + tw]
        return self._center(img, interp)

    def _center(self, img, interp):
        _, th, tw = self._shape
        h, w = img.shape[:2]
        if h < th or w < tw:
            img = _resize_short(img, max(th, tw), interp)
            h, w = img.shape[:2]
        x0 = (w - tw) // 2
        y0 = (h - th) // 2
        return img[y0:y0 + th, x0:x0 + tw]

    def close(self):
        if getattr(self, "_pipe", None) is not None:
            self._pipe.close()
            self._pipe = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
