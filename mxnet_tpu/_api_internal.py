"""Internal packed-API namespace (reference: mxnet._api_internal — the
TVM-FFI module whose attributes are the `_npi.*` op entry points used by
the generated frontends). Attribute access resolves through the op
registry, so reference-era internals like `_api_internal.add(...)` or
`_api_internal.where_lscalar(...)` land on the same implementations as
the public names (ops/aliases.py)."""
from __future__ import annotations

from .ops.registry import _OPS


def __getattr__(name):
    for candidate in (name, f"_npi_{name}", f"_np_{name}", f"_{name}"):
        fn = _OPS.get(candidate)
        if fn is not None:
            return fn
    raise AttributeError(f"no registered op for _api_internal.{name}")


def __dir__():
    return sorted(_OPS)
