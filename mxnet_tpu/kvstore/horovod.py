"""Horovod kvstore adapter (reference: python/mxnet/kvstore/horovod.py:27).

On TPU the native collective path is `tpu_dist` (XLA psum over ICI); this
adapter exists for API parity with reference deployments that drive
training through `kvstore='horovod'`. It delegates broadcast/pushpull to
`horovod.mxnet` when that package is importable and raises a clear error
otherwise (horovod has no TPU backend — the error points at tpu_dist).
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["Horovod"]


@KVStoreBase.register
class Horovod(KVStoreBase):
    def __init__(self):
        # horovod.mxnet operates on MXNet C-handle NDArrays; this
        # framework's arrays are jax-backed, so even with horovod
        # installed the adapter cannot hand tensors across. Raise
        # ImportError either way — kvstore.create() falls back to
        # tpu_dist, whose pushpull honors the same contract.
        try:
            import horovod.mxnet as hvd  # noqa: PLC0415,F401
        except ImportError as e:
            raise ImportError(
                "kvstore='horovod' requires the horovod package; use "
                "kvstore='tpu_dist' — the XLA collective store with the "
                "same pushpull contract") from e
        raise ImportError(
            "horovod.mxnet drives MXNet C-handle arrays and has no "
            "jax/TPU backend; use kvstore='tpu_dist' (kvstore.create "
            "falls back automatically)")

    @property
    def rank(self):
        return self._hvd.rank()

    @property
    def num_workers(self):
        return self._hvd.size()

    def is_capable(self, capability):
        return capability in ("pushpull", "broadcast")

    def broadcast(self, key, value, out, priority=0):  # noqa: ARG002
        vals = value if isinstance(value, (list, tuple)) else [value]
        outs = out if isinstance(out, (list, tuple)) else [out]
        root = self._hvd.broadcast_(vals[0], root_rank=0, name=str(key))
        for o in outs:
            o._data = root._data
            o._version += 1

    def pushpull(self, key, value, out=None, priority=0):  # noqa: ARG002
        vals = value if isinstance(value, (list, tuple)) else [value]
        # sum local per-device copies first (the KVStoreBase contract
        # every store honors), then allreduce across workers
        local = vals[0]
        for v in vals[1:]:
            local = local + v
        reduced = self._hvd.allreduce_(local, average=False,
                                       name=str(key))
        if out is None:
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._data = reduced._data
            o._version += 1
