"""Horovod kvstore adapter (reference: python/mxnet/kvstore/horovod.py).

The reference adapter delegates broadcast/pushpull to `horovod.mxnet`, which
moves MXNet C-handle NDArrays. This framework's arrays are jax-backed
and cannot cross that ABI, and horovod has no TPU/jax backend — so the
adapter's construction always raises ImportError with the porting
guidance, and `kvstore.create('horovod')` falls back to `tpu_dist`,
whose pushpull honors the same KVStoreBase contract over XLA
collectives. The class stays registered so reference-era code that
probes `KVStoreBase.find('horovod')` keeps working.
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["Horovod"]


@KVStoreBase.register
class Horovod(KVStoreBase):
    def __init__(self):
        try:
            import horovod.mxnet  # noqa: PLC0415,F401
        except ImportError as e:
            raise ImportError(
                "kvstore='horovod' requires the horovod package; use "
                "kvstore='tpu_dist' — the XLA collective store with the "
                "same pushpull contract") from e
        raise ImportError(
            "horovod.mxnet drives MXNet C-handle arrays and has no jax/TPU "
            "backend; use kvstore='tpu_dist' (kvstore.create falls back "
            "automatically)")
