"""KVStore — data-parallel parameter/gradient communication.

Reference: src/kvstore/ (local/device comm, NCCL, ps-lite dist_sync/async, P3)
+ python/mxnet/kvstore/. TPU re-design per SURVEY.md §2.4/§5: the entire
parameter-server and NCCL machinery is replaced by XLA collectives —
`kvstore='tpu_dist'` runs pushpull as a jitted psum over the ICI mesh, with
multi-host scale-out via jax.distributed (one process per host). The
KVStoreBase plugin registry is preserved so external stores (horovod-style)
can be registered from Python.
"""
from .base import KVStoreBase  # noqa: F401
from .kvstore import KVStore, KVStoreLocal  # noqa: F401
from .byteps import BytePS  # noqa: F401 - registers 'byteps'
from .horovod import Horovod  # noqa: F401 - registers 'horovod'
from .tpu_dist import P3Store, TPUDist  # noqa: F401


class KVStoreServer:
    """ps-lite server-role shim (reference: kvstore/kvstore_server.py).

    The reference launches this loop in scheduler/server processes; on
    TPU the synchronous XLA-collective store has no server role (see
    docs/distributed_training.md "Why there is no dist_async"), so
    construction succeeds for import parity and run() explains itself
    instead of blocking forever."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def run(self):
        raise RuntimeError(
            "KVStoreServer has no role on TPU: kvstore='tpu_dist' is "
            "serverless (XLA collectives over ICI/DCN). Launch workers "
            "only — see tools/launch.py and docs/distributed_training.md")


def create(name="local"):
    """Create a KVStore by type name (reference: kvstore.cc:41-79 factory).

    Supported: 'local', 'device' (single-process aggregation),
    'tpu_dist' / 'dist_sync' / 'dist' / 'nccl' / 'horovod' (all map to the
    XLA-collective store — there is one true comm path on TPU), plus any
    python class registered via KVStoreBase.register.
    """
    name_l = name.lower()
    if name_l in ("local", "device", "local_allreduce_cpu",
                  "local_allreduce_device"):
        return KVStoreLocal(name_l)
    if name_l == "p3":
        return P3Store()
    if name_l in ("horovod", "byteps"):
        # the registered adapter raises ImportError (package missing, or
        # present but jax-incompatible — see kvstore/horovod.py); fall
        # back to the XLA-collective store, which honors the contract
        try:
            cls = KVStoreBase.find(name_l)
            return cls()
        except ImportError as e:
            import logging

            logging.getLogger(__name__).info(
                "kvstore='%s' unavailable (%s); falling back to tpu_dist",
                name_l, e)
            return TPUDist()
    if name_l in ("tpu_dist", "dist_sync", "dist_async", "dist",
                  "dist_sync_device", "dist_async_device", "nccl"):
        return TPUDist()
    cls = KVStoreBase.find(name_l)
    if cls is not None:
        return cls()
    raise ValueError(f"unknown kvstore type '{name}'")
