"""tpu_dist — distributed KVStore over XLA collectives.

Replaces the reference's entire ps-lite parameter-server stack
(src/kvstore/kvstore_dist.h, kvstore_dist_server.h) and NCCL store with the
one true TPU comm path: allreduce (psum) over the ICI mesh, compiled by XLA.

Design (SURVEY.md §5 "Distributed communication backend"):
  * single host, N chips: values live per-device; pushpull stacks them onto
    the device mesh and runs a jitted `shard_map` psum — XLA emits an
    all-reduce that rides ICI, fully async and overlappable with compute
    (replacing CommDevice + NCCL + P3 priority scheduling, which the XLA
    latency-hiding scheduler subsumes);
  * multi host: `jax.distributed.initialize()` (the tools/launch.py analog),
    `rank`/`num_workers` = jax.process_index/process_count, and the same
    jitted collective spans the whole slice (ICI) or crosses slices (DCN).

Gradient compression (1-bit/2-bit with error feedback,
src/kvstore/gradient_compression.cc) is available via
set_gradient_compression — the packed uint8 payload is what would cross
DCN between hosts; within a slice ICI moves bf16 faster than quantization
costs, so it is opt-in exactly like the reference.
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp

from ..diagnostics import spans as _spans
from ..diagnostics import watchdog as _watchdog
from ..ndarray.ndarray import NDArray, _wrap_out
from ..telemetry import instruments as _telemetry
from .base import KVStoreBase

__all__ = ["TPUDist", "init_distributed_from_env"]

_dist_initialized = False


def init_distributed_from_env():
    """Wire this process into the jax.distributed job described by the
    tools/launch.py env (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID) — the analog of the reference workers connecting to the
    dmlc tracker (tools/launch.py:72-117). No-op when not launched
    distributed or already initialized."""
    global _dist_initialized
    if _dist_initialized:
        return
    n = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if n <= 1:
        return
    coordinator = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=n,
        process_id=int(os.environ["JAX_PROCESS_ID"]))
    _dist_initialized = True


def _aslist(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class TPUDist(KVStoreBase):
    """kvstore='tpu_dist': allreduce over every device in the process/slice."""

    def __init__(self, devices=None):
        init_distributed_from_env()
        self._devices = devices  # optional explicit jax device list
        self._optimizer = None
        self._sum_cache = {}
        self._sharding_plan = None  # set by Trainer (set_sharding_plan)
        try:
            # stamp (job, rank) into flight events + span records so
            # tools/blackbox.py can align this rank's postmortem bundle
            # with its peers on the shared (job_id, step) trace ID, and
            # so the ops server's /identity endpoint answers with this
            # rank's place in the job (tools/fleetctl.py keys its fleet
            # table on it)
            from ..observability import flight as _flight

            _flight.set_identity(rank=self.rank, world=self.num_workers)
            _flight.record("dist_init", rank=self.rank,
                           world=self.num_workers)
        except Exception:
            pass
        if self.num_workers > 1:
            # establish the cross-process collective context NOW, while rank
            # skew is minimal — later pushpulls may be separated by long
            # per-rank compiles that would trip gloo's init timeout
            self._cross_process_sum(jnp.zeros((1,), jnp.float32))

    # -- topology ----------------------------------------------------------
    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return jax.process_count()

    @property
    def num_devices(self):
        return len(self._devices) if self._devices else jax.local_device_count()

    def is_capable(self, capability):
        return capability in ("optimizer", "pushpull", "broadcast")

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer

    # -- collectives -------------------------------------------------------
    def _tree_sum(self, n):
        """Jitted n-way add; cached per n (the CommDevice reduce analog)."""
        fn = self._sum_cache.get(n)
        if fn is None:
            def add_n(*xs):
                total = xs[0]
                for x in xs[1:]:
                    total = total + x
                return total

            fn = jax.jit(add_n)
            self._sum_cache[n] = fn
        return fn

    def pushpull(self, key, value, out=None, priority=0):  # noqa: ARG002
        """Sum `value` copies across device copies AND processes, write the
        result to `out` with each out's own sharding preserved.

        Three regimes, all behind the one KVStoreDist::PushPullImpl contract
        (kvstore_dist.h:218):
          * one global mesh-sharded jax.Array: eager SPMD already produced
            the globally-reduced gradient (XLA inserted the psum during the
            backward) — this is a sharding-preserving no-op;
          * several per-device copies (legacy multi-copy layout): jitted
            add-tree reduce, then broadcast back to each copy's sharding;
          * multiple processes (after jax.distributed.initialize, the
            tools/launch.py path): cross-process sum via process_allgather —
            the eager-mode DCN staged reduce; inside jit the GSPMD step is
            the fast path.
        """
        keys = _aslist(key)
        if len(keys) != 1:
            vals = value
            outs = out if out is not None else [None] * len(keys)
            if self._pushpull_fused(keys, vals, outs):
                return
            for k, v, o in zip(keys, vals, outs):
                self.pushpull(k, v, o, priority)
            return
        t0 = time.perf_counter()
        vals = _aslist(value)
        with _spans.span("kv.pushpull", cat="collective"), \
                _watchdog.guard("kv.pushpull"):
            vals = self._compress_vals(str(keys[0]), vals)
            if len(vals) == 1:
                total_data = vals[0]._data
            else:
                # reduce on the first value's device; XLA moves operands
                # over ICI
                dev = next(iter(vals[0]._data.devices()))
                datas = [jax.device_put(v._data, dev) for v in vals]
                total_data = self._tree_sum(len(datas))(*datas)
            if self.num_workers > 1:
                total_data = self._cross_process_sum(total_data)
        _telemetry.record_collective(
            "pushpull",
            sum(_telemetry.nbytes_of(v._data) for v in vals),
            time.perf_counter() - t0)
        if out is None:
            return
        outs = _aslist(out)
        for o in outs:
            o._data = self._put_like(total_data, o._data)
            o._version += 1

    def _pushpull_fused(self, keys, values, outs, priority=0):  # noqa: ARG002
        """Bucketed flat allreduce for a list-form pushpull (the DDP
        multi-tensor path, docs/performance.md): per-key device copies are
        flattened, concatenated into dtype-homogeneous buffers of
        ~MXTPU_FUSED_BUCKET_MB MB, and each buffer is reduced in ONE
        jitted dispatch (concat + add-tree + split traced together) —
        O(buckets) launches instead of O(keys). Returns False when the
        call shape can't take the fused path (no outs, compression on,
        multi-process, ragged copy counts) so the caller falls back to
        the per-key loop."""
        from .. import env as _env

        if (not _env.get("MXTPU_FUSED_UPDATE") or outs is None
                or any(o is None for o in outs)
                or self._compression is not None
                or self.num_workers > 1):
            return False
        vals_lists = [_aslist(v) for v in values]
        outs_lists = [_aslist(o) for o in outs]
        ncopies = len(vals_lists[0])
        if any(len(v) != ncopies for v in vals_lists):
            return False
        from ..parallel.collectives import _flat_buckets

        t0 = time.perf_counter()
        primaries = [v[0]._data for v in vals_lists]
        cap = int(_env.get("MXTPU_FUSED_BUCKET_MB")) << 20
        buckets = _flat_buckets(primaries, cap)
        with _spans.span("kv.pushpull", cat="collective"), \
                _watchdog.guard("kv.pushpull"):
            for bucket in buckets:
                if ncopies == 1:
                    # single copy, nothing to sum: honor the write-back
                    # contract (out gets the value, version bump) with
                    # zero device dispatches
                    reduced = [vals_lists[j][0]._data for j in bucket]
                else:
                    dev = next(iter(
                        vals_lists[bucket[0]][0]._data.devices()))
                    parts = [
                        [jax.device_put(vals_lists[j][d]._data, dev)
                         for j in bucket]
                        for d in range(ncopies)]
                    fn = self._fused_reduce_fn(
                        ncopies,
                        tuple((p.shape, str(p.dtype))
                              for p in parts[0]))
                    reduced = fn(parts)
                for j, red in zip(bucket, reduced):
                    for o in outs_lists[j]:
                        o._data = self._put_like(red, o._data)
                        o._version += 1
                _telemetry.record_fused_bucket("allreduce", len(bucket))
        _telemetry.record_collective(
            "pushpull",
            sum(_telemetry.nbytes_of(v._data)
                for vl in vals_lists for v in vl),
            time.perf_counter() - t0)
        return True

    def _fused_reduce_fn(self, ncopies, sig):
        """Jitted flat reduce for one bucket: concat each copy's members
        into a flat buffer, add the copies, split back to member shapes —
        one XLA program per (ncopies, member shapes) signature."""
        key = ("fused_reduce", ncopies, sig)
        fn = self._sum_cache.get(key)
        if fn is None:
            def reduce(parts):
                flats = [
                    copy[0].reshape(-1) if len(copy) == 1
                    else jnp.concatenate([p.reshape(-1) for p in copy])
                    for copy in parts]
                total = flats[0]
                for f in flats[1:]:
                    total = total + f
                red, off = [], 0
                for p in parts[0]:
                    red.append(total[off:off + p.size].reshape(p.shape))
                    off += p.size
                return red

            fn = jax.jit(reduce)
            self._sum_cache[key] = fn
        return fn

    @staticmethod
    def _put_like(data, like):
        """Lay `data` out with `like`'s sharding (never collapses a mesh-
        sharded array onto one device)."""
        sh = getattr(like, "sharding", None)
        if sh is not None and getattr(data, "sharding", None) == sh:
            return data
        return jax.device_put(data, sh) if sh is not None else data

    def _cross_process_sum(self, x):
        """Eager cross-process allreduce (multi-host eager mode only)."""
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(x)
        return jnp.sum(jnp.asarray(gathered), axis=0)

    def barrier(self):
        """Block until every worker reaches this point (a trivial
        collective — process_allgather completes only once all
        processes contribute). Single-process: no-op. Used by the
        checkpoint manager to fence rank-0 commits (docs/checkpointing
        .md); must run on the main thread like any collective."""
        if self.num_workers <= 1:
            return
        t0 = time.perf_counter()
        with _spans.span("kv.barrier", cat="collective"), \
                _watchdog.guard("kv.barrier"):
            self._cross_process_sum(jnp.zeros((1,), jnp.float32))
        _telemetry.record_collective(
            "barrier", 4, time.perf_counter() - t0)

    def broadcast(self, key, value, out, priority=0):  # noqa: ARG002
        t0 = time.perf_counter()
        vals = _aslist(value)
        outs = _aslist(out)
        with _spans.span("kv.broadcast", cat="collective"), \
                _watchdog.guard("kv.broadcast"):
            src = vals[0]._data
            if self.num_workers > 1:
                from jax.experimental import multihost_utils

                src = jnp.asarray(
                    multihost_utils.broadcast_one_to_all(src))
            for o in outs:
                o._data = self._put_like(src, o._data)
                o._version += 1
        _telemetry.record_collective(
            "broadcast", _telemetry.nbytes_of(src),
            time.perf_counter() - t0)

    # -- mesh-sharded fast path -------------------------------------------
    def allreduce_sharded(self, arrays, mesh=None, axis="dp"):
        """Allreduce jax.Arrays already sharded over a mesh axis via psum.

        This is the path the sharded Trainer/train-step uses: gradients come
        out of a shard_map-ped backward already device-local; one psum over
        the 'dp' axis completes data parallelism. Returns reduced arrays.
        With the fused-update path on (MXTPU_FUSED_UPDATE, the default) the
        tree rides the bucketed flat allreduce — one collective per ~25 MB
        flat buffer instead of one per leaf.
        """
        from .. import env as _env
        from ..parallel import collectives

        if mesh is None and self._sharding_plan is not None:
            mesh = self._sharding_plan.mesh
            axis = self._sharding_plan.batch_axis
        if _env.get("MXTPU_FUSED_UPDATE"):
            return collectives.psum_tree_flat(arrays, mesh=mesh, axis=axis)
        return collectives.psum_tree(arrays, mesh=mesh, axis=axis)

    def reduce_scatter_sharded(self, arrays, mesh=None, axis=None):
        """Reduce-scatter jax.Arrays along the plan's ZeRO axis.

        The eager half of the ZeRO-sharded optimizer contract
        (docs/sharding.md): each rank ends up owning the reduced 1/n
        slice of every gradient along `axis`, matching the sharded
        optimizer-bucket layout that `ShardingPlan.state_spec_for`
        assigns. The compiled whole-step path gets the same layout for
        free — GSPMD lowers the in-program sharding constraints to
        reduce-scatter + all-gather — so this method exists for eager /
        phased callers that want sharded-state updates without the
        compiled step. Defaults mesh/axis from the adopted plan's
        ``zero_axis()``; raises if no ZeRO axis is available.
        """
        if mesh is None and self._sharding_plan is not None:
            mesh = self._sharding_plan.mesh
        if axis is None and self._sharding_plan is not None:
            axis = self._sharding_plan.zero_axis()
        if mesh is None or axis is None:
            raise ValueError(
                "reduce_scatter_sharded needs a mesh and a ZeRO axis: "
                "pass them explicitly or set_sharding_plan() a plan "
                "whose zero_axis() is not None (fsdp axis present and "
                "MXTPU_ZERO on)")
        from ..parallel import collectives

        return jax.tree_util.tree_map(
            lambda v: collectives.reduce_scatter(v, mesh, axis=axis),
            arrays)

    def set_sharding_plan(self, plan):
        """Adopt a ShardingPlan (Trainer calls this when constructed
        with mesh=/sharding_plan=): the plan's mesh and data axis become
        the defaults for allreduce_sharded, and its ``zero_axis()`` the
        default for reduce_scatter_sharded, so sharded-gradient reduces
        need no per-call topology arguments."""
        self._sharding_plan = plan

    def traced_allreduce(self, tree, axis="dp", bucket_mb=None):
        """In-program gradient allreduce for the whole-step compiled path
        (gluon/train_step.py): called from INSIDE an already-running
        shard_map trace, so the reduce compiles into the same XLA program
        as forward/backward/update — zero extra dispatches. Rides the
        same dtype-homogeneous flat buckets as the eager
        `allreduce_sharded` path (collectives.psum_tree_flat)."""
        from ..parallel import collectives

        return collectives.psum_tree_flat_traced(tree, axis, bucket_mb)


# reference-parity alias so KVStoreBase.find('tpudist') works
KVStoreBase.register(TPUDist)


class P3Store(TPUDist):
    """kvstore='p3' — priority-based propagation (reference:
    src/kvstore/p3store_dist.h).

    The reference sliced big tensors and scheduled ps-lite sends by layer
    priority so late-layer comm overlapped early-layer backprop. On TPU
    the transport is an XLA collective, so the two P3 mechanisms become:

      * slicing: tensors larger than MXNET_KVSTORE_BIGARRAY_BOUND elements
        are reduced in independent chunks — each chunk's reduce dispatches
        asynchronously, letting XLA pipeline transfer/compute instead of
        serializing one monolithic reduce;
      * priority: dispatch order. `Trainer.allreduce_grads` issues calls
        in descending priority; the list-of-keys form below re-sorts by
        its per-key priorities.
    """

    def __init__(self, devices=None):
        super().__init__(devices)
        from .. import env as _env

        if "MXNET_KVSTORE_BIGARRAY_BOUND" not in _env.all_vars():
            _env.register(
                "MXNET_KVSTORE_BIGARRAY_BOUND", int, 1 << 20,
                "Element count above which kvstore='p3' slices a tensor "
                "into independently-dispatched reduce chunks (reference: "
                "P3 slicing, p3store_dist.h).")
        self._bound = _env.get("MXNET_KVSTORE_BIGARRAY_BOUND")

    def pushpull(self, key, value, out=None, priority=0):
        keys = _aslist(key)
        if len(keys) != 1:
            # list form: Trainer passes priority=0 and relies on the P3
            # contract of descending -index dispatch; an explicit caller
            # priority (scalar or per-key list) takes precedence.
            vals = value
            outs = out if out is not None else [None] * len(keys)
            if isinstance(priority, (list, tuple)):
                prios = list(priority)
                if len(prios) != len(keys):
                    raise ValueError(
                        f"priority list length {len(prios)} != {len(keys)}")
            elif priority:
                prios = [priority] * len(keys)
            else:
                prios = [-i for i in range(len(keys))]
            order = sorted(range(len(keys)), key=lambda i: -prios[i])
            for i in order:
                self.pushpull(keys[i], vals[i], outs[i], priority=prios[i])
            return
        vals = _aslist(value)
        size = int(vals[0].size)
        if size <= self._bound or len(vals) == 1:
            return super().pushpull(key, value, out, priority)
        t0 = time.perf_counter()
        # gradient compression applies before slicing, exactly as in the
        # delegated small-tensor path
        vals = self._compress_vals(str(keys[0]), vals)
        # chunked reduce: flatten, split, reduce each chunk independently
        n_chunks = -(-size // self._bound)
        dev = next(iter(vals[0]._data.devices()))
        flats = [jax.device_put(v._data, dev).reshape(-1) for v in vals]
        bounds = [min((c + 1) * self._bound, size)
                  for c in range(n_chunks)]
        starts = [0] + bounds[:-1]
        reduced = []
        addn = self._tree_sum(len(flats))
        for s, e in zip(starts, bounds):
            chunk = addn(*[f[s:e] for f in flats])
            if self.num_workers > 1:
                chunk = self._cross_process_sum(chunk)
            reduced.append(chunk)
        total = jnp.concatenate(reduced).reshape(vals[0].shape)
        _telemetry.record_collective(
            "pushpull",
            sum(_telemetry.nbytes_of(v._data) for v in vals),
            time.perf_counter() - t0)
        if out is None:
            return
        for o in _aslist(out):
            o._data = self._put_like(total, o._data)
            o._version += 1


KVStoreBase.register(P3Store)
KVStoreBase.kv_registry["p3"] = P3Store  # reference spelling
