"""Gradient compression: 1-bit / 2-bit quantization with error feedback.

Reference: src/kvstore/gradient_compression.{h,cc,cu} (CompressionType at
gradient_compression.h:37) — workers quantize gradients against a threshold
before pushing to the parameter server, keeping the quantization error in a
local residual that is added to the next gradient (error feedback), and the
receiving side dequantizes.

TPU re-design: one jitted pipeline per (shape, dtype) — residual add,
threshold quantize, bit-pack into uint8 lanes (4×2-bit or 8×1-bit per byte),
and the mirrored unpack+dequantize. The packed uint8 tensor is what crosses
the wire (DCN, across hosts); XLA fuses the whole pipeline into a few
elementwise kernels. Within one host/slice there is nothing to win — ICI
moves bf16 faster than quantization costs — matching the reference, which
also only compresses the worker→server hop.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["GradientCompression"]


@functools.partial(jax.jit, static_argnames=("threshold",))
def _compress_2bit(grad, residual, threshold):
    g = grad + residual
    q = jnp.where(g > threshold, jnp.int8(1),
                  jnp.where(g < -threshold, jnp.int8(-1), jnp.int8(0)))
    deq = q.astype(grad.dtype) * threshold
    new_residual = g - deq
    # pack 4 2-bit codes per uint8: map {-1,0,1} -> {2,0,1}
    codes = jnp.where(q < 0, jnp.uint8(2), q.astype(jnp.uint8))
    flat = codes.ravel()
    pad = (-flat.shape[0]) % 4
    flat = jnp.pad(flat, (0, pad))
    lanes = flat.reshape(-1, 4)
    packed = (lanes[:, 0] | (lanes[:, 1] << 2) | (lanes[:, 2] << 4)
              | (lanes[:, 3] << 6))
    return packed, new_residual


@functools.partial(jax.jit, static_argnames=("threshold", "shape", "dtype"))
def _decompress_2bit(packed, threshold, shape, dtype):
    lanes = jnp.stack([(packed >> s) & 3 for s in (0, 2, 4, 6)], axis=1)
    flat = lanes.ravel()
    n = 1
    for s in shape:
        n *= s
    codes = flat[:n].reshape(shape)
    q = jnp.where(codes == 2, -1, codes.astype(jnp.int8)).astype(dtype)
    return q * threshold


@functools.partial(jax.jit, static_argnames=("threshold",))
def _compress_1bit(grad, residual, threshold):
    # reference semantics (gradient_compression-inl.h quantize_1bit /
    # dequantize_1bit): split at `threshold`, dequantize to ±1
    g = grad + residual
    q = jnp.where(g > threshold, jnp.uint8(1), jnp.uint8(0))
    deq = jnp.where(q == 1, 1.0, -1.0).astype(grad.dtype)
    new_residual = g - deq
    flat = q.ravel()
    pad = (-flat.shape[0]) % 8
    flat = jnp.pad(flat, (0, pad))
    lanes = flat.reshape(-1, 8)
    packed = lanes[:, 0]
    for i in range(1, 8):
        packed = packed | (lanes[:, i] << i)
    return packed, new_residual


@functools.partial(jax.jit, static_argnames=("threshold", "shape", "dtype"))
def _decompress_1bit(packed, threshold, shape, dtype):
    lanes = jnp.stack([(packed >> i) & 1 for i in range(8)], axis=1)
    flat = lanes.ravel()
    n = 1
    for s in shape:
        n *= s
    bits = flat[:n].reshape(shape)
    del threshold  # 1-bit dequantizes to ±1 (reference dequantize_1bit)
    return jnp.where(bits == 1, 1.0, -1.0).astype(dtype)


class GradientCompression:
    """Stateful compressor: per-key error-feedback residuals.

    compress(key, grad) -> packed uint8 payload (1/16 or 1/32 the fp32
    bytes); decompress(key-agnostic) mirrors it. compress_pipeline() does
    quantize→dequantize in one step for stores that aggregate locally.
    """

    def __init__(self, type="2bit", threshold=0.5):  # noqa: A002
        if type not in ("1bit", "2bit"):
            raise ValueError(f"compression type {type!r} not in 1bit/2bit")
        self.type = type
        self.threshold = float(threshold)
        self._residuals = {}

    def get_compression_factor(self):
        return 16 if self.type == "2bit" else 32

    def compress(self, key, grad):
        """Quantize+pack `grad` (a jax array); updates the residual."""
        res = self._residuals.get(key)
        if res is None or res.shape != grad.shape:
            res = jnp.zeros_like(grad)
        fn = _compress_2bit if self.type == "2bit" else _compress_1bit
        packed, new_res = fn(grad, res, threshold=self.threshold)
        self._residuals[key] = new_res
        return packed

    def decompress(self, packed, shape, dtype):
        fn = _decompress_2bit if self.type == "2bit" else _decompress_1bit
        return fn(packed, threshold=self.threshold, shape=tuple(shape),
                  dtype=jnp.dtype(dtype).name)

    def compress_pipeline(self, key, grad):
        """quantize→dequantize in one call (local aggregation path)."""
        packed = self.compress(key, grad)
        return self.decompress(packed, grad.shape, grad.dtype)
