"""KVStoreBase plugin interface (reference: python/mxnet/kvstore/base.py).

External communication backends register with `@KVStoreBase.register` and
implement broadcast/pushpull (+ optional push/pull). `TestStore` mirrors the
reference's in-process fake backend used by test_kvstore_custom.py.
"""
from __future__ import annotations

__all__ = ["KVStoreBase", "TestStore"]


class KVStoreBase:
    """Abstract KVStore: broadcast + pushpull over string/int keys."""

    kv_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    @staticmethod
    def find(name):
        return KVStoreBase.kv_registry.get(name.lower())

    # -- required API ------------------------------------------------------
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def is_capable(self, capability):
        return False

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    OPTIMIZER = "optimizer"

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    # -- gradient compression (shared by local + tpu_dist stores) ----------
    _compression = None

    def set_gradient_compression(self, compression_params):
        """Enable 1-bit/2-bit gradient compression with error feedback
        (reference: KVStore::SetGradientCompression,
        src/kvstore/gradient_compression.cc)."""
        from .gradient_compression import GradientCompression

        params = dict(compression_params)
        self._compression = GradientCompression(
            type=params.pop("type", "2bit"), **params)

    def _compress_vals(self, key, vals):
        """Run each pushed value through quantize→dequantize with a
        per-(key, slot) residual; identity when compression is off."""
        if self._compression is None:
            return vals
        from ..ndarray.ndarray import NDArray

        return [NDArray(self._compression.compress_pipeline(
            f"{key}:{i}", v._data), v.device) for i, v in enumerate(vals)]

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError


@KVStoreBase.register
class TestStore(KVStoreBase):
    """Pure-python single-process store (reference: base.py:246 TestStore)."""

    def broadcast(self, key, value, out, priority=0):  # noqa: ARG002
        values = out if isinstance(out, (list, tuple)) else [out]
        for o in values:
            value.copyto(o)

    def pushpull(self, key, value, out=None, priority=0):  # noqa: ARG002
        values = value if isinstance(value, (list, tuple)) else [value]
        total = values[0]
        for v in values[1:]:
            total = total + v
        if out is None:
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            total.copyto(o)

    def is_capable(self, capability):
        return capability in ("optimizer",)
