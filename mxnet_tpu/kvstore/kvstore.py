"""Single-process KVStore (reference: src/kvstore/kvstore_local.h + comm.h).

'local'/'device' semantics: init/push/pull over keys; push aggregates the
per-device gradient copies (CommDevice reduce), pull broadcasts the stored
value to each requested device; an optimizer can be installed server-side
(update_on_kvstore=True path of Gluon Trainer).

On TPU the "devices" are PJRT devices on this host; the reduce is a jitted
add-tree executed wherever the values live — XLA handles the transfers over
ICI, replacing the reference's GPU p2p / PCIe staged reduce (comm.h:482).
"""
from __future__ import annotations

import pickle

from ..ndarray.ndarray import NDArray
from .base import KVStoreBase

__all__ = ["KVStore", "KVStoreLocal"]


def _aslist(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class KVStoreLocal(KVStoreBase):
    """In-process key-value store with aggregation."""

    def __init__(self, name="local"):
        self._name = name
        self._store = {}
        self._optimizer = None
        self._updater_states = {}

    @property
    def type(self):
        return self._name

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def is_capable(self, capability):
        return capability in ("optimizer",)

    # -- classic API -------------------------------------------------------
    def init(self, key, value):
        keys, values = _aslist(key), _aslist(value)
        for k, v in zip(keys, values):
            self._store[str(k)] = v.copy()

    def push(self, key, value, priority=0):  # noqa: ARG002
        keys = _aslist(key)
        if len(keys) == 1 and not isinstance(value, (list, tuple)):
            value = [value]
        if len(keys) == 1:
            grouped = {keys[0]: _aslist(value)}
        else:
            grouped = dict(zip(keys, (_aslist(v) for v in value)))
        for k, vals in grouped.items():
            k = str(k)
            agg = vals[0]
            for v in vals[1:]:
                agg = agg + v.as_in_ctx(agg.device)
            if self._optimizer is not None:
                w = self._store[k]
                if k not in self._updater_states:
                    self._updater_states[k] = self._optimizer.create_state(
                        _key_int(k), w)
                self._optimizer.update(_key_int(k), w, agg.as_in_ctx(w.device),
                                       self._updater_states[k])
            else:
                self._store[k] = self._store.get(k, 0) + agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # noqa: ARG002
        keys = _aslist(key)
        outs = _aslist(out) if len(keys) == 1 else out
        for k, o in zip(keys, [outs] if len(keys) == 1 else outs):
            stored = self._store[str(k)]
            for dest in _aslist(o):
                stored.copyto(dest)

    def pushpull(self, key, value, out=None, priority=0):
        """Aggregate value(s); optionally write the result to out(s)."""
        keys = _aslist(key)
        if len(keys) != 1:
            for i, k in enumerate(keys):
                self.pushpull(k, value[i], None if out is None else out[i],
                              priority)
            return
        vals = _aslist(value)
        agg = vals[0]
        for v in vals[1:]:
            agg = agg + v.as_in_ctx(agg.device)
        self._store[str(keys[0])] = agg
        if out is not None:
            for dest in _aslist(out):
                agg.copyto(dest)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    # -- server-side optimizer --------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = optimizer

    def save_optimizer_states(self, fname, dump_optimizer=False):
        states = {
            k: [s.asnumpy() if isinstance(s, NDArray) else s
                for s in _flatten_state(v)]
            for k, v in self._updater_states.items()
        }
        payload = {"states": states}
        if dump_optimizer:
            payload["optimizer"] = self._optimizer
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        if "optimizer" in payload:
            self._optimizer = payload["optimizer"]
        # states are re-materialized lazily on next update
        self._loaded_states = payload["states"]


def _key_int(k):
    try:
        return int(k)
    except ValueError:
        return hash(k) % (2 ** 31)


def _flatten_state(state):
    if state is None:
        return []
    if isinstance(state, NDArray):
        return [state]
    out = []
    for s in state:
        out.extend(_flatten_state(s))
    return out


KVStore = KVStoreLocal
