"""Single-process KVStore (reference: src/kvstore/kvstore_local.h + comm.h).

'local'/'device' semantics: init/push/pull over keys; push aggregates the
per-device gradient copies (CommDevice reduce), pull broadcasts the stored
value to each requested device; an optimizer can be installed server-side
(update_on_kvstore=True path of Gluon Trainer).

On TPU the "devices" are PJRT devices on this host; the reduce is a jitted
add-tree executed wherever the values live — XLA handles the transfers over
ICI, replacing the reference's GPU p2p / PCIe staged reduce (comm.h:482).
"""
from __future__ import annotations

import pickle

import jax.numpy as jnp
import numpy as _np

from ..ndarray import sparse as _sparse
from ..ndarray.ndarray import NDArray
from .base import KVStoreBase

__all__ = ["KVStore", "KVStoreLocal"]


def _aslist(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


class KVStoreLocal(KVStoreBase):
    """In-process key-value store with aggregation."""

    def __init__(self, name="local"):
        self._name = name
        self._store = {}
        self._optimizer = None
        self._updater = None

    @property
    def type(self):
        return self._name

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def is_capable(self, capability):
        return capability in ("optimizer",)

    # -- classic API -------------------------------------------------------
    def init(self, key, value):
        keys, values = _aslist(key), _aslist(value)
        for k, v in zip(keys, values):
            self._store[str(k)] = v.copy()

    def push(self, key, value, priority=0):  # noqa: ARG002
        keys = _aslist(key)
        if len(keys) == 1 and not isinstance(value, (list, tuple)):
            value = [value]
        if len(keys) == 1:
            grouped = {keys[0]: _aslist(value)}
        else:
            grouped = dict(zip(keys, (_aslist(v) for v in value)))
        for k, vals in grouped.items():
            k = str(k)
            if any(isinstance(v, _sparse.BaseSparseNDArray) for v in vals):
                self._push_sparse(k, vals)
                continue
            vals = self._compress_vals(k, vals)
            agg = vals[0]
            for v in vals[1:]:
                agg = agg + v.as_in_ctx(agg.device)
            if self._updater is not None:
                w = self._store[k]
                self._updater(_key_int(k), agg.as_in_ctx(w.device), w)
            else:
                self._store[k] = self._store.get(k, 0) + agg

    def _push_sparse(self, k, vals):
        """Aggregate row-sparse gradient pushes (reference: kvstore sparse
        push over kRowSparseStorage — only touched embedding rows move)."""
        agg = vals[0]
        for v in vals[1:]:
            agg = _sparse.add(agg, v)
        if self._updater is not None:
            w = self._store[k]
            grad = agg.todense() if isinstance(
                agg, _sparse.BaseSparseNDArray) else agg
            self._updater(_key_int(k), grad.as_in_ctx(w.device), w)
        else:
            stored = self._store.get(k)
            self._store[k] = agg if stored is None else _sparse.add(
                stored, agg)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):  # noqa: ARG002
        """Pull only the requested rows as a RowSparseNDArray
        (reference: KVStore::PullRowSparse). The gather stays on device —
        only the requested rows ever move."""
        if row_ids is None:
            raise ValueError("row_sparse_pull requires row_ids")
        stored = self._store[str(key)]
        ids = _np.unique(row_ids.asnumpy().astype("int64") if isinstance(
            row_ids, NDArray) else _np.asarray(row_ids))
        if isinstance(stored, _sparse.RowSparseNDArray):
            # match requested ids against stored indices host-side (both
            # small), then gather the data rows on device; missing ids → 0
            stored_idx = _np.asarray(stored.indices)
            order = _np.argsort(stored_idx)
            pos = _np.searchsorted(stored_idx[order], ids)
            pos = _np.clip(pos, 0, max(len(stored_idx) - 1, 0))
            found = stored_idx[order][pos] == ids if len(stored_idx) else \
                _np.zeros(len(ids), bool)
            gathered = stored.data[order[pos]] if len(stored_idx) else \
                jnp.zeros((len(ids),) + stored.data.shape[1:], stored.dtype)
            rows = jnp.where(
                jnp.asarray(found).reshape((-1,) + (1,) * (gathered.ndim - 1)),
                gathered, 0)
        elif isinstance(stored, _sparse.BaseSparseNDArray):
            rows = stored.todense()._data[ids]
        else:
            rows = stored._data[ids]
        rsp = _sparse.RowSparseNDArray(rows, ids, stored.shape, stored.dtype)
        if out is not None:
            for dest in _aslist(out):
                dest.data, dest.indices = rsp.data, rsp.indices
        return rsp

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # noqa: ARG002
        keys = _aslist(key)
        outs = _aslist(out) if len(keys) == 1 else out
        for k, o in zip(keys, [outs] if len(keys) == 1 else outs):
            stored = self._store[str(k)]
            for dest in _aslist(o):
                stored.copyto(dest)

    def pushpull(self, key, value, out=None, priority=0):
        """Aggregate value(s); optionally write the result to out(s)."""
        keys = _aslist(key)
        if len(keys) != 1:
            for i, k in enumerate(keys):
                self.pushpull(k, value[i], None if out is None else out[i],
                              priority)
            return
        vals = _aslist(value)
        if any(isinstance(v, _sparse.BaseSparseNDArray) for v in vals):
            agg = vals[0]
            for v in vals[1:]:
                agg = _sparse.add(agg, v)
            self._store[str(keys[0])] = agg
            if out is not None:
                dense = agg.todense() if isinstance(
                    agg, _sparse.BaseSparseNDArray) else agg
                for dest in _aslist(out):
                    dense.copyto(dest)
            return
        vals = self._compress_vals(str(keys[0]), vals)
        agg = vals[0]
        for v in vals[1:]:
            agg = agg + v.as_in_ctx(agg.device)
        self._store[str(keys[0])] = agg
        if out is not None:
            for dest in _aslist(out):
                agg.copyto(dest)

    def broadcast(self, key, value, out, priority=0):
        self.init(key, value)
        self.pull(key, out, priority)

    # -- server-side optimizer --------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater

        self._optimizer = optimizer
        # one per-key state/update path shared with the reference's
        # get_updater contract (multi-precision aware)
        self._updater = get_updater(optimizer)
        if getattr(self, "_loaded_states", None):
            # load_optimizer_states ran before set_optimizer
            self._consume_loaded_states()

    @property
    def _updater_states(self):
        return self._updater.states if self._updater is not None else {}

    def save_optimizer_states(self, fname, dump_optimizer=False):
        states = {
            k: [s.asnumpy() if isinstance(s, NDArray) else s
                for s in _flatten_state(v)]
            for k, v in self._updater_states.items()
        }
        payload = {"states": states}
        if dump_optimizer:
            payload["optimizer"] = self._optimizer
        with open(fname, "wb") as f:
            pickle.dump(payload, f)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            payload = pickle.load(f)
        if "optimizer" in payload:
            self._optimizer = payload["optimizer"]
        self._loaded_states = payload["states"]
        if self._updater is None:
            # updater not set yet: set_optimizer consumes _loaded_states
            return
        self._consume_loaded_states()

    def _consume_loaded_states(self):
        """Route loaded states into the updater (ADVICE r4 #1 — the
        payload used to be stored and never consulted). Keys whose state
        already exists are grafted NOW (structure known); unseen keys
        graft lazily on their first update."""
        from ..optimizer.optimizer import _graft_state

        loaded = self._loaded_states or {}
        for k, flat in loaded.items():
            hit = None
            for cand in (k, str(k)):
                if cand in self._updater.states:
                    hit = cand
                    break
            if hit is not None:
                self._updater.states[hit] = _graft_state(
                    self._updater.states[hit], list(flat))
            else:
                self._updater.pending_loaded[k] = flat
        self._loaded_states = None  # consumed: never re-applied to a
        #                             later set_optimizer's fresh updater


def _key_int(k):
    try:
        return int(k)
    except ValueError:
        return hash(k) % (2 ** 31)


def _flatten_state(state):
    if state is None:
        return []
    if isinstance(state, NDArray):
        return [state]
    out = []
    for s in state:
        out.extend(_flatten_state(s))
    return out


KVStore = KVStoreLocal
