"""BytePS kvstore adapter (reference: python/mxnet/kvstore/byteps.py:29).

Parity shim following the same pattern as the horovod adapter: delegates
to `byteps.mxnet` when importable, and points TPU users at `tpu_dist`
otherwise (byteps is a GPU/RDMA parameter-server system).
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["BytePS"]


@KVStoreBase.register
class BytePS(KVStoreBase):
    def __init__(self):
        # byteps.mxnet, like horovod.mxnet, moves MXNet C-handle arrays;
        # jax-backed tensors cannot cross that ABI, so construction
        # raises either way and kvstore.create() falls back to tpu_dist.
        try:
            import byteps.mxnet as bps  # noqa: PLC0415,F401
        except ImportError as e:
            raise ImportError(
                "kvstore='byteps' requires the byteps package; use "
                "kvstore='tpu_dist' — the XLA collective store with the "
                "same pushpull contract") from e
        raise ImportError(
            "byteps.mxnet drives MXNet C-handle arrays and has no "
            "jax/TPU backend; use kvstore='tpu_dist' (kvstore.create "
            "falls back automatically)")

    @property
    def rank(self):
        return self._bps.rank()

    @property
    def num_workers(self):
        return self._bps.size()

    def is_capable(self, capability):
        return capability in ("pushpull", "broadcast")

    def broadcast(self, key, value, out, priority=0):
        """Root rank's value lands in every rank's out — realised as the
        reference adapter does: non-root ranks zero their copy, then one
        push_pull sums to the root value (byteps.py:45-90)."""
        vals = value if isinstance(value, (list, tuple)) else [value]
        outs = out if isinstance(out, (list, tuple)) else [out]
        buf = vals[0]
        if self.rank != 0:
            buf = buf * 0
        self._bps.byteps_declare_tensor(str(key))
        self._bps.byteps_push_pull(buf, name=str(key), priority=priority)
        for o in outs:
            o._data = buf._data
            o._version += 1

    def pushpull(self, key, value, out=None, priority=0):
        vals = value if isinstance(value, (list, tuple)) else [value]
        local = vals[0]
        for v in vals[1:]:  # sum local copies like every other store
            local = local + v
        self._bps.byteps_push_pull(local, name=str(key),
                                   priority=priority)
        if out is None:
            return
        outs = out if isinstance(out, (list, tuple)) else [out]
        for o in outs:
            o._data = local._data
            o._version += 1
