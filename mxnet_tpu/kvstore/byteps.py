"""BytePS kvstore adapter (reference: python/mxnet/kvstore/byteps.py).

The reference adapter delegates broadcast/pushpull to `byteps.mxnet`, which
moves MXNet C-handle NDArrays. This framework's arrays are jax-backed
and cannot cross that ABI, and byteps has no TPU/jax backend — so the
adapter's construction always raises ImportError with the porting
guidance, and `kvstore.create('byteps')` falls back to `tpu_dist`,
whose pushpull honors the same KVStoreBase contract over XLA
collectives. The class stays registered so reference-era code that
probes `KVStoreBase.find('byteps')` keeps working.
"""
from __future__ import annotations

from .base import KVStoreBase

__all__ = ["BytePS"]


@KVStoreBase.register
class BytePS(KVStoreBase):
    def __init__(self):
        try:
            import byteps.mxnet  # noqa: PLC0415,F401
        except ImportError as e:
            raise ImportError(
                "kvstore='byteps' requires the byteps package; use "
                "kvstore='tpu_dist' — the XLA collective store with the "
                "same pushpull contract") from e
        raise ImportError(
            "byteps.mxnet drives MXNet C-handle arrays and has no jax/TPU "
            "backend; use kvstore='tpu_dist' (kvstore.create falls back "
            "automatically)")
