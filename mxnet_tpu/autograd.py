"""Imperative autograd: record/pause scopes, tape, backward.

Re-design of the reference's autograd (python/mxnet/autograd.py +
src/imperative/imperative.cc RecordOp/Backward + src/nnvm/gradient.cc) for a
functional backend. Instead of building an NNVM graph and running a symbolic
MXGradient pass, we record an eager tape: every op executed under `record()`
whose inputs require grad is run through `jax.vjp`, which both computes the
forward value and returns a pullback closure holding the residuals on device.
`backward()` is then a reverse-topological sweep calling the pullbacks — the
tape *is* the backward graph, with residual storage playing the role of the
reference's saved forward buffers.

grad_req semantics ('write'/'add'/'null') follow the reference
(python/mxnet/gluon/parameter.py, kAddTo in the C++ executor).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax
import numpy as _np

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "Function",
]

float0 = jax.dtypes.float0


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.suspended = 0  # >0 while tracing a CachedOp: per-op taping is off


_state = _State()


def is_recording():
    """True iff inside a `record()` scope (reference: autograd.is_recording)."""
    return _state.recording


def is_training():
    """True iff in train mode (reference: autograd.is_training)."""
    return _state.training


def set_recording(is_rec):
    prev = _state.recording
    _state.recording = bool(is_rec)
    return prev


def set_training(train):
    prev = _state.training
    _state.training = bool(train)
    return prev


@contextmanager
def _scope(recording=None, training=None):
    prev_r = _state.recording
    prev_t = _state.training
    if recording is not None:
        _state.recording = recording
    if training is not None:
        _state.training = training
    try:
        yield
    finally:
        _state.recording = prev_r
        _state.training = prev_t


def record(train_mode=True):  # noqa: ARG001 - name parity with reference
    """Scope in which executed ops are recorded for backward."""
    return _scope(recording=True, training=train_mode)


def pause(train_mode=False):
    """Scope in which recording (and by default training mode) is off."""
    return _scope(recording=False, training=train_mode)


def train_mode():
    """Scope forcing train-mode behavior (dropout active etc.)."""
    return _scope(training=True)


def predict_mode():
    """Scope forcing inference-mode behavior."""
    return _scope(training=False)


@contextmanager
def suspend_taping():
    """Internal: disable per-op taping (used while tracing a CachedOp —
    the traced subgraph becomes ONE tape node via jax.vjp on the jitted fn,
    the analog of CachedOp::Backward on the full subgraph)."""
    _state.suspended += 1
    try:
        yield
    finally:
        _state.suspended -= 1


def taping_active():
    return _state.recording and _state.suspended == 0


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op: a pullback + references to its input arrays.

    `inputs` are the NDArray objects passed to the op. For each we snapshot its
    tape entry at record time (mutation may later redirect the array), the
    analog of the reference capturing `autograd_entry_` per NDArray
    (include/mxnet/imperative.h AGInfo).
    """

    __slots__ = (
        "vjp_fn",
        "inputs",
        "input_entries",
        "out_avals",
        "multi_out",
        "name",
        "pure_fn",
        "input_datas",
        "retained",
    )

    def __init__(self, vjp_fn, inputs, input_entries, out_avals, multi_out,
                 name, pure_fn=None, input_datas=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs
        self.input_entries = input_entries
        self.out_avals = out_avals  # list of (shape, dtype)
        self.multi_out = multi_out
        self.name = name
        # for higher-order grad (create_graph): the pure jax function this
        # node executed plus snapshots of its array inputs, so the tape can
        # be replayed symbolically (jax arrays are immutable — these are
        # references, not copies)
        self.pure_fn = pure_fn
        self.input_datas = input_datas
        # (weakref(NDArray), out_idx) pairs registered by attach_grad on
        # an already-recorded array: backward lands the out-cotangent in
        # their .grad (reference retain-grad — test_autograd.py
        # test_retain_grad_drop_grad)
        self.retained = None


def _zero_cotangent(shape, dtype):
    import jax.numpy as jnp

    if _np.issubdtype(_np.dtype(dtype), _np.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool primal outputs take float0 cotangents
    return _np.zeros(shape, dtype=float0)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to arrays (reference: MXAutogradMarkVariables).

    After this, ops consuming `variables` under record() are taped and
    `backward()` writes into `gradients` according to grad_req.
    """
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, gradbuf, req in zip(variables, gradients, grad_reqs):
        var._grad = gradbuf
        var._grad_req = req
        var._tape_entry = None


def _collect_graph(head_entries):
    """Topological order of tape nodes reachable from the heads."""
    order = []
    seen = set()
    stack = [e[0] for e in head_entries if e is not None]
    # iterative DFS post-order
    work = [(n, False) for n in stack]
    while work:
        node, processed = work.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        work.append((node, True))
        for ent in node.input_entries:
            if ent is not None and id(ent[0]) not in seen:
                work.append((ent[0], False))
    return order  # already topological (producers before consumers)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):  # noqa: ARG001
    """Run backward from `heads`, landing gradients in marked variables.

    Matches reference semantics (src/imperative/imperative.cc:438 Backward):
    default head gradient is ones; grad_req 'write' overwrites, 'add'
    accumulates across backward calls.
    """
    import jax.numpy as jnp

    from .diagnostics import spans as _spans
    from .ndarray.ndarray import NDArray

    with _spans.span("backward", cat="bwd"):
        return _backward_impl(heads, head_grads, retain_graph, jnp, NDArray)


def _backward_impl(heads, head_grads, retain_graph, jnp, NDArray):
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulators: id(node) -> list per output
    cot = {}
    node_by_id = {}
    # gradients destined for marked variables: id(var) -> jax array
    var_grads = {}
    var_by_id = {}

    def _acc_var(var, g):
        if var._grad_req == "null" or var._grad is None:
            return
        key = id(var)
        var_by_id[key] = var
        if key in var_grads:
            var_grads[key] = var_grads[key] + g
        else:
            var_grads[key] = g

    head_entries = []
    for h, hg in zip(heads, head_grads):
        seed = hg._data if hg is not None else jnp.ones_like(h._data)
        entry = h._tape_entry
        head_entries.append(entry)
        if entry is None:
            if h._grad is not None:
                _acc_var(h, seed)
                continue
            raise ValueError(
                "one of the backward heads was not computed inside a "
                "record() scope and has no attached grad"
            )
        node, idx = entry
        node_by_id[id(node)] = node
        slots = cot.setdefault(id(node), [None] * len(node.out_avals))
        slots[idx] = seed if slots[idx] is None else slots[idx] + seed

    order = _collect_graph(head_entries)

    for node in reversed(order):
        slots = cot.pop(id(node), None)
        if slots is None:
            continue  # no cotangent reached this node
        full = []
        for s, (shape, dtype) in zip(slots, node.out_avals):
            full.append(s if s is not None else _zero_cotangent(shape, dtype))
        if node.retained:
            # retain-grad: land this node's output cotangents in the
            # .grad of arrays that attach_grad'd mid-graph
            for ref, ridx in node.retained:
                var = ref()
                if var is not None and var._grad is not None:
                    _acc_var(var, full[ridx])
        out_ct = tuple(full) if node.multi_out else full[0]
        if node.vjp_fn is None:
            if node.retained:
                # the arriving cotangents were landed into the retained
                # arrays above; the producer graph is consumed, so they
                # act as leaves — stop here instead of raising
                continue
            raise RuntimeError(
                "tape already freed; call backward(retain_graph=True) to "
                "backprop through the same graph twice"
            )
        in_cts = node.vjp_fn(out_ct)
        for var, ent, g in zip(node.inputs, node.input_entries, in_cts):
            if g is None or (hasattr(g, "dtype") and g.dtype == float0):
                continue
            if ent is not None:
                pnode, pidx = ent
                slots2 = cot.setdefault(id(pnode), [None] * len(pnode.out_avals))
                slots2[pidx] = g if slots2[pidx] is None else slots2[pidx] + g
            elif var is not None and var._grad is not None:
                _acc_var(var, g)
        if not retain_graph:
            node.vjp_fn = None  # free residuals eagerly

    # land gradients
    for key, g in var_grads.items():
        var = var_by_id[key]
        gradbuf = var._grad
        if var._grad_req == "add":
            gradbuf._data = gradbuf._data + g.astype(gradbuf._data.dtype)
        else:
            gradbuf._data = g.astype(gradbuf._data.dtype)
        gradbuf._version += 1


def _tape_function(heads, variables, promote_leaves=False):
    """Lift the recorded tape into a pure function var_datas -> head_datas.

    The functional analog of the reference building a backward NNVM graph
    (src/nnvm/gradient.cc): every reachable TapeNode is replayed through its
    stored pure_fn, with the requested `variables` promoted to function
    arguments and every other leaf bound to its recorded snapshot.

    promote_leaves=True additionally promotes every OTHER grad-requiring,
    un-mutated leaf to an argument (appended to `variables`; the extended
    list is returned) — the returned grads algebraically depend on those
    leaves (d/dx of xW depends on W), and baking them in as constants
    would silently zero mixed second derivatives. A leaf mutated since
    recording (its _data no longer IS the snapshot) keeps the snapshot
    binding — the recorded value is the differentiation point.

    Returns (replay, extended_variables, var_slots) where var_slots maps
    id(var) -> argument slot (first occurrence wins for duplicates).
    """
    variables = list(variables)
    var_ids = {}
    for k, v in enumerate(variables):
        var_ids.setdefault(id(v), k)  # duplicates share the first slot
    head_entries = [h._tape_entry for h in heads]
    for h, ent in zip(heads, head_entries):
        if ent is None and id(h) not in var_ids:
            raise ValueError("backward head was not recorded on the tape")
    order = _collect_graph(head_entries)
    for node in order:
        if node.pure_fn is None:
            raise NotImplementedError(
                f"create_graph=True cannot replay tape node '{node.name}' "
                "(custom Function / CachedOp nodes store no pure function); "
                "run the forward un-hybridized for higher-order grad")
    if promote_leaves:
        for node in order:
            for pos, (var, ent) in enumerate(
                    zip(node.inputs, node.input_entries)):
                if (ent is None and var is not None
                        and id(var) not in var_ids
                        and var._requires_grad_entry
                        and var._data is node.input_datas[pos]):
                    var_ids[id(var)] = len(variables)
                    variables.append(var)

    def replay(*var_datas):
        env = {}
        for node in order:
            args = []
            for pos, (var, ent) in enumerate(
                    zip(node.inputs, node.input_entries)):
                if ent is not None:
                    pn, pi = ent
                    args.append(env[id(pn)][pi])
                elif var is not None and id(var) in var_ids:
                    args.append(var_datas[var_ids[id(var)]])
                else:
                    args.append(node.input_datas[pos])
            out = node.pure_fn(*args)
            env[id(node)] = list(out) if node.multi_out else [out]
        res = []
        for h, ent in zip(heads, head_entries):
            if ent is None:
                res.append(var_datas[var_ids[id(h)]])
            else:
                n, i = ent
                res.append(env[id(n)][i])
        return tuple(res)

    return replay, variables, var_ids


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):  # noqa: ARG001
    """Return gradients of heads w.r.t. variables instead of writing .grad.

    Reference: python/mxnet/autograd.py:grad. With create_graph=True the
    gradient computation itself is recorded, so grads of grads work: the
    tape is replayed as a pure jax function and its jax.vjp runs through
    apply_op like any other op.
    """
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
    if create_graph:
        from .ndarray.ndarray import apply_op

        if isinstance(heads, NDArray):
            heads = [heads]
        # promote_leaves: see _tape_function — keeps mixed second
        # derivatives (WGAN-GP: grad wrt x, then backward into W) taped
        replay, extended, var_slots = _tape_function(
            heads, variables, promote_leaves=True)
        # duplicates in `variables` share one replay slot; map each
        # requested position back to its slot so every duplicate gets
        # the full gradient (matching the create_graph=False path)
        slot_of = [var_slots[id(v)] for v in variables]
        if head_grads is None:
            seeds = [h.ones_like() for h in heads]
        elif isinstance(head_grads, NDArray):
            seeds = [head_grads]
        else:
            # per-head None means ones_like, as backward() treats it
            seeds = [h.ones_like() if hg is None else hg
                     for h, hg in zip(heads, head_grads)]
        n_ext = len(extended)

        def pure_grads(*args):
            vd = args[:n_ext]
            sd = args[n_ext:]
            _, pull = jax.vjp(replay, *vd)
            all_grads = pull(tuple(sd))
            return tuple(all_grads[s] for s in slot_of)

        # create_graph FORCES recording (reference: the gradient pass is
        # itself recorded so dx.backward() works outside any record scope)
        with record(train_mode=train_mode):
            out = apply_op(pure_grads, *extended, *seeds, name="grad")
        return list(out) if isinstance(out, (tuple, list)) else [out]
    saved = [(v._grad, v._grad_req) for v in variables]
    zeros = []
    for v in variables:
        z = v.zeros_like() if hasattr(v, "zeros_like") else None
        zeros.append(z)
        v._grad = z
        v._grad_req = "write"
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph), train_mode=train_mode)
        out = [v._grad for v in variables]
    finally:
        for v, (g, r) in zip(variables, saved):
            v._grad, v._grad_req = g, r
    return out


class Function:
    """Custom differentiable function (reference: autograd.Function,
    python/mxnet/autograd.py:369).

    Subclass and implement `forward(self, *inputs)` and
    `backward(self, *output_grads)` in terms of NDArrays. Tensors needed by
    backward can be stashed with `save_for_backward`.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap_out

        with pause():
            outputs = self.forward(*inputs)
        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]

        if taping_active() and any(
            isinstance(i, NDArray) and i._requires_grad_entry for i in inputs
        ):
            nd_inputs = [i for i in inputs if isinstance(i, NDArray)]

            func = self

            def vjp_fn(out_ct):
                cts = out_ct if multi else (out_ct,)
                with pause():
                    grads = func.backward(*[_wrap_out(c) for c in cts])
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                it = iter(grads)
                result = []
                for i in inputs:
                    g = next(it)
                    if isinstance(i, NDArray):
                        result.append(None if g is None else g._data)
                return tuple(result)

            node = TapeNode(
                vjp_fn,
                nd_inputs,
                [i._tape_entry for i in nd_inputs],
                [(o.shape, o.dtype) for o in outs],
                multi_out=multi,
                name=type(self).__name__,
            )
            for idx, o in enumerate(outs):
                o._tape_entry = (node, idx)
        return outputs
