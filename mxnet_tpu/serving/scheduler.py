"""Priority-class request scheduler: strict-priority dequeue + per-class
token-bucket admission for the serving engine (ISSUE 15 tentpole).

The continuous-batching pipeline (engine.py) separates *what to serve
next* from *how to execute it*; this module owns the first half. It is
the TF-Serving batch-queue generalized to priority classes (PAPERS.md
"TensorFlow", §serving):

  * every request belongs to a :class:`ServeClass` — by default
    ``interactive`` (priority 0, served first) and ``batch`` (priority
    10, rides along in spare capacity);
  * dequeue is STRICT priority: the next micro-batch's head is always
    the oldest request of the highest-priority non-empty class, so an
    overload of batch-class work can never starve interactive traffic
    (the inverse — batch starvation under interactive overload — is the
    documented, intended behavior; cap it with a rate on the
    interactive class);
  * admission is layered: a per-class token bucket (``rate``/``burst``)
    sheds with :class:`~mxnet_tpu.serving.errors.RateLimited` BEFORE the
    shared queue bound sheds with
    :class:`~mxnet_tpu.serving.errors.Overloaded` — both deterministic
    and immediate, never a blocked client;
  * batch fill stays signature-safe: after the head is chosen, only
    same-signature requests coalesce, scanned in priority order, so a
    lower class can fill spare rows of a higher-class batch but never
    reorder its own FIFO;
  * everything is observable per class: ``serve_class_queue_depth``
    gauges and ``serve_class_shed_total{reason=queue|rate}`` counters.

Stdlib-only (threading + time); telemetry is the only framework import,
mirroring buckets.py's layering.
"""
from __future__ import annotations

import collections
import threading
import time

from ..telemetry import instruments as _instr
from .errors import Overloaded, RateLimited, RequestTimeout

__all__ = ["ServeClass", "TokenBucket", "RequestScheduler",
           "DEFAULT_CLASSES"]


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.

    ``try_take`` is called under the scheduler lock, so refill
    bookkeeping needs no lock of its own. ``rate=None`` means unlimited
    (every take succeeds).
    """

    __slots__ = ("rate", "burst", "_tokens", "_t_last")

    def __init__(self, rate=None, burst=None):
        self.rate = None if rate is None else float(rate)
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"rate must be > 0 or None, got {rate}")
        self.burst = float(burst if burst is not None
                           else max(1.0, self.rate or 1.0))
        self._tokens = self.burst
        self._t_last = time.monotonic()

    def try_take(self, n=1.0):
        if self.rate is None:
            return True
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t_last) * self.rate)
        self._t_last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


class ServeClass:
    """One priority class: name, strict-priority rank (lower serves
    first), and an optional token-bucket admission rate."""

    __slots__ = ("name", "priority", "rate", "burst")

    def __init__(self, name, priority=0, rate=None, burst=None):
        self.name = str(name)
        self.priority = int(priority)
        self.rate = rate
        self.burst = burst


#: The default two-class policy: interactive traffic strictly first,
#: batch-class work fills the slack. No rate limits — defaults shed only
#: at the shared queue bound, exactly like the single-class engine did.
DEFAULT_CLASSES = (ServeClass("interactive", priority=0),
                   ServeClass("batch", priority=10))


class _ClassQueue:
    __slots__ = ("cls", "queue", "bucket", "g_depth")

    def __init__(self, cls, model):
        self.cls = cls
        self.queue = collections.deque()
        self.bucket = TokenBucket(cls.rate, cls.burst)
        self.g_depth = _instr.serve_class_queue_depth.labels(model,
                                                            cls.name)


class RequestScheduler:
    """Strict-priority, signature-aware micro-batch scheduler.

    Owns the per-class FIFO queues, the shared admission bound, deadline
    expiry sweeps, and the condition variable the engine's assembler
    blocks on. The engine calls :meth:`offer` from client threads and
    :meth:`collect` from exactly one assembler thread.
    """

    def __init__(self, model, classes=None, max_queue=256):
        self.model = str(model)
        self.max_queue = int(max_queue)
        classes = tuple(classes) if classes else DEFAULT_CLASSES
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate class names in {names}")
        # stable sort: priority rank first, declaration order breaks ties
        self._classes = {
            c.name: _ClassQueue(c, self.model)
            for c in sorted(classes, key=lambda c: c.priority)}
        self.default_class = next(iter(self._classes))
        self.cond = threading.Condition()
        self._stopping = False
        self._forced = False

    # -- admission (client threads) ---------------------------------------
    def class_names(self):
        return list(self._classes)

    def offer(self, req):
        """Admit one request into its class queue, or shed.

        Sheds with :class:`RateLimited` when the class token bucket is
        empty, :class:`Overloaded` when the shared queue bound is hit —
        both recorded per class in ``serve_class_shed_total``. Never
        blocks."""
        cq = self._classes.get(req.cls)
        if cq is None:
            raise ValueError(
                f"unknown priority class {req.cls!r}; classes: "
                f"{list(self._classes)}")
        with self.cond:
            if not cq.bucket.try_take():
                self._record_shed(cq, "rate")
                raise RateLimited(
                    f"engine {self.model!r} class {req.cls!r} over its "
                    f"{cq.bucket.rate:g}/s admission rate; request shed")
            if self.depth_locked() >= self.max_queue:
                self._record_shed(cq, "queue")
                raise Overloaded(
                    f"engine {self.model!r} queue at bound "
                    f"{self.max_queue}; request shed")
            cq.queue.append(req)
            tr = getattr(req, "trace", None)
            if tr is not None:  # reqtrace: admission won — admit phase
                tr.stamp("admitted")  # closes, queue phase opens
            cq.g_depth.set(len(cq.queue))
            self._set_total_gauge()
            self.cond.notify_all()

    def _record_shed(self, cq, reason):
        _instr.record_serve_request(self.model, "shed")
        _instr.serve_class_shed_total.labels(
            self.model, cq.cls.name, reason).inc()

    # -- bookkeeping (call with self.cond held) ----------------------------
    def depth_locked(self):
        return sum(len(cq.queue) for cq in self._classes.values())

    def _set_total_gauge(self):
        _instr.serve_queue_depth.labels(self.model).set(self.depth_locked())

    def _expire_locked(self):
        """Drop finished (client-claimed) and past-deadline requests."""
        now = time.monotonic()
        changed = False
        for cq in self._classes.values():
            keep = collections.deque()
            for r in cq.queue:
                if r.done:
                    changed = True
                    continue  # client already claimed (timeout) — drop
                if r.deadline is not None and now >= r.deadline:
                    if r._finish("timeout", error=RequestTimeout(
                            "deadline elapsed while queued")):
                        _instr.record_serve_request(
                            self.model, "timeout", now - r.t_submit)
                    changed = True
                    continue
                keep.append(r)
            if len(keep) != len(cq.queue):
                cq.queue = keep
                cq.g_depth.set(len(keep))
        if changed:
            self._set_total_gauge()

    def _pop_head_locked(self):
        """Oldest request of the highest-priority non-empty class."""
        for cq in self._classes.values():
            if cq.queue:
                r = cq.queue.popleft()
                cq.g_depth.set(len(cq.queue))
                return r
        return None

    def _fill_locked(self, signature, room):
        """Same-signature requests that fit in ``room`` rows, scanned in
        priority order; per class only the head run is taken (never scan
        past a mismatched head — class FIFO order is preserved)."""
        taken = []
        for cq in self._classes.values():
            while room > 0 and cq.queue:
                nxt = cq.queue[0]
                if nxt.done or (nxt.deadline is not None
                                and time.monotonic() >= nxt.deadline):
                    self._expire_locked()
                    continue
                if nxt.signature != signature or nxt.rows > room:
                    break
                cq.queue.popleft()
                cq.g_depth.set(len(cq.queue))
                taken.append(nxt)
                room -= nxt.rows
        return taken

    # -- batching (the one assembler thread) -------------------------------
    def collect(self, max_rows, max_wait_s):
        """Block for the next micro-batch (list of requests, head first).

        Same contract as the PR-3 batcher's collect, generalized to
        classes: the head is strict-priority FIFO; the batch fills with
        same-signature requests until ``max_rows`` or until the head has
        waited ``max_wait_s`` since submit. Returns None when the
        scheduler is stopped and (drained, or force-stopped)."""
        with self.cond:
            while True:
                self._expire_locked()
                if self._forced:
                    return None
                head = self._pop_head_locked()
                if head is not None:
                    break
                if self._stopping:
                    return None
                self.cond.wait(0.05)
            batch, rows = [head], head.rows
            launch_at = head.t_submit + max_wait_s
            while rows < max_rows:
                taken = self._fill_locked(head.signature, max_rows - rows)
                if taken:
                    batch.extend(taken)
                    rows += sum(r.rows for r in taken)
                    continue
                if self._next_head_locked() is not None:
                    break  # head-of-line mismatch: launch now, batch next
                remaining = launch_at - time.monotonic()
                if remaining <= 0 or self._stopping or self._forced:
                    break
                self.cond.wait(min(remaining, 0.05))
            self._set_total_gauge()
        return batch

    def _next_head_locked(self):
        for cq in self._classes.values():
            if cq.queue:
                return cq.queue[0]
        return None

    # -- shutdown ----------------------------------------------------------
    def stop(self, force=False):
        """Stop: collect() returns None once drained (or immediately
        when ``force``); offer() admission is the engine's job."""
        with self.cond:
            self._stopping = True
            if force:
                self._forced = True
            self.cond.notify_all()

    def drain_all(self):
        """Pop every queued request (stop paths); returns them oldest
        first in priority order."""
        with self.cond:
            out = []
            for cq in self._classes.values():
                out.extend(cq.queue)
                cq.queue.clear()
                cq.g_depth.set(0)
            self._set_total_gauge()
        return out

    def latest_deadline(self):
        """The latest absolute deadline among queued requests — the
        moment past which draining is pointless (everything left will
        have expired). None when the queue is empty or any queued
        request is deadline-less."""
        with self.cond:
            deadlines = []
            for cq in self._classes.values():
                for r in cq.queue:
                    if r.deadline is None:
                        return None
                    deadlines.append(r.deadline)
        return max(deadlines) if deadlines else None

    # -- introspection -----------------------------------------------------
    def depth(self):
        with self.cond:
            return self.depth_locked()

    def depth_rows(self):
        with self.cond:
            return sum(r.rows for cq in self._classes.values()
                       for r in cq.queue)

    def at_bound(self):
        with self.cond:
            return self.depth_locked() >= self.max_queue

    def class_stats(self):
        """{class: {priority, depth, rate, shed_queue, shed_rate}}."""
        sheds = {
            (lv[1], lv[2]): c.value
            for lv, c in _instr.serve_class_shed_total.series()
            if lv[0] == self.model}
        with self.cond:
            return {
                name: {
                    "priority": cq.cls.priority,
                    "depth": len(cq.queue),
                    "rate": cq.bucket.rate,
                    "shed_queue": sheds.get((name, "queue"), 0),
                    "shed_rate": sheds.get((name, "rate"), 0),
                }
                for name, cq in self._classes.items()}
