"""Typed serving rejections (serving/engine.py admission + deadlines).

Deterministic failure is part of the serving contract: an overloaded
engine REJECTS with :class:`Overloaded` at submit time (TensorFlow
Serving's batch-queue bound — PAPERS.md "TensorFlow: A system for
large-scale machine learning", §serving), it never blocks the client or
deadlocks; a request that misses its deadline fails with
:class:`RequestTimeout`. Both subclass :class:`~mxnet_tpu.base.MXNetError`
so existing framework-error handling catches them.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["ServingError", "Overloaded", "RateLimited", "RequestTimeout",
           "EngineStopped"]


class ServingError(MXNetError):
    """Base class of every serving-engine rejection."""


class Overloaded(ServingError):
    """Admission control rejected the request: the bounded queue is at
    capacity. Clients should back off / retry against another replica —
    the engine sheds load instead of queueing unboundedly."""


class RateLimited(Overloaded):
    """The request's priority class is over its token-bucket admission
    rate (scheduler.py). Subclasses :class:`Overloaded` so existing
    shed handling catches it; catch this type to tell a policy rejection
    from a capacity one."""


class RequestTimeout(ServingError):
    """The request's deadline elapsed before a result was ready (still
    queued, or its batch had not finished)."""


class EngineStopped(ServingError):
    """The engine is stopped (or stopping) and accepts no new work."""
