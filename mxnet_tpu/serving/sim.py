"""Simulated slow device for serving-pipeline tests and benchmarks.

Proving that the pipelined engine (engine.py) overlaps host assembly
with device compute needs a device whose per-batch latency is KNOWN and
independent of host CPU contention. Real XLA-on-CPU can't provide that
on a small CI box: device "compute" and host assembly fight for the same
cores, so wall-clock deltas measure scheduler noise, not pipelining.
(And ``jax.pure_callback`` is no help — on the CPU backend it executes
synchronously at dispatch, which would serialize the very overlap under
test.)

:class:`SimulatedBlock` quacks exactly like a hybridized
``HybridBlock`` as far as the engine cares — ``call_cached_graph``,
``jit_trace_count``, ``aot_introspect`` — but its "device" is a single
daemon thread executing batches FIFO, each taking ``device_ms`` of
``time.sleep`` (GIL released, like a real device stream):

  * ``call_cached_graph`` ENQUEUES the batch and returns immediately —
    async dispatch, like JAX;
  * the returned outputs hold a :class:`_PendingResult` whose
    ``block_until_ready()`` blocks until the device thread finishes that
    batch — like a jax.Array;
  * one device thread + FIFO order = a serial compute stream: two
    batches in flight take ``2 * device_ms`` of device time but the
    SECOND batch's host assembly cost is hidden under the first's
    compute. That is the pipeline win, now measurable to sub-millisecond
    precision.

The block sets ``_host_native = True`` so the engine skips the
``jnp.asarray`` device transfer and feeds padded host numpy straight in.
Used by tests/test_serving_pipeline.py and ``tools/serve_bench.py
--block slow``.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as _np

__all__ = ["SimulatedBlock"]


class _PendingResult:
    """A future-ish array handle: shaped like the output, readable only
    after the simulated device finishes the batch (duck-types the slice
    of jax.Array surface the engine touches)."""

    __slots__ = ("_event", "_value", "shape", "dtype")

    def __init__(self, shape, dtype):
        self._event = threading.Event()
        self._value = None
        self.shape = tuple(shape)
        self.dtype = _np.dtype(dtype)

    def _set(self, value):
        self._value = value
        self._event.set()

    def block_until_ready(self):
        self._event.wait()
        return self

    def __getitem__(self, idx):
        if not self._event.is_set():
            raise RuntimeError(
                "simulated result sliced before block_until_ready() — "
                "the completer must wait before unpadding")
        return self._value[idx]

    def __array__(self, dtype=None):
        self.block_until_ready()
        return _np.asarray(self._value, dtype=dtype)


class _Out:
    """Engine-facing output wrapper: the engine reads ``._data`` off
    whatever call_cached_graph returns (NDArray protocol)."""

    __slots__ = ("_data",)

    def __init__(self, data):
        self._data = data


class SimulatedBlock:
    """A fake hybridized block whose forward costs ``device_ms`` on a
    serial device stream and ``host_ms`` of synchronous host time.

    ``fn`` maps the padded input batch (numpy) to the output batch;
    default is identity — convenient because padded-row leak checks can
    compare against the input directly. ``host_ms`` models a
    non-overlappable host cost inside dispatch (tokenization, feature
    lookup); it burns wall-clock in the CALLER's thread before the
    enqueue, so sync mode pays it serially while pipelined mode overlaps
    it with the previous batch's device time.
    """

    _host_native = True  # engine: skip jnp.asarray, feed host numpy

    def __init__(self, device_ms=20.0, host_ms=0.0, fn=None):
        self.device_ms = float(device_ms)
        self.host_ms = float(host_ms)
        self._fn = fn if fn is not None else lambda *a: a[0]
        self._q = queue.Queue()
        self._calls = 0
        self._done = 0
        self._busy_s = 0.0
        self._calls_lock = threading.Lock()
        self._device = threading.Thread(
            target=self._device_loop, name="mxtpu-sim-device", daemon=True)
        self._device.start()

    # -- the serial device stream -----------------------------------------
    def _device_loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            arrays, pending = item
            t0 = time.perf_counter()
            time.sleep(self.device_ms / 1e3)  # GIL released: "compute"
            out = self._fn(*arrays)
            pending._set(_np.asarray(out))
            with self._calls_lock:
                self._done += 1
                self._busy_s += time.perf_counter() - t0

    def close(self):
        self._q.put(None)

    # -- the HybridBlock surface the engine uses ---------------------------
    def call_cached_graph(self, *nds):
        """Async dispatch: enqueue on the device stream, return a
        pending handle immediately (JAX dispatch semantics)."""
        if self.host_ms:
            t_end = time.perf_counter() + self.host_ms / 1e3
            while time.perf_counter() < t_end:  # busy host work
                pass
        arrays = [_np.asarray(nd._data) for nd in nds]
        with self._calls_lock:
            self._calls += 1
        pending = _PendingResult(arrays[0].shape, arrays[0].dtype)
        self._q.put((arrays, pending))
        return _Out(pending)

    def jit_trace_count(self, training=False):
        """No XLA underneath: the 'compile cache' is trivially sealed."""
        return 0

    def aot_introspect(self, variant, *args, label=None):
        return {"variant": variant, "simulated": True}

    # -- introspection -----------------------------------------------------
    @property
    def dispatches(self):
        with self._calls_lock:
            return self._calls

    @property
    def batches_done(self):
        """Batches the device stream has finished (vs ``dispatches``
        enqueued — the gap is the in-flight window)."""
        with self._calls_lock:
            return self._done

    @property
    def busy_ms(self):
        """Total device-stream busy time — the ground truth a traced
        request's ``device`` phase spans are checked against."""
        with self._calls_lock:
            return self._busy_s * 1e3
