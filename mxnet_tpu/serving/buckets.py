"""Batch-bucket ladder: the serving engine's compile-shape vocabulary.

A TPU serves from a jit cache keyed by exact shapes — a stray batch size
on the hot path means an online XLA compile (seconds) in front of a
millisecond request. So the micro-batcher never launches a raw batch:
every batch is padded UP to the nearest rung of a fixed ladder
(1/2/4/.../max by default), all rungs are pre-compiled by
``InferenceEngine.warmup()``, and steady state touches only cached
executables. Doubling rungs bound the padding waste at <2x worst case
while keeping the compile count at O(log max_batch) — the bucketing
trade the TPU cost model motivates (PAPERS.md "A Learned Performance
Model for Tensor Processing Units").

Stdlib + numpy only: batch assembly is host-side; the single
device transfer happens in engine.py after padding.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["bucket_ladder", "pick_bucket", "pad_rows", "assemble_batch"]


def bucket_ladder(max_batch, buckets=None):
    """The sorted tuple of batch buckets to pre-compile.

    Default: powers of two up to ``max_batch``, with ``max_batch`` itself
    always the top rung (so a full batch never pads). An explicit
    ``buckets`` iterable is validated, deduplicated, sorted, and capped
    at ``max_batch``.
    """
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if buckets is None:
        ladder, b = [], 1
        while b < max_batch:
            ladder.append(b)
            b *= 2
        ladder.append(max_batch)
        return tuple(sorted(set(ladder)))
    ladder = sorted({int(b) for b in buckets})
    if not ladder or ladder[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets}")
    if ladder[-1] > max_batch:
        raise ValueError(
            f"bucket {ladder[-1]} exceeds max_batch {max_batch}")
    if ladder[-1] != max_batch:
        ladder.append(max_batch)
    return tuple(ladder)


def pick_bucket(ladder, rows):
    """Smallest rung >= rows, or None when rows exceeds the top rung
    (the batcher never assembles past the top; submit() rejects
    single requests that big)."""
    for b in ladder:
        if rows <= b:
            return b
    return None


def pad_rows(arr, bucket):
    """Pad a host batch up to ``bucket`` rows by repeating the last row.

    Repetition (not zeros) keeps padding inside the input distribution —
    zeros can NaN through normalization layers — and the pad rows are
    sliced off before any result leaves the engine, so their values are
    unobservable.
    """
    pad = int(bucket) - arr.shape[0]
    if pad < 0:
        raise ValueError(
            f"batch of {arr.shape[0]} rows does not fit bucket {bucket}")
    if pad == 0:
        return arr
    return _np.concatenate([arr, _np.repeat(arr[-1:], pad, axis=0)])


def assemble_batch(request_inputs, bucket):
    """Concatenate per-request host inputs and pad to ``bucket``.

    ``request_inputs`` is a list over requests, each a tuple of numpy
    arrays (one per model input, sharing the request's row count).
    Returns a list over model inputs of padded ``(bucket, ...)`` arrays.
    """
    n_inputs = len(request_inputs[0])
    return [
        pad_rows(_np.concatenate([r[j] for r in request_inputs]), bucket)
        for j in range(n_inputs)
    ]
