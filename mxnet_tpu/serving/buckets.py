"""Bucket ladders: the serving tier's compile-shape vocabulary.

A TPU serves from a jit cache keyed by exact shapes — a stray shape on
the hot path means an online XLA compile (seconds) in front of a
millisecond request. So nothing dispatches raw: every batch (and, since
the decode subsystem, every prompt) is padded UP to the nearest rung of
a fixed ladder (1/2/4/.../max by default), all rungs are pre-compiled
by warmup, and steady state touches only cached executables. Doubling
rungs bound the padding waste at <2x worst case while keeping the
compile count at O(log max) — the bucketing trade the TPU cost model
motivates (PAPERS.md "A Learned Performance Model for Tensor Processing
Units").

The ladder is AXIS-NAMED (ISSUE 18): the one-shot engine buckets batch
ROWS (axis="rows", the historical default — the axis-less calls below
are unchanged), while the decode engine's prefill buckets sequence
LENGTH (axis="seqlen"), where padding repeats along a time axis and the
KV-cache position mask hides the pad. Same ladder math, different
padding semantics: rows repeat the last ROW (pad must stay in the input
distribution — zeros can NaN through normalization), seqlen pads are
masked so zeros are fine and cheapest.

Stdlib + numpy only: assembly is host-side; the single device transfer
happens in the engines after padding.
"""
from __future__ import annotations

import numpy as _np

__all__ = ["bucket_ladder", "pick_bucket", "pad_rows", "pad_axis",
           "assemble_batch", "AXES"]

#: The named ladder axes. "rows" buckets batch rows (one-shot serving);
#: "seqlen" buckets sequence length (decode prefill).
AXES = ("rows", "seqlen")


def _check_axis(axis):
    if axis not in AXES:
        raise ValueError(f"axis must be one of {AXES}, got {axis!r}")
    return axis


def bucket_ladder(max_size, buckets=None, axis="rows"):
    """The sorted tuple of bucket rungs to pre-compile along ``axis``.

    Default: powers of two up to ``max_size``, with ``max_size`` itself
    always the top rung (so a full batch / max-length prompt never
    pads). An explicit ``buckets`` iterable is validated, deduplicated,
    sorted, and capped at ``max_size``. ``axis`` names what the rungs
    mean — ``"rows"`` (batch rows, the back-compat default) or
    ``"seqlen"`` (prompt length for decode prefill); the ladder math is
    axis-independent, the name is validated so call sites state intent.
    """
    _check_axis(axis)
    max_size = int(max_size)
    if max_size < 1:
        raise ValueError(f"max_{axis} must be >= 1, got {max_size}")
    if buckets is None:
        ladder, b = [], 1
        while b < max_size:
            ladder.append(b)
            b *= 2
        ladder.append(max_size)
        return tuple(sorted(set(ladder)))
    ladder = sorted({int(b) for b in buckets})
    if not ladder or ladder[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets}")
    if ladder[-1] > max_size:
        raise ValueError(
            f"bucket {ladder[-1]} exceeds max_{axis} {max_size}")
    if ladder[-1] != max_size:
        ladder.append(max_size)
    return tuple(ladder)


def pick_bucket(ladder, size):
    """Smallest rung >= size, or None when size exceeds the top rung
    (the batcher never assembles past the top; submit() rejects
    single requests that big)."""
    for b in ladder:
        if size <= b:
            return b
    return None


def pad_rows(arr, bucket):
    """Pad a host batch up to ``bucket`` rows by repeating the last row.

    Repetition (not zeros) keeps padding inside the input distribution —
    zeros can NaN through normalization layers — and the pad rows are
    sliced off before any result leaves the engine, so their values are
    unobservable.
    """
    return pad_axis(arr, bucket, axis=0, fill="repeat")


def pad_axis(arr, bucket, axis=0, fill="zero"):
    """Pad ``arr`` up to ``bucket`` along ``axis`` (an integer array
    dimension, not a ladder-axis name).

    ``fill="repeat"`` repeats the trailing slice (row-padding semantics:
    pad must stay in the input distribution); ``fill="zero"`` appends
    zeros (seqlen-padding semantics: the KV-cache position mask hides
    pad positions, so zeros are correct and cheapest).
    """
    arr = _np.asarray(arr)
    pad = int(bucket) - arr.shape[axis]
    if pad < 0:
        raise ValueError(
            f"size {arr.shape[axis]} along axis {axis} does not fit "
            f"bucket {bucket}")
    if pad == 0:
        return arr
    if fill == "repeat":
        idx = [slice(None)] * arr.ndim
        idx[axis] = slice(-1, None)
        return _np.concatenate(
            [arr, _np.repeat(arr[tuple(idx)], pad, axis=axis)], axis=axis)
    if fill != "zero":
        raise ValueError(f"fill must be 'repeat' or 'zero', got {fill!r}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return _np.pad(arr, widths)


def assemble_batch(request_inputs, bucket):
    """Concatenate per-request host inputs and pad to ``bucket`` rows.

    ``request_inputs`` is a list over requests, each a tuple of numpy
    arrays (one per model input, sharing the request's row count).
    Returns a list over model inputs of padded ``(bucket, ...)`` arrays.
    """
    n_inputs = len(request_inputs[0])
    return [
        pad_rows(_np.concatenate([r[j] for r in request_inputs]), bucket)
        for j in range(n_inputs)
    ]
