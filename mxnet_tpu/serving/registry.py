"""Multi-model registry: named InferenceEngines under one roof.

A process that serves several models (the TF-Serving "model server"
shape) needs one place to register, look up, and tear down engines —
and one call that snapshots every engine's stats for an ops endpoint.
Engines stay fully independent (own queue, own batcher thread, own
telemetry label series); the registry only owns the name -> engine map.

Replica sets (:meth:`ModelRegistry.register_replicas`) register N
engines of the same model as ``name/0`` .. ``name/N-1`` — each replica
is an ordinary registry entry, so the ops server's ``/readyz``
(observability/opsd.py) health-checks every replica individually — and
return a :class:`~mxnet_tpu.serving.frontdoor.FrontDoor` routing across
them, retrievable later with :meth:`ModelRegistry.frontdoor`.
"""
from __future__ import annotations

import threading

from .engine import InferenceEngine
from .frontdoor import FrontDoor

__all__ = ["ModelRegistry", "REGISTRY"]


class ModelRegistry:
    """Thread-safe name -> :class:`InferenceEngine` map."""

    def __init__(self):
        self._lock = threading.Lock()
        self._engines = {}
        self._frontdoors = {}

    @staticmethod
    def _is_engine(obj):
        """A ready engine, duck-typed on the serving surface the
        registry and opsd consume (submit / lifecycle / health / stats)
        — so decode.DecodeEngine (and any future engine kind) registers
        exactly like InferenceEngine without importing it here."""
        if isinstance(obj, InferenceEngine):
            return True
        return all(hasattr(obj, a) for a in
                   ("submit", "start", "stop", "admission_state",
                    "stats", "load"))

    def register(self, name, block_or_engine, start=True, **engine_kwargs):
        """Register a model and return its engine.

        ``block_or_engine`` is either a ready engine — an
        :class:`InferenceEngine`, a
        :class:`~mxnet_tpu.decode.engine.DecodeEngine`, or anything
        exposing the same serving surface — adopted as-is
        (``engine_kwargs`` must be empty), or a hybridized block wrapped
        in a new :class:`InferenceEngine` built with ``engine_kwargs``.
        Duplicate names raise ValueError — replacing a live model is an
        explicit unregister + register, never a silent swap.
        """
        name = str(name)
        if self._is_engine(block_or_engine):
            if engine_kwargs:
                raise ValueError(
                    "engine_kwargs only apply when registering a block, "
                    f"got a ready engine plus {sorted(engine_kwargs)}")
            engine = block_or_engine
        else:
            engine = InferenceEngine(block_or_engine, name=name,
                                     **engine_kwargs)
        with self._lock:
            if name in self._engines:
                raise ValueError(f"model {name!r} already registered")
            self._engines[name] = engine
        if start and not engine.started:
            engine.start()
        return engine

    def register_replicas(self, name, engines, start=True,
                          health_check=None):
        """Register a replica set and return its :class:`FrontDoor`.

        ``engines`` is a list of ready :class:`InferenceEngine` replicas
        of the same model signature. Each is registered individually
        under ``name/i`` — so ``stats()`` and the ops server's
        ``/readyz`` see every replica — and the front door routing
        across them is stored under ``name`` (:meth:`frontdoor` fetches
        it). Give replicas distinct engine names at construction time
        (e.g. ``m/0``, ``m/1``) so their telemetry label series don't
        collide.
        """
        name = str(name)
        engines = list(engines)
        if not engines:
            raise ValueError("register_replicas needs at least one engine")
        with self._lock:
            if name in self._frontdoors:
                raise ValueError(
                    f"replica set {name!r} already registered")
        for i, eng in enumerate(engines):
            self.register(f"{name}/{i}", eng, start=start)
        fd = FrontDoor(engines, name=name, health_check=health_check)
        with self._lock:
            self._frontdoors[name] = fd
        return fd

    def frontdoor(self, name):
        """The :class:`FrontDoor` of a registered replica set."""
        with self._lock:
            try:
                return self._frontdoors[name]
            except KeyError:
                raise KeyError(
                    f"no replica set {name!r}; registered: "
                    f"{sorted(self._frontdoors)}") from None

    def unregister_replicas(self, name, stop=True):
        """Remove a replica set: drops the front door and unregisters
        (by default stopping) every ``name/i`` replica."""
        with self._lock:
            fd = self._frontdoors.pop(name, None)
        if fd is None:
            raise KeyError(f"no replica set {name!r}")
        for eng in fd.engines:
            self.unregister(eng.name, stop=stop)
        return fd

    def get(self, name):
        with self._lock:
            try:
                return self._engines[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r}; registered: "
                    f"{sorted(self._engines)}") from None

    def __contains__(self, name):
        with self._lock:
            return name in self._engines

    def names(self):
        with self._lock:
            return sorted(self._engines)

    def unregister(self, name, stop=True):
        """Remove a model; by default also stop (drain) its engine."""
        with self._lock:
            engine = self._engines.pop(name, None)
        if engine is None:
            raise KeyError(f"no model {name!r}")
        if stop:
            engine.stop()
        return engine

    def stats(self):
        """{name: engine.stats()} for every registered model."""
        with self._lock:
            engines = dict(self._engines)
        return {n: e.stats() for n, e in sorted(engines.items())}

    def slo_status(self):
        """{name: per-class SLO table} for every registered model that
        has a declared objective and observed traffic — the registry
        slice of ``reqtrace.slo_status()`` (opsd's ``/readyz`` reads the
        full process-wide table; this is the per-registry view)."""
        with self._lock:
            names = sorted(self._engines)
        try:
            from ..observability import reqtrace
        except Exception:
            return {}
        table = reqtrace.slo_status()
        return {n: table[n] for n in names if n in table}

    def stop_all(self):
        """Unregister and drain every engine (process shutdown hook)."""
        with self._lock:
            engines, self._engines = dict(self._engines), {}
            self._frontdoors = {}
        for e in engines.values():
            e.stop()


# The process-wide default registry (mirrors telemetry.REGISTRY /
# diagnostics' module-level registries).
REGISTRY = ModelRegistry()
