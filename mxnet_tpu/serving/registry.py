"""Multi-model registry: named InferenceEngines under one roof.

A process that serves several models (the TF-Serving "model server"
shape) needs one place to register, look up, and tear down engines —
and one call that snapshots every engine's stats for an ops endpoint.
Engines stay fully independent (own queue, own batcher thread, own
telemetry label series); the registry only owns the name -> engine map.
"""
from __future__ import annotations

import threading

from .engine import InferenceEngine

__all__ = ["ModelRegistry", "REGISTRY"]


class ModelRegistry:
    """Thread-safe name -> :class:`InferenceEngine` map."""

    def __init__(self):
        self._lock = threading.Lock()
        self._engines = {}

    def register(self, name, block_or_engine, start=True, **engine_kwargs):
        """Register a model and return its engine.

        ``block_or_engine`` is either a ready :class:`InferenceEngine`
        (adopted as-is; ``engine_kwargs`` must be empty) or a hybridized
        block wrapped in a new engine built with ``engine_kwargs``.
        Duplicate names raise ValueError — replacing a live model is an
        explicit unregister + register, never a silent swap.
        """
        name = str(name)
        if isinstance(block_or_engine, InferenceEngine):
            if engine_kwargs:
                raise ValueError(
                    "engine_kwargs only apply when registering a block, "
                    f"got a ready engine plus {sorted(engine_kwargs)}")
            engine = block_or_engine
        else:
            engine = InferenceEngine(block_or_engine, name=name,
                                     **engine_kwargs)
        with self._lock:
            if name in self._engines:
                raise ValueError(f"model {name!r} already registered")
            self._engines[name] = engine
        if start and not engine.started:
            engine.start()
        return engine

    def get(self, name):
        with self._lock:
            try:
                return self._engines[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r}; registered: "
                    f"{sorted(self._engines)}") from None

    def __contains__(self, name):
        with self._lock:
            return name in self._engines

    def names(self):
        with self._lock:
            return sorted(self._engines)

    def unregister(self, name, stop=True):
        """Remove a model; by default also stop (drain) its engine."""
        with self._lock:
            engine = self._engines.pop(name, None)
        if engine is None:
            raise KeyError(f"no model {name!r}")
        if stop:
            engine.stop()
        return engine

    def stats(self):
        """{name: engine.stats()} for every registered model."""
        with self._lock:
            engines = dict(self._engines)
        return {n: e.stats() for n, e in sorted(engines.items())}

    def stop_all(self):
        """Unregister and drain every engine (process shutdown hook)."""
        with self._lock:
            engines, self._engines = dict(self._engines), {}
        for e in engines.values():
            e.stop()


# The process-wide default registry (mirrors telemetry.REGISTRY /
# diagnostics' module-level registries).
REGISTRY = ModelRegistry()
