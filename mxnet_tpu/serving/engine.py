"""Continuous-batching inference engine over a hybridized block.

The serving hot path, rebuilt as a PIPELINE (ISSUE 15 tentpole; design
anchors: TF-Serving's request batching — PAPERS.md "TensorFlow" §serving
— and the bucketed compile cache per "A Learned Performance Model for
Tensor Processing Units"). The PR-3 engine was a synchronous
micro-batcher: one thread assembled a batch, dispatched it, and settled
it before touching the next — so the device idled through every
host-side pad/assemble/unpack window. Now the batcher is split in two,
mirroring what the dataloader's ``device_prefetch`` does for training:

  * the **assembler** thread pops the next micro-batch from the
    priority scheduler (scheduler.py), pads it to a bucket rung on the
    host, and ISSUES the dispatch — JAX dispatch is async, so the call
    returns while the device is still computing, and the assembler
    immediately starts coalescing + padding the NEXT batch;
  * dispatched-but-unsettled batches sit in a bounded in-flight window
    (``max_inflight``, default 2 = double buffering): the assembler runs
    at most that many batches ahead, which is the backpressure that
    keeps dispatch-ahead from turning into unbounded device queueing;
  * the **completer** thread blocks on the OLDEST in-flight batch's
    results, slices each request's rows off, and settles the futures —
    a request is "done" only when its output buffers actually exist
    (the PR-3 engine settled with lazy arrays, deferring device wait to
    whichever client touched the result first).

Requests arriving while a dispatch is in flight join the batch the
assembler is building RIGHT NOW (in-flight joining) — their wait to
dispatch is bounded by one assembly, not a full round trip. On top of
the pipeline ride the scheduler's priority classes + per-class token
buckets, the deadline-aware bounded drain in :meth:`stop`, and the
replica front door (frontdoor.py).

``mode="sync"`` keeps the serialized PR-3 loop (collect → assemble →
dispatch → block → settle on one thread) for A/B measurement —
``tools/serve_bench.py --engine sync`` is the baseline the pipeline's
speedup is quoted against.

Everything else is unchanged contract: bucket-ladder padding so steady
state never sees an online XLA compile, ``warmup()`` with the
zero-retrace proof, bounded-queue admission with typed ``Overloaded``
shedding, per-request deadlines, ``serve_*`` telemetry. Defaults come
from the typed env registry: MXTPU_SERVE_MAX_BATCH, MXTPU_SERVE_QUEUE,
MXTPU_SERVE_MAX_WAIT_MS, MXTPU_SERVE_TIMEOUT_MS, MXTPU_SERVE_MODE,
MXTPU_SERVE_INFLIGHT, MXTPU_SERVE_DRAIN_MS. See docs/serving.md.
"""
from __future__ import annotations

import collections
import threading
import time

import jax.numpy as jnp
import numpy as _np

from .. import env as _env
from ..diagnostics import spans as _spans
from ..ndarray.ndarray import NDArray
from ..telemetry import instruments as _instr
from .buckets import assemble_batch, bucket_ladder, pad_rows, pick_bucket
from .errors import EngineStopped, Overloaded, RequestTimeout
from .scheduler import RequestScheduler

__all__ = ["InferenceEngine", "ServeRequest", "warm_and_seal"]

_REQTRACE = [None]


def _reqtrace():
    """Lazy, cached handle on observability.reqtrace (imported at first
    use, not at module import — serving loads before observability in
    the package graph)."""
    rt = _REQTRACE[0]
    if rt is None:
        from ..observability import reqtrace as rt

        _REQTRACE[0] = rt
    return rt


def _to_host(a):
    """Request input -> host numpy (one device transfer per BATCH, not
    per request, so assembly stays on the host)."""
    if isinstance(a, NDArray):
        return a.asnumpy()
    return _np.asarray(a)


def _wait_ready(datas):
    """Block until every output buffer exists. Duck-typed so simulated
    devices (sim.py) and jax arrays both work; plain numpy is a no-op."""
    for d in datas:
        ready = getattr(d, "block_until_ready", None)
        if ready is not None:
            ready()


def warm_and_seal(drive, rungs, trace_count, label="buckets"):
    """Warm a shape vocabulary and PROVE the jit cache sealed.

    Drives every rung once (compiling whatever misses), snapshots the
    caller's trace counter, drives every rung AGAIN, and raises if the
    counter moved — a moving counter means some served shape still
    misses the jit cache and would compile online on the hot path.
    Shared by :meth:`InferenceEngine.warmup` (row buckets) and
    ``decode.DecodeEngine.warmup`` (prefill seq-len rungs + the decode
    step), so every engine's zero-retrace proof is the same code path.
    Returns the post-warm trace count (the ``recompiles_since_warmup``
    baseline).
    """
    rungs = list(rungs)
    for r in rungs:
        drive(r)
    before = trace_count()
    for r in rungs:  # re-drive: everything must cache-hit now
        drive(r)
    added = trace_count() - before
    if added:
        raise RuntimeError(
            f"warmup failed to seal the jit cache: {added} "
            f"recompile(s) re-driving {label} {rungs} — served shapes "
            "would compile online")
    return before


class ServeRequest:
    """One in-flight request: inputs, class, deadline, and a settable
    outcome.

    The outcome transition is atomic (first of {completer result, batch
    error, timeout, shed} wins), so the client and the engine can race
    on a deadline without double-counting or half-set results.
    """

    __slots__ = ("inputs", "rows", "signature", "cls", "t_submit",
                 "t_dispatch", "deadline", "_event", "_lock", "outcome",
                 "_result", "_error", "model", "trace")

    def __init__(self, inputs, rows, signature, deadline, cls="interactive"):
        self.inputs = inputs
        self.rows = rows
        self.signature = signature
        self.cls = cls
        self.t_submit = time.monotonic()
        self.t_dispatch = None  # stamped when the batch is issued
        self.deadline = deadline  # absolute monotonic seconds, or None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.outcome = None  # ok | timeout | error | shed (claimed once)
        self._result = None
        self._error = None
        self.model = ""    # owning engine name (SLO attribution)
        self.trace = None  # reqtrace.ReqTrace when sampled, else None

    def _finish(self, outcome, result=None, error=None):
        """Claim the outcome; True iff this call won the claim.

        Every settled request — served, timed out, errored, or shed —
        funnels through here, so this is also the reqtrace/SLO terminal
        chokepoint: the trace (when sampled) freezes into the ring with
        its terminal span, and the latency feeds the class SLO window."""
        with self._lock:
            if self.outcome is not None:
                return False
            self.outcome = outcome
            self._result = result
            self._error = error
        self._event.set()
        try:
            _reqtrace().finish(self, outcome, error)
        except Exception:
            pass
        return True

    @property
    def done(self):
        return self.outcome is not None

    def result(self, timeout=None):
        """Block until the outcome; return the model output (NDArray, or
        a tuple for multi-output models) or raise the typed failure.

        ``timeout`` (seconds) overrides the request deadline for this
        wait; by default the wait extends to the deadline (forever when
        the request has none).
        """
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        self._event.wait(timeout)
        if not self.done:
            # nothing finished us in time — claim the timeout ourselves
            # (the engine skips claimed requests when it reaches them)
            self._finish("timeout",
                         error=RequestTimeout(
                             f"request not served within "
                             f"{timeout if timeout is not None else 0:.3f}s"))
        if self.outcome == "ok":
            return self._result
        raise self._error


class _Flight:
    """One dispatched-but-unsettled micro-batch in the pipeline window."""

    __slots__ = ("batch", "datas", "rows", "bucket", "t_dispatch",
                 "batch_id", "traced")

    def __init__(self, batch, datas, rows, bucket, batch_id=None,
                 traced=()):
        self.batch = batch
        self.datas = datas
        self.rows = rows
        self.bucket = bucket
        self.t_dispatch = time.monotonic()
        self.batch_id = batch_id  # reqtrace causality id (None unsampled)
        self.traced = traced      # member ReqTraces sharing batch stamps


class InferenceEngine:
    """Thread-safe continuous-batching server around one hybridized
    block.

    ::

        net = ...HybridBlock...; net.initialize(); net.hybridize()
        eng = serving.InferenceEngine(net, name="resnet", max_batch_size=16)
        eng.warmup(mx.np.zeros((1, 224, 224, 3)))   # compile every bucket
        eng.start()
        out = eng.predict(x)                        # from any thread
        eng.stop()

    Lifecycle: construct -> (optional) warmup -> start -> serve -> stop.
    ``submit()`` works before ``start()`` (requests queue; admission
    control still applies) — convenient for tests and staged bring-up.
    """

    def __init__(self, block, name="model", max_batch_size=None,
                 max_queue=None, max_wait_ms=None, timeout_ms=None,
                 buckets=None, mode=None, max_inflight=None,
                 classes=None, drain_timeout_ms=None):
        if not hasattr(block, "call_cached_graph"):
            raise TypeError(
                f"InferenceEngine needs a HybridBlock, got {type(block)}")
        self._block = block
        self.name = str(name)
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else _env.get("MXTPU_SERVE_MAX_BATCH"))
        self.max_queue = int(
            max_queue if max_queue is not None
            else _env.get("MXTPU_SERVE_QUEUE"))
        self.max_wait_s = float(
            max_wait_ms if max_wait_ms is not None
            else _env.get("MXTPU_SERVE_MAX_WAIT_MS")) / 1e3
        self.timeout_s = float(
            timeout_ms if timeout_ms is not None
            else _env.get("MXTPU_SERVE_TIMEOUT_MS")) / 1e3
        self.drain_timeout_s = float(
            drain_timeout_ms if drain_timeout_ms is not None
            else _env.get("MXTPU_SERVE_DRAIN_MS")) / 1e3
        self.mode = str(mode if mode is not None
                        else _env.get("MXTPU_SERVE_MODE")).lower()
        if self.mode not in ("pipelined", "sync"):
            raise ValueError(
                f"mode must be 'pipelined' or 'sync', got {self.mode!r}")
        self.max_inflight = max(1, int(
            max_inflight if max_inflight is not None
            else _env.get("MXTPU_SERVE_INFLIGHT")))
        self.buckets = bucket_ladder(self.max_batch_size, buckets)
        self._sched = RequestScheduler(self.name, classes=classes,
                                       max_queue=self.max_queue)
        self._lifecycle = threading.Lock()
        self._stopping = False
        self._force = False  # force-stop: window bound lifted, queue dropped
        self._threads = ()
        self._warm_traces = None
        # the pipeline window: dispatched-but-unsettled _Flights, bounded
        # at max_inflight (the assembler waits on _icond for a free slot)
        self._icond = threading.Condition()
        self._inflight = collections.deque()
        self._inflight_rows = 0
        self._max_inflight_seen = 0
        self._drained = threading.Event()  # set each time pipeline empties
        # cached label children: the hot path mutates gauges without
        # re-resolving labels (each child still honors enable/disable)
        self._g_inflight = _instr.serve_in_flight.labels(self.name)
        self._g_inflight_batches = _instr.serve_inflight_batches.labels(
            self.name)
        self._c_dispatch = _instr.serve_dispatch_total.labels(self.name)

    # -- lifecycle ---------------------------------------------------------
    @property
    def started(self):
        return any(t.is_alive() for t in self._threads)

    def start(self):
        """Start the pipeline threads (idempotent)."""
        with self._lifecycle:
            if self._stopping:
                raise EngineStopped(f"engine {self.name!r} was stopped")
            if not self.started:
                if self.mode == "sync":
                    self._threads = (threading.Thread(
                        target=self._loop_sync,
                        name=f"mxtpu-serve-{self.name}", daemon=True),)
                else:
                    self._threads = (
                        threading.Thread(
                            target=self._loop_assembler,
                            name=f"mxtpu-serve-{self.name}-asm",
                            daemon=True),
                        threading.Thread(
                            target=self._loop_completer,
                            name=f"mxtpu-serve-{self.name}-cpl",
                            daemon=True),
                    )
                for t in self._threads:
                    t.start()
        try:
            from ..observability import flight as _flight

            _flight.record("serve_start", model=self.name, mode=self.mode)
        except Exception:
            pass
        return self

    def stop(self, drain=True, drain_timeout_ms=None):
        """Stop accepting work; by default drain queued requests first.

        The drain is DEADLINE-AWARE and bounded: it never blocks past
        ``drain_timeout_ms`` (default MXTPU_SERVE_DRAIN_MS), nor past
        the latest deadline among queued requests (after which everything
        left would have expired anyway). Requests still queued when the
        drain deadline hits are force-dropped with
        :class:`EngineStopped` and counted in
        ``serve_drain_dropped_total``. With ``drain=False`` pending
        requests fail immediately.
        """
        with self._lifecycle:
            first = not self._stopping
            self._stopping = True
        self._sched.stop()
        dropped = []
        if not drain:
            self._sched.stop(force=True)
            self._force = True
            with self._icond:
                self._icond.notify_all()
            dropped = self._sched.drain_all()
            for r in dropped:
                if r._finish("error",
                             error=EngineStopped(
                                 f"engine {self.name!r} stopped")):
                    _instr.record_serve_request(self.name, "error")
        elif not self.started:
            # never started (or already exited): nothing will ever serve
            # the queue — dropping now IS the bounded drain
            self._force_drop()
        else:
            timeout_s = (float(drain_timeout_ms) / 1e3
                         if drain_timeout_ms is not None
                         else self.drain_timeout_s)
            deadline = time.monotonic() + timeout_s
            latest = self._sched.latest_deadline()
            if latest is not None:
                deadline = min(deadline, latest)
            for t in self._threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            if any(t.is_alive() for t in self._threads):
                # drain deadline hit: force the scheduler empty and give
                # the pipeline a moment to settle what it already
                # dispatched (device work in flight completes on its own)
                self._sched.stop(force=True)
                self._force = True
                self._force_drop()
                with self._icond:
                    self._icond.notify_all()
                for t in self._threads:
                    t.join(timeout=2.0)
        self._fail_unsettled_inflight()
        if first:
            try:
                from ..observability import flight as _flight

                _flight.record("serve_stop", model=self.name,
                               drained=bool(drain),
                               dropped=len(dropped))
            except Exception:
                pass
        return self

    def _force_drop(self):
        """Drop every queued request unserved (bounded-drain expiry)."""
        dropped = self._sched.drain_all()
        now = time.monotonic()
        for r in dropped:
            if r._finish("error",
                         error=EngineStopped(
                             f"engine {self.name!r} drain deadline hit; "
                             "request dropped unserved")):
                _instr.record_serve_request(self.name, "error",
                                            now - r.t_submit)
        if dropped:
            _instr.serve_drain_dropped_total.labels(self.name).inc(
                len(dropped))

    def _fail_unsettled_inflight(self):
        """Fail any dispatched-but-unsettled requests after the pipeline
        threads are gone (stop-path stragglers)."""
        if any(t.is_alive() for t in self._threads):
            return
        with self._icond:
            flights, self._inflight = list(self._inflight), \
                collections.deque()
            self._inflight_rows = 0
        stragglers = 0
        for fl in flights:
            if fl is None:
                continue
            for r in fl.batch:
                if r._finish("error", error=EngineStopped(
                        f"engine {self.name!r} stopped before the "
                        "dispatched batch settled")):
                    _instr.record_serve_request(self.name, "error")
                    stragglers += 1
        if stragglers:
            _instr.serve_drain_dropped_total.labels(self.name).inc(
                stragglers)
        self._g_inflight.set(0)
        self._g_inflight_batches.set(0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- warmup ------------------------------------------------------------
    def warmup(self, *example_inputs, shapes=None, introspect=True):
        """Pre-compile EVERY bucket, then prove the cache is sealed.

        ``example_inputs`` is one example request (each array with a
        leading row dim; trailing dims and dtypes fix the served
        signature). For each ladder rung the example is tiled/padded to
        the rung's row count and pushed through the compiled graph; with
        ``introspect=True`` each rung also lands in the diagnostics
        compile registry under ``(name, "b<rows>")`` with XLA's
        cost/memory analysis (HybridBlock.aot_introspect).

        ``shapes`` overrides the rung list (a caller-supplied iterable
        of row counts, each <= ``max_batch_size``) — for warming a
        deployment's measured shape mix instead of the whole ladder, or
        re-warming one rung after a cache flush. Default: every ladder
        bucket.

        The proof (shared :func:`warm_and_seal` path): after compiling,
        every rung is driven AGAIN and the predict-variant retrace
        counter must not move — a moving counter means some served
        shape misses the jit cache, and warmup raises rather than let
        an online compile hide on the hot path. Returns a summary dict.
        """
        ex = [_to_host(a) for a in example_inputs]
        if not ex or any(a.ndim < 1 for a in ex):
            raise ValueError(
                "warmup needs one example request: arrays with a "
                "leading row dimension")
        rows = ex[0].shape[0]
        if any(a.shape[0] != rows for a in ex):
            raise ValueError("example inputs disagree on row count")
        if shapes is None:
            rungs = list(self.buckets)
        else:
            rungs = sorted({int(b) for b in shapes})
            if not rungs:
                raise ValueError("shapes must name at least one rung")
            if rungs[0] < 1 or rungs[-1] > self.max_batch_size:
                raise ValueError(
                    f"warmup shapes {rungs} outside "
                    f"1..{self.max_batch_size}")
        t0 = time.perf_counter()

        def rung_inputs(b):
            return [NDArray(jnp.asarray(pad_rows(a[:min(rows, b)], b)))
                    for a in ex]

        def drive(b):
            _wait_ready([o._data for o in self._flatten_out(
                self._block.call_cached_graph(*rung_inputs(b)))])

        if introspect and hasattr(self._block, "aot_introspect"):
            # introspection pass first (it costs an extra AOT compile per
            # rung, so it must stay out of the seal-proof re-drive below)
            for b in rungs:
                self._block.aot_introspect(f"b{b}", *rung_inputs(b),
                                           label=self.name)
        warm_and_seal(drive, rungs,
                      lambda: self._block.jit_trace_count(False),
                      label="buckets")
        self._warm_traces = self._block.jit_trace_count(False)
        return {
            "model": self.name,
            "buckets": rungs,
            "compile_traces": self._warm_traces,
            "seconds": round(time.perf_counter() - t0, 4),
        }

    def recompiles_since_warmup(self):
        """Predict-variant retraces since warmup() sealed the cache —
        0 is the steady-state invariant; None before warmup."""
        if self._warm_traces is None:
            return None
        return self._block.jit_trace_count(False) - self._warm_traces

    # -- client side -------------------------------------------------------
    def submit(self, *inputs, timeout_ms=None, priority=None):
        """Enqueue one request; returns a :class:`ServeRequest` handle.

        Each input must carry a leading row dimension (1 <= rows <=
        ``max_batch_size``). ``priority`` names a scheduler class
        (default: the highest-priority one, ``"interactive"`` under the
        stock two-class policy). Never blocks: a full queue sheds with
        :class:`Overloaded`, a class over its admission rate with
        :class:`RateLimited`, a stopped engine raises
        :class:`EngineStopped`. ``timeout_ms`` overrides the engine's
        per-request deadline (0 disables it).
        """
        arrays = [_to_host(a) for a in inputs]
        if not arrays or any(a.ndim < 1 for a in arrays):
            raise ValueError(
                "submit needs arrays with a leading row dimension")
        rows = arrays[0].shape[0]
        if any(a.shape[0] != rows for a in arrays):
            raise ValueError("request inputs disagree on row count")
        if rows < 1 or rows > self.max_batch_size:
            raise ValueError(
                f"request rows {rows} outside 1..{self.max_batch_size} "
                "(split oversized requests client-side)")
        signature = tuple(
            (tuple(a.shape[1:]), str(a.dtype)) for a in arrays)
        tmo = self.timeout_s if timeout_ms is None else float(
            timeout_ms) / 1e3
        deadline = (time.monotonic() + tmo) if tmo > 0 else None
        cls = str(priority) if priority is not None \
            else self._sched.default_class
        req = ServeRequest(tuple(arrays), rows, signature, deadline,
                           cls=cls)
        req.model = self.name
        try:  # head-based sampling decision: None on the unsampled path
            req.trace = _reqtrace().maybe_start(
                self.name, cls=cls, rows=rows, deadline=deadline)
        except Exception:
            req.trace = None
        if self._stopping:
            err = EngineStopped(f"engine {self.name!r} is stopped")
            req._finish("shed", error=err)  # terminal trace span
            raise err
        try:
            self._sched.offer(req)  # sheds with Overloaded / RateLimited
        except Overloaded as e:  # includes RateLimited
            req._finish("shed", error=e)  # terminal span with the reason
            raise
        return req

    def predict(self, *inputs, timeout_ms=None, priority=None):
        """Synchronous round-trip: submit + wait. Raises Overloaded /
        RequestTimeout / EngineStopped like submit()/result()."""
        req = self.submit(*inputs, timeout_ms=timeout_ms,
                          priority=priority)
        try:
            return req.result()
        except RequestTimeout:
            _instr.record_serve_request(self.name, "timeout")
            raise

    # -- pipeline: assemble + dispatch ------------------------------------
    @staticmethod
    def _flatten_out(out):
        return out if isinstance(out, (list, tuple)) else (out,)

    def _assemble_dispatch(self, batch):
        """Pad the batch to its bucket on the host and ISSUE the
        dispatch; returns a :class:`_Flight` (or None — the whole batch
        failed and was settled with the error)."""
        rows = sum(r.rows for r in batch)
        bucket = pick_bucket(self.buckets, rows)
        # sampled members share batch-wide boundary stamps (ONE
        # perf_counter read per boundary per batch) and a batch id —
        # the batch->request causality link; unsampled batches pay one
        # empty list comprehension here and nothing below
        traced = [r.trace for r in batch if r.trace is not None]
        batch_id = None
        if traced:
            batch_id = _reqtrace().next_batch_id()
            t_asm = time.perf_counter()
            for tr in traced:
                tr.stamp("assembling", t_asm)  # queue phase closes
                tr.batch_id = batch_id
                tr.bucket = bucket
        try:
            with _spans.span(self.name, cat="serve"):
                padded = assemble_batch([r.inputs for r in batch], bucket)
                if getattr(self._block, "_host_native", False):
                    # simulated devices (sim.py) consume host numpy
                    # directly — no device transfer to model
                    nds = [NDArray(a) for a in padded]
                else:
                    nds = [NDArray(jnp.asarray(a)) for a in padded]
                if traced:
                    t_disp = time.perf_counter()
                    for tr in traced:
                        tr.stamp("dispatching", t_disp)
                out = self._block.call_cached_graph(*nds)
            datas = [o._data for o in self._flatten_out(out)]
            if traced:
                t_issued = time.perf_counter()
                for tr in traced:
                    tr.stamp("dispatched", t_issued)
            now = time.monotonic()
            for r in batch:
                r.t_dispatch = now
            self._c_dispatch.inc()
            return _Flight(batch, datas, rows, bucket,
                           batch_id=batch_id, traced=traced)
        except Exception as e:  # noqa: BLE001 — batch failure -> per-request
            now = time.monotonic()
            for r in batch:
                if r._finish("error", error=e):
                    _instr.record_serve_request(
                        self.name, "error", now - r.t_submit)
            return None

    def _complete(self, flight):
        """Block until the flight's outputs exist, slice each request's
        rows off, and settle the futures."""
        try:
            with _spans.span(self.name, cat="serve_complete"):
                _wait_ready(flight.datas)
            if flight.traced:
                t_ready = time.perf_counter()
                for tr in flight.traced:
                    tr.stamp("ready", t_ready)  # device phase closes
            _instr.record_serve_batch(self.name, flight.rows,
                                      flight.bucket)
            off, now = 0, time.monotonic()
            for r in flight.batch:
                # slice off exactly this request's rows — bucket padding
                # never reaches a client
                sl = [NDArray(d[off:off + r.rows]) for d in flight.datas]
                res = sl[0] if len(sl) == 1 else tuple(sl)
                if r.trace is not None:
                    r.trace.stamp("sliced")
                if r._finish("ok", result=res):
                    _instr.record_serve_request(
                        self.name, "ok", now - r.t_submit)
                off += r.rows
            if flight.traced:
                _reqtrace().record_batch(
                    flight.batch_id, self.name, flight.traced,
                    flight.rows, flight.bucket)
        except Exception as e:  # noqa: BLE001 — batch failure -> per-request
            now = time.monotonic()
            for r in flight.batch:
                if r._finish("error", error=e):
                    _instr.record_serve_request(
                        self.name, "error", now - r.t_submit)

    # -- pipelined mode: assembler + completer threads ---------------------
    def _loop_assembler(self):
        while True:
            batch = self._sched.collect(self.max_batch_size,
                                        self.max_wait_s)
            if batch is None:
                break
            # host work (pad/concat) + async dispatch happen OUTSIDE the
            # window lock: this is exactly the overlap — the device is
            # still computing the previous flight(s) while we assemble
            flight = self._assemble_dispatch(batch)
            if flight is None:
                continue
            with self._icond:
                # the window bound holds even while draining — only a
                # FORCE stop lifts it (so a dead completer can't wedge
                # shutdown); a graceful drain keeps dispatch-ahead bounded
                while (len(self._inflight) >= self.max_inflight
                       and not self._force):
                    self._icond.wait(0.05)
                self._inflight.append(flight)
                self._inflight_rows += flight.rows
                depth = len(self._inflight)
                if depth > self._max_inflight_seen:
                    self._max_inflight_seen = depth
                self._g_inflight.set(self._inflight_rows)
                self._g_inflight_batches.set(depth)
                self._icond.notify_all()
        with self._icond:  # sentinel: completer exits after draining
            self._inflight.append(None)
            self._icond.notify_all()

    def _loop_completer(self):
        while True:
            with self._icond:
                while not self._inflight:
                    self._icond.wait(0.05)
                flight = self._inflight[0]
                if flight is None:
                    self._inflight.popleft()
                    self._g_inflight.set(0)
                    self._g_inflight_batches.set(0)
                    return
            self._complete(flight)  # blocks on device results, settles
            with self._icond:
                self._inflight.popleft()
                self._inflight_rows -= flight.rows
                self._g_inflight.set(self._inflight_rows)
                self._g_inflight_batches.set(len(self._inflight))
                self._icond.notify_all()

    # -- sync mode: the serialized PR-3 baseline ---------------------------
    def _loop_sync(self):
        while True:
            batch = self._sched.collect(self.max_batch_size,
                                        self.max_wait_s)
            if batch is None:
                return
            flight = self._assemble_dispatch(batch)
            if flight is None:
                continue
            if not self._max_inflight_seen:
                self._max_inflight_seen = 1
            self._g_inflight.set(flight.rows)
            self._g_inflight_batches.set(1)
            self._complete(flight)
            self._g_inflight.set(0)
            self._g_inflight_batches.set(0)

    # -- observability -----------------------------------------------------
    def queue_depth(self):
        """Queued requests right now (mirrors serve_queue_depth)."""
        return self._sched.depth()

    def inflight_rows(self):
        """Rows inside dispatched-but-unsettled batches (mirrors
        serve_in_flight)."""
        with self._icond:
            return self._inflight_rows

    def load(self):
        """Least-loaded routing score for the front door: queued rows +
        in-flight rows (the same quantities the serve_queue_depth and
        serve_in_flight gauges publish)."""
        return self._sched.depth_rows() + self.inflight_rows()

    def _latency_quantile_ms(self, q):
        """Approximate latency quantile (ms) from the telemetry histogram
        (upper bound of the covering bucket); None when no samples or
        telemetry is disabled."""
        child = _instr.serve_request_latency_seconds.labels(self.name)
        count = child.count
        if not count:
            return None
        target = q * count
        cum = child.cumulative()
        for bound, acc in cum:
            if acc >= target:
                if bound == float("inf"):
                    bound = cum[-2][0] if len(cum) > 1 else 0.0
                return round(float(bound) * 1e3, 3)
        return None

    def admission_state(self):
        """What a submit() would meet right now: ``"ok"`` (admitted),
        ``"overloaded"`` (queue at bound — the next submit sheds with
        :class:`Overloaded`), or ``"stopped"``. The ops server's
        ``/readyz`` reports not-ready unless every registered engine is
        ``"ok"`` — a front door stops routing to a shedding replica and
        resumes once its queue drains."""
        if self._stopping:
            return "stopped"
        if self._sched.at_bound():
            return "overloaded"
        return "ok"

    def stats(self):
        """Live snapshot: queue/in-flight, outcome counters, batch shape,
        latency p50/p99, per-class scheduler state, pipeline window, and
        the zero-recompile invariant."""
        outcomes = {
            lv[1]: c.value
            for lv, c in _instr.serve_request_total.series()
            if lv[0] == self.name}
        batches = _instr.serve_batch_total.labels(self.name).value
        bs = _instr.serve_batch_size.labels(self.name)
        with self._icond:
            inflight_batches = sum(
                1 for f in self._inflight if f is not None)
            inflight_rows = self._inflight_rows
            max_seen = self._max_inflight_seen
        return {
            "model": self.name,
            "started": self.started,
            "mode": self.mode,
            "buckets": list(self.buckets),
            "queue_depth": self._sched.depth(),
            "max_queue": self.max_queue,
            "in_flight": inflight_rows,
            "inflight_batches": inflight_batches,
            "max_inflight": self.max_inflight,
            "max_inflight_seen": max_seen,
            "classes": self._sched.class_stats(),
            "requests": outcomes,
            "batches": batches,
            "dispatches": self._c_dispatch.value,
            "avg_batch_rows": round(bs.sum / bs.count, 3) if bs.count
            else None,
            "padded_rows":
                _instr.serve_padded_rows_total.labels(self.name).value,
            "drain_dropped":
                _instr.serve_drain_dropped_total.labels(self.name).value,
            "p50_ms": self._latency_quantile_ms(0.50),
            "p99_ms": self._latency_quantile_ms(0.99),
            "recompiles_since_warmup": self.recompiles_since_warmup(),
            "trace_sample": self._trace_sample(),
            "slo": self._slo_status(),
        }

    def _trace_sample(self):
        try:
            return _reqtrace().sample_rate()
        except Exception:
            return 0.0

    def _slo_status(self):
        """This model's per-class SLO table (None when no class has a
        declared objective or no traffic has been observed)."""
        try:
            return _reqtrace().slo_status().get(self.name)
        except Exception:
            return None
