"""Dynamic micro-batching inference engine over a hybridized block.

The in-process serving half of the stack (ISSUE 3 tentpole; design
anchors: TensorFlow Serving's request batching — PAPERS.md "TensorFlow:
A system for large-scale machine learning" §serving — and bucketed
compile caching per the TPU cost model, "A Learned Performance Model for
Tensor Processing Units"):

  * client threads ``submit()`` single- or multi-row requests into ONE
    bounded queue; a dedicated batcher thread coalesces them up to
    ``max_batch_size`` rows or until the oldest request has waited
    ``max_wait_ms`` (TF-Serving's batch deadline), whichever first;
  * every batch is padded to a rung of the pre-compiled bucket ladder
    (buckets.py), so steady state NEVER sees an online XLA compile —
    ``warmup()`` compiles all rungs up front and proves it (zero
    retraces re-driving every bucket, per-bucket entries in the
    diagnostics compile registry);
  * admission control is a hard queue bound: submits beyond it fail
    FAST with :class:`~mxnet_tpu.serving.errors.Overloaded` (typed,
    deterministic — never a blocked client, never a deadlock), and each
    request carries a deadline enforced on both sides of the queue
    (:class:`~mxnet_tpu.serving.errors.RequestTimeout`);
  * everything is observable: request-latency histogram (p50/p99),
    queue-depth and in-flight gauges, shed/timeout/batch-size counters
    (telemetry/instruments.py ``serve_*``), and a ``serve`` span per
    executed batch (diagnostics/spans.py).

The compiled hot path is ``HybridBlock.call_cached_graph`` — predict
mode, no taping, thread-safe, and never an eager fallback.

Defaults come from the typed env registry: MXTPU_SERVE_MAX_BATCH,
MXTPU_SERVE_QUEUE, MXTPU_SERVE_MAX_WAIT_MS, MXTPU_SERVE_TIMEOUT_MS.
See docs/serving.md.
"""
from __future__ import annotations

import collections
import threading
import time

import jax.numpy as jnp
import numpy as _np

from .. import env as _env
from ..diagnostics import spans as _spans
from ..ndarray.ndarray import NDArray
from ..telemetry import instruments as _instr
from .buckets import assemble_batch, bucket_ladder, pad_rows, pick_bucket
from .errors import EngineStopped, Overloaded, RequestTimeout

__all__ = ["InferenceEngine", "ServeRequest"]


def _to_host(a):
    """Request input -> host numpy (one device transfer per BATCH, not
    per request, so assembly stays on the host)."""
    if isinstance(a, NDArray):
        return a.asnumpy()
    return _np.asarray(a)


class ServeRequest:
    """One in-flight request: inputs, deadline, and a settable outcome.

    The outcome transition is atomic (first of {batcher result, batcher
    error, timeout, shed} wins), so the client and the batcher can race
    on a deadline without double-counting or half-set results.
    """

    __slots__ = ("inputs", "rows", "signature", "t_submit", "deadline",
                 "_event", "_lock", "outcome", "_result", "_error")

    def __init__(self, inputs, rows, signature, deadline):
        self.inputs = inputs
        self.rows = rows
        self.signature = signature
        self.t_submit = time.monotonic()
        self.deadline = deadline  # absolute monotonic seconds, or None
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.outcome = None  # ok | timeout | error (claimed once)
        self._result = None
        self._error = None

    def _finish(self, outcome, result=None, error=None):
        """Claim the outcome; True iff this call won the claim."""
        with self._lock:
            if self.outcome is not None:
                return False
            self.outcome = outcome
            self._result = result
            self._error = error
        self._event.set()
        return True

    @property
    def done(self):
        return self.outcome is not None

    def result(self, timeout=None):
        """Block until the outcome; return the model output (NDArray, or
        a tuple for multi-output models) or raise the typed failure.

        ``timeout`` (seconds) overrides the request deadline for this
        wait; by default the wait extends to the deadline (forever when
        the request has none).
        """
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        self._event.wait(timeout)
        if not self.done:
            # nothing finished us in time — claim the timeout ourselves
            # (the batcher skips claimed requests when it reaches them)
            self._finish("timeout",
                         error=RequestTimeout(
                             f"request not served within "
                             f"{timeout if timeout is not None else 0:.3f}s"))
        if self.outcome == "ok":
            return self._result
        raise self._error


class InferenceEngine:
    """Thread-safe dynamic-batching server around one hybridized block.

    ::

        net = ...HybridBlock...; net.initialize(); net.hybridize()
        eng = serving.InferenceEngine(net, name="resnet", max_batch_size=16)
        eng.warmup(mx.np.zeros((1, 224, 224, 3)))   # compile every bucket
        eng.start()
        out = eng.predict(x)                        # from any thread
        eng.stop()

    Lifecycle: construct -> (optional) warmup -> start -> serve -> stop.
    ``submit()`` works before ``start()`` (requests queue; admission
    control still applies) — convenient for tests and staged bring-up.
    """

    def __init__(self, block, name="model", max_batch_size=None,
                 max_queue=None, max_wait_ms=None, timeout_ms=None,
                 buckets=None):
        if not hasattr(block, "call_cached_graph"):
            raise TypeError(
                f"InferenceEngine needs a HybridBlock, got {type(block)}")
        self._block = block
        self.name = str(name)
        self.max_batch_size = int(
            max_batch_size if max_batch_size is not None
            else _env.get("MXTPU_SERVE_MAX_BATCH"))
        self.max_queue = int(
            max_queue if max_queue is not None
            else _env.get("MXTPU_SERVE_QUEUE"))
        self.max_wait_s = float(
            max_wait_ms if max_wait_ms is not None
            else _env.get("MXTPU_SERVE_MAX_WAIT_MS")) / 1e3
        self.timeout_s = float(
            timeout_ms if timeout_ms is not None
            else _env.get("MXTPU_SERVE_TIMEOUT_MS")) / 1e3
        self.buckets = bucket_ladder(self.max_batch_size, buckets)
        self._cond = threading.Condition()
        self._queue = collections.deque()
        self._stopping = False
        self._thread = None
        self._warm_traces = None
        # cached label children: the hot path mutates gauges without
        # re-resolving labels (each child still honors enable/disable)
        self._g_queue = _instr.serve_queue_depth.labels(self.name)
        self._g_inflight = _instr.serve_in_flight.labels(self.name)

    # -- lifecycle ---------------------------------------------------------
    @property
    def started(self):
        return self._thread is not None and self._thread.is_alive()

    def start(self):
        """Start the batcher thread (idempotent)."""
        with self._cond:
            if self._stopping:
                raise EngineStopped(f"engine {self.name!r} was stopped")
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name=f"mxtpu-serve-{self.name}",
                    daemon=True)
                self._thread.start()
        try:
            from ..observability import flight as _flight

            _flight.record("serve_start", model=self.name)
        except Exception:
            pass
        return self

    def stop(self, drain=True):
        """Stop accepting work; by default drain queued requests first.
        With ``drain=False`` pending requests fail with EngineStopped."""
        with self._cond:
            self._stopping = True
            if not drain:
                dropped, self._queue = list(self._queue), \
                    collections.deque()
                self._g_queue.set(0)
            else:
                dropped = []
            self._cond.notify_all()
        for r in dropped:
            if r._finish("error",
                         error=EngineStopped(
                             f"engine {self.name!r} stopped")):
                _instr.record_serve_request(self.name, "error")
        if self._thread is not None:
            self._thread.join(timeout=30)
        try:
            from ..observability import flight as _flight

            _flight.record("serve_stop", model=self.name,
                           drained=bool(drain))
        except Exception:
            pass
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- warmup ------------------------------------------------------------
    def warmup(self, *example_inputs, introspect=True):
        """Pre-compile EVERY bucket, then prove the cache is sealed.

        ``example_inputs`` is one example request (each array with a
        leading row dim; trailing dims and dtypes fix the served
        signature). For each ladder rung the example is tiled/padded to
        the rung's row count and pushed through the compiled graph; with
        ``introspect=True`` each rung also lands in the diagnostics
        compile registry under ``(name, "b<rows>")`` with XLA's
        cost/memory analysis (HybridBlock.aot_introspect).

        The proof: after compiling, every rung is driven AGAIN and the
        predict-variant retrace counter must not move — a moving counter
        means some served shape misses the jit cache, and warmup raises
        rather than let an online compile hide on the hot path. Returns
        a summary dict.
        """
        ex = [_to_host(a) for a in example_inputs]
        if not ex or any(a.ndim < 1 for a in ex):
            raise ValueError(
                "warmup needs one example request: arrays with a "
                "leading row dimension")
        rows = ex[0].shape[0]
        if any(a.shape[0] != rows for a in ex):
            raise ValueError("example inputs disagree on row count")
        t0 = time.perf_counter()

        def rung_inputs(b):
            return [NDArray(jnp.asarray(pad_rows(a[:min(rows, b)], b)))
                    for a in ex]

        for b in self.buckets:
            nds = rung_inputs(b)
            self._block.call_cached_graph(*nds)
            if introspect:
                self._block.aot_introspect(f"b{b}", *nds, label=self.name)
        traces = self._block.jit_trace_count(False)
        for b in self.buckets:  # re-drive: everything must cache-hit now
            self._block.call_cached_graph(*rung_inputs(b))
        added = self._block.jit_trace_count(False) - traces
        if added:
            raise RuntimeError(
                f"warmup failed to seal the jit cache: {added} "
                f"recompile(s) re-driving buckets {self.buckets} — "
                "served shapes would compile online")
        self._warm_traces = self._block.jit_trace_count(False)
        self._example_trailing = [
            (tuple(a.shape[1:]), _np.dtype(a.dtype)) for a in ex]
        return {
            "model": self.name,
            "buckets": list(self.buckets),
            "compile_traces": self._warm_traces,
            "seconds": round(time.perf_counter() - t0, 4),
        }

    def recompiles_since_warmup(self):
        """Predict-variant retraces since warmup() sealed the cache —
        0 is the steady-state invariant; None before warmup."""
        if self._warm_traces is None:
            return None
        return self._block.jit_trace_count(False) - self._warm_traces

    # -- client side -------------------------------------------------------
    def submit(self, *inputs, timeout_ms=None):
        """Enqueue one request; returns a :class:`ServeRequest` handle.

        Each input must carry a leading row dimension (1 <= rows <=
        ``max_batch_size``). Never blocks: a full queue sheds with
        :class:`Overloaded`, a stopped engine raises
        :class:`EngineStopped`. ``timeout_ms`` overrides the engine's
        per-request deadline (0 disables it).
        """
        arrays = [_to_host(a) for a in inputs]
        if not arrays or any(a.ndim < 1 for a in arrays):
            raise ValueError(
                "submit needs arrays with a leading row dimension")
        rows = arrays[0].shape[0]
        if any(a.shape[0] != rows for a in arrays):
            raise ValueError("request inputs disagree on row count")
        if rows < 1 or rows > self.max_batch_size:
            raise ValueError(
                f"request rows {rows} outside 1..{self.max_batch_size} "
                "(split oversized requests client-side)")
        signature = tuple(
            (tuple(a.shape[1:]), str(a.dtype)) for a in arrays)
        tmo = self.timeout_s if timeout_ms is None else float(
            timeout_ms) / 1e3
        deadline = (time.monotonic() + tmo) if tmo > 0 else None
        req = ServeRequest(tuple(arrays), rows, signature, deadline)
        with self._cond:
            if self._stopping:
                raise EngineStopped(f"engine {self.name!r} is stopped")
            if len(self._queue) >= self.max_queue:
                _instr.record_serve_request(self.name, "shed")
                raise Overloaded(
                    f"engine {self.name!r} queue at bound "
                    f"{self.max_queue}; request shed")
            self._queue.append(req)
            self._g_queue.set(len(self._queue))
            self._cond.notify()
        return req

    def predict(self, *inputs, timeout_ms=None):
        """Synchronous round-trip: submit + wait. Raises Overloaded /
        RequestTimeout / EngineStopped like submit()/result()."""
        req = self.submit(*inputs, timeout_ms=timeout_ms)
        try:
            return req.result()
        except RequestTimeout:
            _instr.record_serve_request(self.name, "timeout")
            raise

    # -- batcher side ------------------------------------------------------
    def _expire_locked(self):
        """Drop finished (client-claimed) and past-deadline requests from
        the queue; called with the condition held."""
        now = time.monotonic()
        keep = collections.deque()
        for r in self._queue:
            if r.done:
                continue  # client already claimed (timeout) — drop
            if r.deadline is not None and now >= r.deadline:
                if r._finish("timeout", error=RequestTimeout(
                        "deadline elapsed while queued")):
                    _instr.record_serve_request(
                        self.name, "timeout", now - r.t_submit)
                continue
            keep.append(r)
        if len(keep) != len(self._queue):
            self._queue = keep
            self._g_queue.set(len(keep))

    def _collect(self):
        """Pop the next micro-batch: same-signature requests up to
        ``max_batch_size`` rows, or whatever arrived by the time the
        oldest one has waited ``max_wait_ms``. None = stopped + drained."""
        with self._cond:
            while True:
                self._expire_locked()
                if self._queue:
                    break
                if self._stopping:
                    return None
                self._cond.wait(0.05)
            head = self._queue.popleft()
            batch, rows = [head], head.rows
            launch_at = head.t_submit + self.max_wait_s
            while rows < self.max_batch_size:
                if self._queue:
                    nxt = self._queue[0]
                    if nxt.done or (
                            nxt.deadline is not None
                            and time.monotonic() >= nxt.deadline):
                        self._expire_locked()
                        continue
                    if nxt.signature != head.signature or \
                            rows + nxt.rows > self.max_batch_size:
                        break  # different shape family / no room: next batch
                    self._queue.popleft()
                    batch.append(nxt)
                    rows += nxt.rows
                    continue
                remaining = launch_at - time.monotonic()
                if remaining <= 0 or self._stopping:
                    break
                self._cond.wait(min(remaining, 0.05))
            self._g_queue.set(len(self._queue))
        return batch

    def _run_batch(self, batch):
        rows = sum(r.rows for r in batch)
        bucket = pick_bucket(self.buckets, rows)
        self._g_inflight.set(rows)
        try:
            padded = assemble_batch([r.inputs for r in batch], bucket)
            nds = [NDArray(jnp.asarray(a)) for a in padded]
            with _spans.span(self.name, cat="serve"):
                out = self._block.call_cached_graph(*nds)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            datas = [o._data for o in outs]
            _instr.record_serve_batch(self.name, rows, bucket)
            off, now = 0, time.monotonic()
            for r in batch:
                # slice off exactly this request's rows — bucket padding
                # never reaches a client
                sl = [NDArray(d[off:off + r.rows]) for d in datas]
                res = sl[0] if len(sl) == 1 else tuple(sl)
                if r._finish("ok", result=res):
                    _instr.record_serve_request(
                        self.name, "ok", now - r.t_submit)
                off += r.rows
        except Exception as e:  # noqa: BLE001 — batch failure -> per-request
            now = time.monotonic()
            for r in batch:
                if r._finish("error", error=e):
                    _instr.record_serve_request(
                        self.name, "error", now - r.t_submit)
        finally:
            self._g_inflight.set(0)

    def _loop(self):
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._run_batch(batch)

    # -- observability -----------------------------------------------------
    def _latency_quantile_ms(self, q):
        """Approximate latency quantile (ms) from the telemetry histogram
        (upper bound of the covering bucket); None when no samples or
        telemetry is disabled."""
        child = _instr.serve_request_latency_seconds.labels(self.name)
        count = child.count
        if not count:
            return None
        target = q * count
        cum = child.cumulative()
        for bound, acc in cum:
            if acc >= target:
                if bound == float("inf"):
                    bound = cum[-2][0] if len(cum) > 1 else 0.0
                return round(float(bound) * 1e3, 3)
        return None

    def admission_state(self):
        """What a submit() would meet right now: ``"ok"`` (admitted),
        ``"overloaded"`` (queue at bound — the next submit sheds with
        :class:`Overloaded`), or ``"stopped"``. The ops server's
        ``/readyz`` reports not-ready unless every registered engine is
        ``"ok"`` — a front door stops routing to a shedding replica and
        resumes once its queue drains."""
        with self._cond:
            if self._stopping:
                return "stopped"
            if len(self._queue) >= self.max_queue:
                return "overloaded"
        return "ok"

    def stats(self):
        """Live snapshot: queue/in-flight, outcome counters, batch shape,
        latency p50/p99, and the zero-recompile invariant."""
        outcomes = {
            lv[1]: c.value
            for lv, c in _instr.serve_request_total.series()
            if lv[0] == self.name}
        batches = _instr.serve_batch_total.labels(self.name).value
        bs = _instr.serve_batch_size.labels(self.name)
        return {
            "model": self.name,
            "started": self.started,
            "buckets": list(self.buckets),
            "queue_depth": len(self._queue),
            "max_queue": self.max_queue,
            "in_flight": _instr.serve_in_flight.labels(self.name).value,
            "requests": outcomes,
            "batches": batches,
            "avg_batch_rows": round(bs.sum / bs.count, 3) if bs.count
            else None,
            "padded_rows":
                _instr.serve_padded_rows_total.labels(self.name).value,
            "p50_ms": self._latency_quantile_ms(0.50),
            "p99_ms": self._latency_quantile_ms(0.99),
            "recompiles_since_warmup": self.recompiles_since_warmup(),
        }
