"""Replica front door: N engines behind one ``submit()``.

One :class:`InferenceEngine` serves one device's worth of traffic; a
fleet serves "millions of users" (ROADMAP north star) by running N
replicas of the same model and routing each request to the replica that
will serve it soonest. :class:`FrontDoor` is that router, deliberately
thin:

  * **least-loaded dispatch** — each submit goes to the healthy replica
    with the smallest ``engine.load()`` (queued rows + in-flight rows,
    the same quantities the ``serve_queue_depth`` / ``serve_in_flight``
    gauges publish, so the routing decision is exactly what the
    dashboards show);
  * **health-checking** — a replica is routable iff its health check
    passes. The default check is in-process:
    ``admission_state() == "ok"`` (stopped and shedding replicas drop
    out, and recover automatically once their queue drains). For
    replicas fronted by the live ops server, :class:`OpsPlaneHealth`
    polls each rank's ``/readyz`` endpoint (observability/opsd.py) on a
    background thread — the same plane ``fleetctl`` scrapes — so
    out-of-process replicas are routable too;
  * **failover on shed** — if the chosen replica sheds with
    ``Overloaded`` the front door tries the remaining healthy replicas
    in load order before giving up; only when EVERY replica sheds does
    the caller see :class:`~mxnet_tpu.serving.errors.Overloaded`.

The front door adds no queue of its own — admission control stays in
the engines, so the bounded-queue/shedding contract (errors.py) is
unchanged, and a front-door submit is one lock-free load scan plus the
engine submit. Register a replica set with
``serving.REGISTRY.register_replicas(name, engines)`` and the ops
server's ``/readyz`` reflects every replica individually.
"""
from __future__ import annotations

import threading
import urllib.request

from .errors import EngineStopped, Overloaded

__all__ = ["FrontDoor", "OpsPlaneHealth"]


def _default_healthy(engine):
    return engine.admission_state() == "ok"


class OpsPlaneHealth:
    """Health checker backed by the live ops plane: polls each replica's
    ``/readyz`` (observability/opsd.py, HTTP 200 = ready) on a daemon
    thread and caches the verdict.

    ``urls`` maps engine name -> base URL (e.g. ``http://host:9100``).
    Replicas without a URL fall back to the in-process
    ``admission_state()`` check. A replica whose endpoint errors or
    times out is unhealthy until a poll succeeds again — fail closed,
    like fleetctl's unreachable-rank accounting.
    """

    def __init__(self, urls, interval_s=1.0, timeout_s=0.5):
        self.urls = dict(urls)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._ready = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="mxtpu-frontdoor-health", daemon=True)
        self._thread.start()

    def _poll_once(self):
        for name, base in self.urls.items():
            ok = False
            try:
                with urllib.request.urlopen(
                        base.rstrip("/") + "/readyz",
                        timeout=self.timeout_s) as resp:
                    ok = resp.status == 200
            except Exception:
                ok = False
            with self._lock:
                self._ready[name] = ok

    def _loop(self):
        while not self._stop.is_set():
            self._poll_once()
            self._stop.wait(self.interval_s)

    def __call__(self, engine):
        name = getattr(engine, "name", None)
        if name not in self.urls:
            return _default_healthy(engine)
        with self._lock:
            return self._ready.get(name, False)

    def close(self):
        self._stop.set()


class FrontDoor:
    """Least-loaded router over a replica set of engines serving the
    same model signature.

    ::

        fd = FrontDoor([eng0, eng1, eng2])
        req = fd.submit(x)          # routed to the least-loaded replica
        out = req.result()

    ``health_check`` is any callable ``engine -> bool``; default is the
    in-process ``admission_state() == "ok"``. Pass an
    :class:`OpsPlaneHealth` to route on the ops-server plane instead.
    """

    def __init__(self, engines, name="frontdoor", health_check=None):
        engines = list(engines)
        if not engines:
            raise ValueError("FrontDoor needs at least one engine")
        self.name = str(name)
        self.engines = engines
        self._healthy = health_check or _default_healthy
        self._routed = {e.name: 0 for e in engines}
        self._lock = threading.Lock()

    # -- routing -----------------------------------------------------------
    def _candidates(self):
        """Healthy replicas, least-loaded first (ties: declaration
        order, which keeps routing deterministic in tests)."""
        healthy = [e for e in self.engines if self._healthy(e)]
        return sorted(healthy, key=lambda e: e.load())

    def submit(self, *inputs, timeout_ms=None, priority=None):
        """Route one request to the best replica; returns that engine's
        :class:`~mxnet_tpu.serving.engine.ServeRequest`.

        Raises :class:`Overloaded` only when every healthy replica
        sheds, :class:`EngineStopped` when no replica is healthy at
        all."""
        last = None
        for tries, eng in enumerate(self._candidates(), start=1):
            try:
                req = eng.submit(*inputs, timeout_ms=timeout_ms,
                                 priority=priority)
                with self._lock:
                    self._routed[eng.name] += 1
                if req.trace is not None:
                    # routing context on the sampled trace: which
                    # replica won and how many sheds it took to land
                    req.trace.annotate(frontdoor=self.name,
                                       replica=eng.name, tries=tries)
                return req
            except Overloaded as e:  # includes RateLimited
                last = e  # shed here — fail over to the next replica
            except EngineStopped as e:
                last = e  # stopped between health check and submit
        if isinstance(last, Overloaded):
            raise Overloaded(
                f"front door {self.name!r}: all "
                f"{len(self.engines)} replicas shed") from last
        raise EngineStopped(
            f"front door {self.name!r}: no healthy replica "
            f"(of {len(self.engines)})") from last

    def predict(self, *inputs, timeout_ms=None, priority=None):
        req = self.submit(*inputs, timeout_ms=timeout_ms,
                          priority=priority)
        return req.result()

    # -- streaming (decode replicas) ---------------------------------------
    def submit_stream(self, prompt, **kwargs):
        """Route one generation request to the best decode replica;
        returns that engine's
        :class:`~mxnet_tpu.decode.engine.SequenceRequest` (stream
        tokens off its ``.stream()``).

        Only replicas exposing ``submit_stream`` (decode engines) are
        candidates — a mixed registry of one-shot and decode replicas
        routes each request kind to the engines that speak it. Failover
        semantics match :meth:`submit`: sheds try the next replica,
        :class:`Overloaded` only when every streaming replica sheds.
        """
        last = None
        cands = [e for e in self._candidates()
                 if hasattr(e, "submit_stream")]
        for tries, eng in enumerate(cands, start=1):
            try:
                seq = eng.submit_stream(prompt, **kwargs)
                with self._lock:
                    self._routed[eng.name] += 1
                if seq.trace is not None:
                    seq.trace.annotate(frontdoor=self.name,
                                       replica=eng.name, tries=tries)
                return seq
            except Overloaded as e:  # includes RateLimited
                last = e
            except EngineStopped as e:
                last = e
        if isinstance(last, Overloaded):
            raise Overloaded(
                f"front door {self.name!r}: all {len(cands)} streaming "
                "replicas shed") from last
        raise EngineStopped(
            f"front door {self.name!r}: no healthy streaming replica "
            f"(of {len(self.engines)} total)") from last

    def generate(self, prompt, **kwargs):
        """Submit + stream through the front door: yields tokens from
        the routed replica as they settle."""
        return self.submit_stream(prompt, **kwargs).stream()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        for e in self.engines:
            e.start()
        return self

    def stop(self, drain=True, drain_timeout_ms=None):
        for e in self.engines:
            e.stop(drain=drain, drain_timeout_ms=drain_timeout_ms)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- observability -----------------------------------------------------
    def healthy_names(self):
        return [e.name for e in self.engines if self._healthy(e)]

    def stats(self):
        """Routing table snapshot: per-replica health, load score, and
        requests routed, plus the replica the NEXT submit would pick."""
        cands = self._candidates()
        with self._lock:
            routed = dict(self._routed)
        return {
            "frontdoor": self.name,
            "replicas": {
                e.name: {
                    "healthy": self._healthy(e),
                    "load": e.load(),
                    "queue_depth": e.queue_depth(),
                    "inflight_rows": e.inflight_rows(),
                    "routed": routed.get(e.name, 0),
                    "state": e.admission_state(),
                }
                for e in self.engines},
            "next_pick": cands[0].name if cands else None,
        }
