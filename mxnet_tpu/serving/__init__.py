"""In-process inference serving: pipelined continuous batching over the
jit cache (ISSUE 3 tentpole, rebuilt as a pipeline in ISSUE 15;
docs/serving.md).

    engine.py     InferenceEngine — assembler/completer pipeline with a
                  bounded in-flight window, in-flight joining, bucket
                  padding, warmup() zero-recompile proof, deadline-aware
                  bounded drain; mode="sync" keeps the serialized PR-3
                  loop as the A/B baseline
    scheduler.py  RequestScheduler — priority classes, strict-priority
                  dequeue, per-class token-bucket admission
    frontdoor.py  FrontDoor — N replicas behind one submit(),
                  least-loaded routing, ops-plane health checks
    buckets.py    the batch-bucket ladder (compile-shape vocabulary)
    registry.py   ModelRegistry — multi-model process, replica sets,
                  REGISTRY default
    sim.py        SimulatedBlock — deterministic slow device for
                  pipeline tests/benchmarks
    errors.py     Overloaded / RateLimited / RequestTimeout /
                  EngineStopped

Quick start::

    from mxnet_tpu import serving
    eng = serving.InferenceEngine(net, name="resnet")
    eng.warmup(example_batch)
    with eng:                       # start()/stop()
        y = eng.predict(x)
        bg = eng.submit(x2, priority="batch")   # rides in spare rows
        y2 = bg.result()
"""
from __future__ import annotations

from .buckets import (assemble_batch, bucket_ladder, pad_axis, pad_rows,
                      pick_bucket)
from .engine import InferenceEngine, ServeRequest, warm_and_seal
from .errors import (EngineStopped, Overloaded, RateLimited,
                     RequestTimeout, ServingError)
from .frontdoor import FrontDoor, OpsPlaneHealth
from .registry import REGISTRY, ModelRegistry
from .scheduler import (DEFAULT_CLASSES, RequestScheduler, ServeClass,
                        TokenBucket)
from .sim import SimulatedBlock

__all__ = [
    "InferenceEngine", "ServeRequest",
    "RequestScheduler", "ServeClass", "TokenBucket", "DEFAULT_CLASSES",
    "FrontDoor", "OpsPlaneHealth",
    "ModelRegistry", "REGISTRY",
    "SimulatedBlock",
    "ServingError", "Overloaded", "RateLimited", "RequestTimeout",
    "EngineStopped",
    "bucket_ladder", "pick_bucket", "pad_rows", "pad_axis",
    "assemble_batch", "warm_and_seal",
]
