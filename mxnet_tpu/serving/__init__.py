"""In-process inference serving: dynamic micro-batching over the jit
cache (ISSUE 3 tentpole; docs/serving.md).

    engine.py    InferenceEngine — bounded queue, batcher thread,
                 bucket padding, warmup() zero-recompile proof,
                 admission control, per-request deadlines
    buckets.py   the batch-bucket ladder (compile-shape vocabulary)
    registry.py  ModelRegistry — multi-model process, REGISTRY default
    errors.py    Overloaded / RequestTimeout / EngineStopped

Quick start::

    from mxnet_tpu import serving
    eng = serving.InferenceEngine(net, name="resnet")
    eng.warmup(example_batch)
    with eng:                       # start()/stop()
        y = eng.predict(x)
"""
from __future__ import annotations

from .buckets import assemble_batch, bucket_ladder, pad_rows, pick_bucket
from .engine import InferenceEngine, ServeRequest
from .errors import EngineStopped, Overloaded, RequestTimeout, ServingError
from .registry import REGISTRY, ModelRegistry

__all__ = [
    "InferenceEngine", "ServeRequest",
    "ModelRegistry", "REGISTRY",
    "ServingError", "Overloaded", "RequestTimeout", "EngineStopped",
    "bucket_ladder", "pick_bucket", "pad_rows", "assemble_batch",
]
