"""External operator library loading (reference: python/mxnet/library.py
dlopen of user .so built against include/mxnet/lib_api.h, MX_LIBRARY_VERSION
11 — CustomOp/CustomPartitioner/CustomPass without rebuilding the framework).

TPU re-design: the versioned C ABI is a small tensor struct + compute entry
points (see native/mxtpu_ext_example.cc). Loaded ops execute on host buffers
via ctypes and are wrapped as framework ops: they appear under `mx.nd.<name>`
and integrate with autograd through the numerical path only if the library
provides a backward entry (suffix `_backward`), mirroring how lib_api custom
ops declare gradients. Graph passes/partitioners have no analog here — XLA
owns the graph (SURVEY.md §7 translation table: subgraph properties →
whole-graph jit).
"""
from __future__ import annotations

import ctypes
import os

import numpy as _np

from .ndarray.ndarray import NDArray

__all__ = ["load", "loaded_libs", "MXTPU_LIB_VERSION"]

MXTPU_LIB_VERSION = 1

_LOADED = {}


class _MXTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.POINTER(ctypes.c_float)),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("ndim", ctypes.c_int32),
    ]


def _to_mxtensor(arr, keepalive):
    arr = _np.ascontiguousarray(arr, _np.float32)
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    keepalive.extend([arr, shape])
    return _MXTensor(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, arr.ndim)


def loaded_libs():
    return dict(_LOADED)


def load(path, verbose=True):
    """Load an external op library; returns the list of registered op names.

    The library must export:
      int  mxtpu_lib_version(void);
      int  mxtpu_num_ops(void);
      const char* mxtpu_op_name(int i);
      int  mxtpu_op_num_outputs(int i);
      int  mxtpu_op_compute(int i, MXTensor* ins, int n_in,
                            MXTensor* outs, int n_out);
    Output buffers are preallocated by the framework with the same shape as
    input 0 (libraries needing other shapes export
    mxtpu_op_infer_shape(int i, int64_t* shape, int* ndim)).
    """
    path = os.path.abspath(path)
    lib = ctypes.CDLL(path)
    lib.mxtpu_lib_version.restype = ctypes.c_int
    version = lib.mxtpu_lib_version()
    if version > MXTPU_LIB_VERSION:
        raise RuntimeError(
            f"library ABI v{version} newer than supported "
            f"v{MXTPU_LIB_VERSION}")
    lib.mxtpu_num_ops.restype = ctypes.c_int
    lib.mxtpu_op_name.restype = ctypes.c_char_p
    lib.mxtpu_op_name.argtypes = [ctypes.c_int]
    lib.mxtpu_op_num_outputs.restype = ctypes.c_int
    lib.mxtpu_op_num_outputs.argtypes = [ctypes.c_int]
    lib.mxtpu_op_compute.restype = ctypes.c_int
    lib.mxtpu_op_compute.argtypes = [
        ctypes.c_int, ctypes.POINTER(_MXTensor), ctypes.c_int,
        ctypes.POINTER(_MXTensor), ctypes.c_int]
    has_infer = hasattr(lib, "mxtpu_op_infer_shape")
    if has_infer:
        lib.mxtpu_op_infer_shape.restype = ctypes.c_int
        lib.mxtpu_op_infer_shape.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int)]

    names = []
    from . import ndarray as nd_mod
    from .ops.registry import register_op

    for i in range(lib.mxtpu_num_ops()):
        name = lib.mxtpu_op_name(i).decode()
        n_out = lib.mxtpu_op_num_outputs(i)

        def make_wrapper(idx, n_out, opname):
            def wrapper(*inputs):
                keep = []
                np_ins = [x.asnumpy() if isinstance(x, NDArray)
                          else _np.asarray(x) for x in inputs]
                ins = (_MXTensor * len(np_ins))(
                    *[_to_mxtensor(a, keep) for a in np_ins])
                if has_infer:
                    shape_buf = (ctypes.c_int64 * 8)()
                    ndim = ctypes.c_int(0)
                    rc = lib.mxtpu_op_infer_shape(idx, shape_buf,
                                                  ctypes.byref(ndim))
                    if rc != 0:
                        raise RuntimeError(f"{opname}: infer_shape failed")
                    out_shape = tuple(shape_buf[: ndim.value])
                else:
                    out_shape = np_ins[0].shape
                np_outs = [_np.zeros(out_shape, _np.float32)
                           for _ in range(n_out)]
                outs = (_MXTensor * n_out)(
                    *[_to_mxtensor(a, keep) for a in np_outs])
                rc = lib.mxtpu_op_compute(idx, ins, len(np_ins), outs, n_out)
                if rc != 0:
                    raise RuntimeError(f"external op {opname} returned {rc}")
                # read back through the MXTensor pointers (ascontiguousarray
                # may have copied)
                results = []
                for t in outs:
                    n = 1
                    for d in range(t.ndim):
                        n *= t.shape[d]
                    flat = _np.ctypeslib.as_array(t.data, shape=(n,))
                    results.append(NDArray(flat.reshape(
                        tuple(t.shape[d] for d in range(t.ndim))).copy()))
                return tuple(results) if n_out > 1 else results[0]

            wrapper.__name__ = opname
            wrapper.__doc__ = f"external op {opname} from {path}"
            return wrapper

        w = make_wrapper(i, n_out, name)
        register_op(f"lib::{name}", w)
        setattr(nd_mod, name, w)
        names.append(name)
    _LOADED[path] = names
    if verbose:
        print(f"loaded library {os.path.basename(path)} "
              f"(ABI v{version}): ops {names}")
    return names
