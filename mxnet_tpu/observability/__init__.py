"""Observability plane: numerics checking, flight recorder, request
tracing, postmortems.

Coordinated pieces (docs/observability.md):

  * :mod:`~mxnet_tpu.observability.numerics` — a graph pass
    (``MXTPU_NUMERICS=off|step|op``) that instruments captured jaxprs
    with fused is-finite checks and, on a trip, bisects the recorded
    program to the first non-finite equation;
  * :mod:`~mxnet_tpu.observability.flight` — the bounded ring of
    structured runtime events every subsystem reports into;
  * :mod:`~mxnet_tpu.observability.reqtrace` — per-request phase traces
    through the serving pipeline (head-sampled via MXTPU_TRACE_SAMPLE)
    plus the per-class SLO burn-rate plane that gates opsd ``/readyz``;
  * :mod:`~mxnet_tpu.observability.postmortem` — serializes everything
    (events + telemetry + spans + request traces + compile registry +
    env snapshot) into one atomic per-rank bundle that
    ``tools/blackbox.py`` merges across ranks.

Quick use::

    import mxnet_tpu as mx
    mx.observability.record_event("phase", name="warmup done")
    path = mx.observability.dump(reason="manual")   # the black box

Set ``MXTPU_FLIGHTREC_CRASHDUMP=1`` to auto-arm the excepthook /
atexit / faulthandler crash hooks at import.
"""
from __future__ import annotations

import os

from . import (  # noqa: F401
    costdb, flight, measure, numerics, opsd, postmortem, reqtrace,
)
from .flight import (  # noqa: F401
    events, record, record_loss, set_identity, trace_id,
)
from .numerics import NonFiniteError  # noqa: F401
from .postmortem import dump, install_crash_hooks  # noqa: F401

__all__ = [
    "costdb", "flight", "measure", "numerics", "opsd", "postmortem",
    "reqtrace",
    "record", "record_event", "record_loss", "events",
    "set_identity", "trace_id",
    "dump", "install_crash_hooks", "reset",
    "NonFiniteError",
]

record_event = record


def reset():
    """Test hygiene: drop flight events, numerics trip bookkeeping,
    request traces / SLO windows, and the measurement plane's in-memory
    state (pending programs, site scores, the loaded CostDB)."""
    flight.reset()
    numerics.reset()
    reqtrace.reset()
    measure.reset()
    costdb.reset()


if os.environ.get("MXTPU_FLIGHTREC_CRASHDUMP", "").lower() \
        not in ("", "0", "false", "off"):
    install_crash_hooks()

# MXTPU_OPS_PORT=<port> starts the live ops server at import (the
# per-process HTTP plane supervisors poll); unset/0 touches nothing.
opsd.start_from_env()
