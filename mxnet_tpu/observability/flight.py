"""Flight recorder: a bounded, lock-cheap ring of structured events.

The black-box half of the observability plane (docs/observability.md):
every notable runtime event — training steps, compile-cache misses,
collective dispatches, checkpoint commits, serving admissions/sheds,
watchdog beats, numerics trips — lands here as one small dict. The ring
is bounded (``MXTPU_FLIGHTREC_CAPACITY``), so a week-long job holds the
*last* N events, exactly what a postmortem needs; ``postmortem.dump()``
serializes it (with the telemetry/span/compile-registry snapshots) into
one atomic bundle that ``tools/blackbox.py`` can merge across ranks.

Hot-path cost: one ``enabled`` check, one dict build, one uncontended
lock acquire around a ``deque.append`` (the lock keeps the snapshot in
:func:`events` from iterating a mutating deque, which raises
``RuntimeError`` mid-postmortem). ``MXTPU_FLIGHTREC=0`` turns
recording into a single branch.

Cross-rank correlation: :func:`set_identity` stamps this process's
``(job_id, rank)`` — called by ``kvstore.tpu_dist`` at init — and every
event carries the live training-step index, so ``(job_id, step)`` is
the shared trace ID blackbox.py aligns bundles on. Events also keep a
``perf_counter`` timestamp (``pc``) on the same clock as diagnostics
spans, so merged chrome traces interleave events with spans.
"""
from __future__ import annotations

import collections
import os
import threading
import time

__all__ = [
    "record", "events", "reset", "enabled", "set_capacity", "capacity",
    "set_identity", "identity", "trace_id", "record_loss",
]

_DEFAULT_CAPACITY = 4096
_ring = collections.deque(maxlen=_DEFAULT_CAPACITY)
_lock = threading.Lock()

_identity = {}          # {"job": str, "rank": int, "world": int}
_step_events = [0]      # "step" events seen, drives periodic flushing
_capacity_synced = [False]


def _reinit_after_fork():
    # mxtpu service threads (watchdog scanner, serving batcher) record
    # events continuously; a fork — dataloader workers fork from a
    # threaded parent — landing inside the critical section would leave
    # _lock held forever in the child. Fresh lock, same ring.
    global _lock
    _lock = threading.Lock()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reinit_after_fork)


def _env_get(name, default):
    try:
        from .. import env as _env

        if name in _env.all_vars():
            return _env.get(name)
    except Exception:
        pass
    return default


def enabled():
    raw = os.environ.get("MXTPU_FLIGHTREC")
    if raw is not None:
        return raw.lower() not in ("", "0", "false", "off")
    return True


def capacity():
    return _ring.maxlen


def set_capacity(n):
    """Rebound the ring, keeping the newest events up to the new cap;
    returns the previous capacity."""
    global _ring
    n = max(1, int(n))
    _capacity_synced[0] = True  # an explicit call beats the env default
    with _lock:
        prev = _ring.maxlen
        _ring = collections.deque(_ring, maxlen=n)
    return prev


def _sync_capacity():
    # one-time: honor MXTPU_FLIGHTREC_CAPACITY without import-order games
    if _capacity_synced[0]:
        return
    _capacity_synced[0] = True
    n = _env_get("MXTPU_FLIGHTREC_CAPACITY", None)
    if n is None:
        raw = os.environ.get("MXTPU_FLIGHTREC_CAPACITY")
        n = int(raw) if raw else None
    if n and int(n) != _ring.maxlen:
        set_capacity(int(n))


def set_identity(rank=None, world=None, job=None, mesh=None, coords=None,
                 zero_frac=None, generation=None):
    """Stamp this process's place in the job — called by
    ``kvstore.tpu_dist`` at collective init (and by tests). Also pushes
    the (job, rank) trace context onto diagnostics spans so span records
    carry the same correlation ID as flight events.

    ``mesh`` ({axis: size}), ``coords`` ({axis: index}) and
    ``zero_frac`` (the 1/fsdp optimizer-state fraction this rank holds
    under ZeRO, or None when state replicates) come from
    ``ShardingPlan.apply``: they flow through :func:`identity` into the
    ops server's /identity payload, so tools/fleetctl.py tables can show
    each rank's (dp, tp) coordinates and ZeRO shard next to its rank
    number."""
    if rank is not None:
        _identity["rank"] = int(rank)
    if world is not None:
        _identity["world"] = int(world)
    if job is not None:
        _identity["job"] = str(job)
    if mesh is not None:
        _identity["mesh"] = {str(k): int(v) for k, v in dict(mesh).items()}
    if coords is not None:
        _identity["coords"] = {str(k): int(v)
                               for k, v in dict(coords).items()}
    if zero_frac is not None:
        _identity["zero_frac"] = float(zero_frac)
    if generation is not None:
        # elastic world generation (mxnet_tpu/elastic/reentry.py): which
        # incarnation of the job this process runs — supervisor restarts
        # and in-process reenter() both bump it; flows to opsd /identity
        # and the fleetctl table
        _identity["generation"] = int(generation)
    try:
        from ..diagnostics import spans as _spans

        ident = identity()
        _spans.set_trace_context(job=ident["job"], rank=ident["rank"])
    except Exception:
        pass


def identity():
    """Resolved ``{job, rank, world}``: explicit set_identity beats the
    MXTPU_JOB_ID / MXTPU_FLIGHTREC_RANK env, beats jax process info."""
    ident = dict(_identity)
    if "job" not in ident:
        job = _env_get("MXTPU_JOB_ID", "") or \
            os.environ.get("MXTPU_JOB_ID", "")
        ident["job"] = job or "local"
    if "rank" not in ident:
        raw = os.environ.get("MXTPU_FLIGHTREC_RANK")
        if raw is not None:
            ident["rank"] = int(raw)
        else:
            try:
                import jax

                ident["rank"] = jax.process_index()
            except Exception:
                ident["rank"] = 0
    if "world" not in ident:
        try:
            import jax

            ident["world"] = jax.process_count()
        except Exception:
            ident["world"] = 1
    if "generation" not in ident:
        # a supervisor-relaunched rank inherits its generation via env
        # (tools/supervisor.py stamps MXTPU_ELASTIC_GENERATION)
        raw = os.environ.get("MXTPU_ELASTIC_GENERATION")
        if raw:
            try:
                ident["generation"] = int(raw)
            except ValueError:
                pass
    return ident


def trace_id(step=None):
    """The shared cross-rank trace ID: ``(job_id, step)``."""
    if step is None:
        step = _current_step()
    return (identity()["job"], step)


def _current_step():
    try:
        from ..diagnostics import spans as _spans

        return _spans.current_step()
    except Exception:
        return 0


def record(kind, **fields):
    """Append one structured event. Never raises; a broken observability
    layer must not take the training loop down with it."""
    if not enabled():
        return None
    _sync_capacity()
    ev = {"kind": kind, "t": time.time(), "pc": time.perf_counter(),
          "step": _current_step()}
    if fields:
        ev.update(fields)
    # the lock is uncontended on the hot path; appending OUTSIDE it
    # would let a concurrent events() snapshot die with "deque mutated
    # during iteration" — exactly when a postmortem dump runs
    with _lock:
        _ring.append(ev)
    try:
        from ..telemetry import instruments as _instr

        _instr.record_flight_event(kind)
    except Exception:
        pass
    if kind == "step":
        _maybe_flush()
    return ev


def record_loss(value, **fields):
    """Record a host-synced loss value as a ``loss`` event — for loops
    that already paid the host read (MXTPU_NUMERICS=step does this at
    every step boundary; eager loops can call it after ``asnumpy()``)."""
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    return record("loss", value=value, **fields)


def _maybe_flush():
    """Periodic black-box spill: every MXTPU_FLIGHTREC_FLUSH_STEPS step
    events, write the postmortem bundle asynchronously so a SIGKILL'd
    run still leaves evidence on disk (the acceptance path for
    tools/blackbox.py)."""
    every = _env_get("MXTPU_FLIGHTREC_FLUSH_STEPS", 0)
    if not every:
        raw = os.environ.get("MXTPU_FLIGHTREC_FLUSH_STEPS")
        every = int(raw) if raw else 0
    if every <= 0:
        return
    _step_events[0] += 1
    if _step_events[0] % int(every):
        return
    try:
        from . import postmortem

        postmortem.dump(reason="periodic", sync=False)
    except Exception:
        pass


def events(kind=None):
    """Snapshot of the ring, oldest first. ``kind`` filters by
    event-kind PREFIX (``kind="serve"`` matches serve_batch /
    serve_shed / serve_start / ... — families share a prefix by
    convention), so opsd's ``/flight?kind=`` can hand a fleet poller
    just the serving events without dragging the whole ring."""
    with _lock:
        evs = list(_ring)
    if kind:
        k = str(kind)
        evs = [e for e in evs if str(e.get("kind", "")).startswith(k)]
    return evs


def reset():
    """Drop events and the periodic-flush counter (test hygiene);
    identity stays — it describes the process, not the run."""
    with _lock:
        _ring.clear()
    _step_events[0] = 0
