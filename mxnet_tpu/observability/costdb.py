"""Persistent CostDB: measured program costs + predicted-vs-measured
drift auditing.

The other half of the measurement plane (observability/measure.py runs
the microbenchmarks; this module keeps the results). Three subsystems
make performance decisions from the analytic byte model in
``passes/memory.py`` — kernel dispatch, the remat auto policy, the
layout accept test — and nothing ever checked whether those predictions
match reality. The CostDB closes the loop:

  * every measured program lands here keyed by ``(fingerprint,
    platform)`` — the PR-7 dedup structural fingerprint, so two
    processes (or two runs) measuring structurally identical programs
    share one record;
  * the file is atomic JSON-lines (write-tmp → fsync → ``os.replace``
    through the ``_checkpoint_io`` engine path, the postmortem idiom):
    ``save()`` first merges what other processes committed since our
    load, newest measurement wins, so N ranks on a shared filesystem
    converge instead of clobbering;
  * :func:`drift_report` joins the measurements against the analytic
    predictions. Absolute bandwidth is unknowable portably, so the
    auditor self-calibrates: the median ``predicted_bytes / wall_ms``
    over a platform's entries is that platform's effective bandwidth,
    and each program's drift ratio is its own implied bandwidth over
    the median. A ratio far from 1.0 (beyond
    ``MXTPU_COSTDB_DRIFT_MAX``, either direction) means the byte model
    is lying about THAT program — exactly the case where
    ``MXTPU_KERNELS=auto`` or remat-auto chose wrong;
  * :func:`audit` publishes ``cost_model_drift_ratio{site,program}``
    gauges (one per measured program, plus one per kernel-dispatch
    site recorded inside it) and drops a ``cost_drift`` flight event
    the first time a program trips.

Surfaced by opsd ``GET /costdb``, ``tools/diagnose.py --passes``,
``tools/costdb.py`` (list/measure/verify/diff), postmortem bundles, and
the fleetctl ``drift`` column. This is the substrate the ROADMAP
autotuner ("persist winners keyed by (program fingerprint, platform)")
plugs into.
"""
from __future__ import annotations

import json
import os
import statistics
import threading
import time

__all__ = [
    "CostDB", "db", "reset", "default_path",
    "drift_report", "drift_max", "audit",
]

DB_FORMAT = 1


def _env_get(name, default):
    try:
        from .. import env as _env

        if name in _env.all_vars():
            return _env.get(name)
    except Exception:
        pass
    raw = os.environ.get(name)
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() not in ("", "0", "false", "off")
    try:
        return type(default)(raw)
    except (TypeError, ValueError):
        return default


def default_path():
    """``MXTPU_COSTDB_PATH``, else ``<MXTPU_FLIGHTREC_DIR>/
    mxtpu_costdb.jsonl`` — next to the postmortem bundles."""
    p = str(_env_get("MXTPU_COSTDB_PATH", "") or "")
    if p:
        return p
    d = str(_env_get("MXTPU_FLIGHTREC_DIR", ".") or ".")
    return os.path.join(d, "mxtpu_costdb.jsonl")


def _atomic_write(path, payload):
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class CostDB:
    """In-memory measurement cache over one atomic JSON-lines file.

    Entries are dicts from ``measure.measure_callable`` — at minimum
    ``{fingerprint, platform, block, variant, wall_ms_p50, wall_ms_p95,
    predicted_bytes, time}``. The newest ``time`` wins on every merge,
    in memory and on disk alike.
    """

    def __init__(self, path=None, load=True):
        self.path = path or default_path()
        self._entries = {}  # (fingerprint, platform) -> entry dict
        self._lock = threading.Lock()
        if load:
            self.merge_load()

    @staticmethod
    def _key(entry):
        return (str(entry.get("fingerprint", "?")),
                str(entry.get("platform", "?")))

    def put(self, entry):
        """Merge one measurement (newest time wins); autosaves when
        ``MXTPU_COSTDB_AUTOSAVE`` (default on). Returns the entry."""
        entry = dict(entry)
        entry.setdefault("time", time.time())
        entry.setdefault("format", DB_FORMAT)
        with self._lock:
            k = self._key(entry)
            prev = self._entries.get(k)
            if prev is None or prev.get("time", 0) <= entry["time"]:
                self._entries[k] = entry
        if _env_get("MXTPU_COSTDB_AUTOSAVE", True):
            try:
                self.save()
            except Exception:
                pass  # a read-only filesystem must not fail a measurement
        return entry

    def get(self, fingerprint, platform):
        with self._lock:
            return self._entries.get((str(fingerprint), str(platform)))

    def entries(self):
        """Snapshot, oldest measurement first."""
        with self._lock:
            return sorted(self._entries.values(),
                          key=lambda e: e.get("time", 0))

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def merge_load(self):
        """Merge the on-disk file into memory (newest time wins per
        key). Tolerates a missing file and skips torn/garbage lines —
        the JSONL is append-merged by many processes. Returns the
        number of entries merged in."""
        merged = 0
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return 0
        with self._lock:
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(entry, dict):
                    continue
                k = self._key(entry)
                prev = self._entries.get(k)
                if prev is None or \
                        prev.get("time", 0) < entry.get("time", 0):
                    self._entries[k] = entry
                    merged += 1
        return merged

    def save(self, sync=True):
        """Commit the merged view atomically: re-merge what other
        processes wrote since our load, then write-tmp → fsync →
        ``os.replace`` through the ``_checkpoint_io`` engine path (the
        postmortem idiom — a kill mid-write leaves the previous
        complete file). Returns the path."""
        self.merge_load()
        with self._lock:
            rows = sorted(self._entries.values(),
                          key=lambda e: e.get("time", 0))
        payload = "".join(
            json.dumps(e, default=str) + "\n" for e in rows)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            from .. import _checkpoint_io

            _checkpoint_io.async_run(
                self.path, lambda: _atomic_write(self.path, payload))
            if sync:
                _checkpoint_io.wait_for_path(self.path)
        except Exception:
            _atomic_write(self.path, payload)
        return self.path

    def summary(self):
        entries = self.entries()
        return {
            "path": self.path,
            "entries": len(entries),
            "platforms": sorted({str(e.get("platform"))
                                 for e in entries}),
            "blocks": sorted({f"{e.get('block')}/{e.get('variant')}"
                              for e in entries}),
        }


# ---------------------------------------------------------------------------
# per-process singleton
# ---------------------------------------------------------------------------

_db = [None]
_db_lock = threading.Lock()
_tripped = set()  # (fingerprint, platform) already flight-evented


def db():
    """The per-process CostDB (lazily created, merge-loaded from
    :func:`default_path`)."""
    with _db_lock:
        if _db[0] is None:
            _db[0] = CostDB()
        return _db[0]


def reset():
    """Drop the in-memory DB + drift-event dedup (test hygiene). The
    on-disk file is untouched; the next :func:`db` re-loads it from the
    path resolved THEN, so tests can repoint MXTPU_COSTDB_PATH."""
    with _db_lock:
        _db[0] = None
    _tripped.clear()


# ---------------------------------------------------------------------------
# drift auditing
# ---------------------------------------------------------------------------


def drift_max():
    """The trip threshold: a program whose drift ratio leaves
    ``[1/max, max]`` trips the auditor. Analytic byte models are crude
    — within an order of magnitude of the platform norm is
    "consistent"; beyond it the model is mispredicting that program."""
    try:
        return max(1.0, float(_env_get("MXTPU_COSTDB_DRIFT_MAX", 8.0)))
    except (TypeError, ValueError):
        return 8.0


def drift_report(entries=None, threshold=None):
    """Join measurements against the analytic byte model.

    Per platform: ``calibration`` = median implied bandwidth
    (predicted_bytes / wall_ms_p50) over that platform's entries; each
    program's ``drift_ratio`` is its own implied bandwidth over the
    median, so 1.0 means "the model prices this program like it prices
    everything else here" and a large/small ratio means the model
    over/under-predicts its bytes. Returns::

        {"threshold": float,
         "calibration": {platform: bytes_per_ms},
         "programs": [{program, fingerprint, platform, drift_ratio,
                       tripped, wall_ms_p50, predicted_bytes,
                       sites}, ...],
         "tripped": [the subset with tripped=True]}
    """
    if entries is None:
        entries = db().entries()
    if threshold is None:
        threshold = drift_max()
    usable = [e for e in entries
              if float(e.get("predicted_bytes") or 0) > 0
              and float(e.get("wall_ms_p50") or 0) > 0]
    by_platform = {}
    for e in usable:
        by_platform.setdefault(str(e.get("platform")), []).append(e)
    calibration = {}
    programs = []
    for platform, group in sorted(by_platform.items()):
        bws = [float(e["predicted_bytes"]) / float(e["wall_ms_p50"])
               for e in group]
        calib = statistics.median(bws)
        calibration[platform] = calib
        for e, bw in zip(group, bws):
            ratio = bw / calib if calib > 0 else 1.0
            programs.append({
                "program": f"{e.get('block')}/{e.get('variant')}",
                "fingerprint": e.get("fingerprint"),
                "platform": platform,
                "drift_ratio": round(ratio, 4),
                "tripped": bool(ratio > threshold
                                or ratio < 1.0 / threshold),
                "wall_ms_p50": e.get("wall_ms_p50"),
                "predicted_bytes": e.get("predicted_bytes"),
                "sites": e.get("sites") or [],
            })
    programs.sort(key=lambda r: -abs(_log_ratio(r["drift_ratio"])))
    return {
        "threshold": threshold,
        "calibration": calibration,
        "programs": programs,
        "tripped": [r for r in programs if r["tripped"]],
    }


def _log_ratio(r):
    import math

    try:
        return math.log(max(float(r), 1e-12))
    except (TypeError, ValueError):
        return 0.0


def audit(entries=None, threshold=None):
    """Run the drift join and publish it: one
    ``cost_model_drift_ratio{site="program", program}`` gauge per
    measured program plus one per kernel-dispatch site recorded inside
    it (the BN-kernel / fused-optimizer analytic scores), and a
    ``cost_drift`` flight event the FIRST time a (fingerprint,
    platform) trips — re-audits (opsd polls) don't spam the ring.
    Never raises; returns the :func:`drift_report` dict."""
    try:
        rep = drift_report(entries=entries, threshold=threshold)
    except Exception as e:
        return {"error": repr(e), "programs": [], "tripped": [],
                "calibration": {}, "threshold": None}
    try:
        from ..telemetry import instruments as _instr

        for r in rep["programs"]:
            _instr.set_cost_drift("program", r["program"],
                                  r["drift_ratio"])
            for s in r["sites"]:
                _instr.set_cost_drift(str(s.get("site", "?")),
                                      r["program"], r["drift_ratio"])
    except Exception:
        pass
    for r in rep["tripped"]:
        key = (r["fingerprint"], r["platform"])
        if key in _tripped:
            continue
        _tripped.add(key)
        try:
            from . import flight as _flight

            _flight.record(
                "cost_drift", program=r["program"],
                fingerprint=r["fingerprint"], platform=r["platform"],
                drift_ratio=r["drift_ratio"],
                predicted_bytes=r["predicted_bytes"],
                wall_ms_p50=r["wall_ms_p50"],
                threshold=rep["threshold"])
        except Exception:
            pass
    return rep
