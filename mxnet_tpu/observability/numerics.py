"""In-graph numerics checks + the NaN-bisect interpreter.

The reference stack's ``monitor.py`` watched per-op tensor stats through
executor callbacks; a jit'd program has no callback seam, so the checks
must live IN the compiled program. :class:`NumericsPass` is a graph pass
(PR-7 pipeline, ``kind in (block, whole_step)``) driven by
``MXTPU_NUMERICS``:

  * ``step`` — one fused is-finite scalar per program: every inexact
    output (for the whole-step program: the loss, the updated params,
    the new optimizer state, the BN aux — grads feed all of them) is
    AND-reduced into a single bool delivered through an async
    ``jax.debug.callback``. Cost per dispatch: one reduction fused into
    the program, zero extra host syncs (the device pushes the byte when
    the step completes; ``gluon.TrainStep`` reads the verdict at its
    step-boundary sync).
  * ``op`` — a per-equation flag vector: the program is re-emitted
    equation by equation (``subgraph._eval_eqn``), each inexact-output
    equation contributes one is-finite bit, and ONE callback carries the
    stacked vector. A trip is attributed immediately from the rewrite-
    time equation table (op name / shapes / dtypes) with no re-run —
    the always-on debugging mode.

On a tripped ``step`` check the owner re-runs the recorded program
through :func:`bisect` — an eager, eqn-by-eqn walk reusing
``subgraph._eval_eqn`` that descends into pjit/remat/custom-call bodies
and stops at the FIRST equation producing a non-finite value, reporting
op name, output shapes/dtypes, which operand was already non-finite,
and per-operand stats. The report lands in the postmortem bundle
(docs/observability.md).
"""
from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp

from ..passes.manager import GraphPass, retrace_flat

__all__ = [
    "NumericsPass", "NonFiniteError", "mode", "normalize", "bisect",
    "bisect_callable", "tripped", "take_trip", "trips", "reset",
    "effects_barrier",
]

MODES = ("off", "step", "op")

_trip_lock = threading.Lock()
_trips = []          # oldest-first trip dicts (bounded below)
_MAX_TRIPS = 64
_programs = {}       # pid -> {"label", "mode", "checks", "table"}
_next_pid = [0]


class NonFiniteError(ArithmeticError):
    """A numerics check tripped. ``.trip`` is the flight-recorder trip
    record, ``.report`` the bisect attribution (may be None when the
    re-run could not reproduce it), ``.bundle`` the postmortem path."""

    def __init__(self, message, trip=None, report=None, bundle=None):
        super().__init__(message)
        self.trip = trip
        self.report = report
        self.bundle = bundle


def normalize(raw):
    """MXTPU_NUMERICS value -> off|step|op. Unrecognized spellings
    ('none', '1', 'true', typos) resolve to 'off': pass installation
    (passes/manager.resolve_passes) and the step-boundary poll
    (gluon.TrainStep) share THIS function, so a value that installs no
    NumericsPass must not make TrainStep disable donation and pay the
    effects barrier for checks that never run."""
    m = str(raw).strip().lower()
    return m if m in MODES else "off"


def mode():
    """Live MXTPU_NUMERICS value, normalized to off|step|op."""
    import os

    raw = None
    try:
        from .. import env as _env

        if "MXTPU_NUMERICS" in _env.all_vars():
            raw = _env.get("MXTPU_NUMERICS")
    except Exception:
        raw = None
    if raw is None:
        raw = os.environ.get("MXTPU_NUMERICS", "off")
    return normalize(raw)


def effects_barrier():
    """Wait for pending debug-callback deliveries (the verdict for a
    dispatch is guaranteed in once the program AND its effects land)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass


# ---------------------------------------------------------------------------
# trip bookkeeping (callbacks land here, owners poll at sync points)
# ---------------------------------------------------------------------------


def _register_program(label, pmode, checks, table=None):
    pid = _next_pid[0]
    _next_pid[0] += 1
    _programs[pid] = {"label": label, "mode": pmode, "checks": checks,
                      "table": table or []}
    return pid


def _record_trip(pid, attribution=None):
    meta = _programs.get(pid, {"label": f"pid{pid}", "mode": "?"})
    trip = {"label": meta["label"], "mode": meta["mode"]}
    if attribution:
        trip["equation"] = attribution
    try:
        from ..diagnostics import spans as _spans

        trip["step"] = _spans.current_step()
    except Exception:
        trip["step"] = 0
    with _trip_lock:
        _trips.append(trip)
        del _trips[:-_MAX_TRIPS]
    try:
        from ..telemetry import instruments as _instr

        _instr.record_numerics_trip(meta["label"])
    except Exception:
        pass
    try:
        from . import flight

        flight.record("numerics_trip", **trip)
    except Exception:
        pass
    return trip


def _on_step_flag(pid, ok):
    if not bool(ok):
        _record_trip(pid)


def _on_op_flags(pid, flags):
    import numpy as onp

    flags = onp.asarray(flags).astype(bool)
    if flags.all():
        return
    meta = _programs.get(pid)
    idx = int(onp.argmax(~flags))
    attribution = None
    if meta and idx < len(meta["table"]):
        attribution = dict(meta["table"][idx])
    _record_trip(pid, attribution)


def tripped():
    with _trip_lock:
        return bool(_trips)


def trips():
    with _trip_lock:
        return list(_trips)


def take_trip(label_prefix=None):
    """Pop (and return) the oldest trip, optionally only one whose label
    starts with ``label_prefix``; None when nothing tripped."""
    with _trip_lock:
        for i, t in enumerate(_trips):
            if label_prefix is None or \
                    str(t.get("label", "")).startswith(label_prefix):
                return _trips.pop(i)
    return None


def reset():
    with _trip_lock:
        _trips.clear()


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------


def _is_inexact_aval(aval):
    dt = getattr(aval, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.inexact)


def _is_dropvar(v):
    return type(v).__name__ == "DropVar"


class NumericsPass(GraphPass):
    """MXTPU_NUMERICS in-graph is-finite instrumentation (step | op)."""

    name = "numerics"
    priority = 99  # after AMP/remat: instrument the program that RUNS
    kinds = ("block", "whole_step")

    def __init__(self, mode_=None):
        self._mode = mode_

    def effective_mode(self):
        m = (self._mode or mode()).strip().lower()
        return m if m in ("step", "op") else ("off" if m in (
            "", "0", "off", "false", "no") else "step")

    def applies(self, ctx):
        return super().applies(ctx) and self.effective_mode() != "off"

    def run(self, closed, ctx):
        m = self.effective_mode()
        label = f"{ctx.label}/{ctx.variant or ctx.kind}"
        if m == "op":
            fn = _instrument_per_eqn(closed, label)
        else:
            fn = _instrument_outputs(closed, label)
        if fn is None:  # nothing inexact to check: keep the program
            return closed
        return retrace_flat(fn, closed)


def _instrument_outputs(closed, label):
    """step mode: AND-reduce isfinite over every inexact program output
    into one scalar, delivered asynchronously."""
    n_checked = sum(1 for v in closed.jaxpr.outvars
                    if _is_inexact_aval(getattr(v, "aval", None)))
    if not n_checked:
        return None
    pid = _register_program(label, "step", n_checked)

    def fn(*flat):
        outs = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
        checks = [jnp.isfinite(o).all() for o in outs
                  if jnp.issubdtype(jnp.result_type(o), jnp.inexact)]
        ok = functools.reduce(jnp.logical_and, checks)
        jax.debug.callback(functools.partial(_on_step_flag, pid), ok)
        return tuple(outs)

    return fn


def _eqn_meta(index, eqn, path=""):
    return {
        "eqn": f"{path}{index}",
        "op": eqn.primitive.name,
        "out_shapes": [tuple(getattr(v.aval, "shape", ()))
                       for v in eqn.outvars if not _is_dropvar(v)],
        "out_dtypes": [str(getattr(v.aval, "dtype", "?"))
                       for v in eqn.outvars if not _is_dropvar(v)],
        "in_shapes": [tuple(getattr(getattr(v, "aval", None), "shape", ()))
                      for v in eqn.invars],
        "in_dtypes": [str(getattr(getattr(v, "aval", None), "dtype", "?"))
                      for v in eqn.invars],
    }


def _instrument_per_eqn(closed, label):
    """op mode: the program re-emitted eqn by eqn with one is-finite bit
    per inexact-output equation; one callback carries the stacked
    vector, and a trip is attributed from the static equation table."""
    from ..subgraph import _eval_eqn
    from jax.extend import core as jcore

    jaxpr = closed.jaxpr
    table = []
    checked = {}
    for i, eqn in enumerate(jaxpr.eqns):
        if any(_is_inexact_aval(getattr(v, "aval", None))
               for v in eqn.outvars if not _is_dropvar(v)):
            checked[i] = len(table)
            table.append(_eqn_meta(i, eqn))
    if not table:
        return None
    pid = _register_program(label, "op", len(table), table)

    def fn(*flat):
        env = {}

        def read(v):
            if isinstance(v, jcore.Literal):
                return v.val
            return env[v]

        for v, c in zip(jaxpr.constvars, closed.consts):
            env[v] = c
        for v, a in zip(jaxpr.invars, flat):
            env[v] = a
        flags = []
        for i, eqn in enumerate(jaxpr.eqns):
            out = _eval_eqn(eqn, [read(v) for v in eqn.invars])
            if not isinstance(out, (tuple, list)):
                out = [out]
            for v, val in zip(eqn.outvars, out):
                env[v] = val
            if i in checked:
                bits = [jnp.isfinite(val).all()
                        for v, val in zip(eqn.outvars, out)
                        if not _is_dropvar(v)
                        and _is_inexact_aval(getattr(v, "aval", None))]
                flags.append(functools.reduce(jnp.logical_and, bits))
        jax.debug.callback(functools.partial(_on_op_flags, pid),
                           jnp.stack(flags))
        return [read(v) for v in jaxpr.outvars]

    return fn


# ---------------------------------------------------------------------------
# the bisect interpreter (postmortem attribution for step mode)
# ---------------------------------------------------------------------------

_CALL_PRIMS = ("pjit", "closed_call", "remat2", "checkpoint")
_CUSTOM_PRIMS = ("custom_jvp_call", "custom_vjp_call",
                 "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")


def _operand_stats(x):
    """Small host summary of one operand (device reductions, then tiny
    scalars to host — this only runs during a postmortem)."""
    try:
        xa = jnp.asarray(x)
        if not jnp.issubdtype(xa.dtype, jnp.inexact):
            return {"shape": tuple(xa.shape), "dtype": str(xa.dtype),
                    "finite_frac": 1.0}
        xf = xa.astype(jnp.float32)
        finite = jnp.isfinite(xf)
        return {
            "shape": tuple(xa.shape), "dtype": str(xa.dtype),
            "finite_frac": float(finite.mean()),
            "nan_count": int(jnp.isnan(xf).sum()),
            "inf_count": int(jnp.isinf(xf).sum()),
            "min": float(jnp.nanmin(jnp.where(finite, xf, jnp.nan))),
            "max": float(jnp.nanmax(jnp.where(finite, xf, jnp.nan))),
        }
    except Exception as e:  # stats must never mask the attribution
        return {"error": repr(e)}


def _inner_closed(eqn):
    """The inner ClosedJaxpr of a call-like equation, or None."""
    from jax.extend import core as jcore

    p = eqn.params
    name = eqn.primitive.name
    if name in ("pjit", "closed_call"):
        return p.get("jaxpr")
    if name in ("remat2", "checkpoint"):
        inner = p.get("jaxpr")
        if inner is not None and not hasattr(inner, "consts"):
            return jcore.ClosedJaxpr(inner, ())
        return inner
    for key in ("call_jaxpr", "fun_jaxpr"):
        inner = p.get(key)
        if inner is not None:
            if not hasattr(inner, "consts"):
                return jcore.ClosedJaxpr(inner, ())
            return inner
    return None


def _walk(jaxpr, consts, args, path, out):
    """Eager eqn-by-eqn eval; fills ``out`` with the first non-finite
    equation's report and returns the eqn outputs for the caller."""
    from jax.extend import core as jcore

    from ..subgraph import _eval_eqn

    env = {}

    def read(v):
        if isinstance(v, jcore.Literal):
            return v.val
        return env[v]

    for v, c in zip(jaxpr.constvars, consts):
        env[v] = c
    for v, a in zip(jaxpr.invars, args):
        env[v] = a
    for i, eqn in enumerate(jaxpr.eqns):
        invals = [read(v) for v in eqn.invars]
        vals = _eval_eqn(eqn, invals)
        if not isinstance(vals, (tuple, list)):
            vals = [vals]
        for v, val in zip(eqn.outvars, vals):
            env[v] = val
        if out:  # already attributed deeper in this walk
            continue
        bad = None
        for k, (v, val) in enumerate(zip(eqn.outvars, vals)):
            if _is_dropvar(v) or \
                    not _is_inexact_aval(getattr(v, "aval", None)):
                continue
            if not bool(jnp.isfinite(val).all()):
                bad = k
                break
        if bad is None:
            continue
        inner = _inner_closed(eqn)
        if inner is not None and len(inner.jaxpr.invars) == len(invals):
            try:
                _walk(inner.jaxpr, inner.consts, invals, f"{path}{i}/",
                      out)
            except Exception:
                pass  # misaligned body: attribute the call eqn itself
            if out:
                continue
        meta = _eqn_meta(i, eqn, path)
        meta["first_bad_output"] = bad
        meta["operands"] = [_operand_stats(x) for x in invals]
        meta["params"] = {k: str(v)[:120] for k, v in eqn.params.items()
                          if k not in ("jaxpr", "call_jaxpr", "fun_jaxpr")}
        out.append(meta)
    return [read(v) for v in jaxpr.outvars]


def bisect(closed, args):
    """Re-run ``closed`` eagerly on the recorded operands and return the
    first-non-finite-equation report (None when everything stayed
    finite — e.g. the operands were already consumed/donated)."""
    flat, _ = jax.tree_util.tree_flatten(args)
    if len(flat) != len(closed.jaxpr.invars):
        raise ValueError(
            f"bisect: {len(flat)} operands for a program with "
            f"{len(closed.jaxpr.invars)} inputs")
    out = []
    _walk(closed.jaxpr, closed.consts, flat, "", out)
    return out[0] if out else None


def bisect_callable(fn, *args):
    """Trace ``fn`` at ``args`` (side-effect-suppressed) and bisect the
    captured program on those exact operands."""
    from ..passes import _state as _pass_state

    with _pass_state.suppress_trace_bumps():
        closed = jax.make_jaxpr(fn)(*args)
    return bisect(closed, args)


def format_report(report):
    """One-line human rendering of a bisect/op-mode attribution."""
    if not report:
        return "(no attribution)"
    ops = ", ".join(
        f"op{j}[{o.get('dtype', '?')}{list(o.get('shape', ()))}"
        f" finite={o.get('finite_frac', '?')}]"
        for j, o in enumerate(report.get("operands", [])))
    return (f"eqn {report.get('eqn')} `{report.get('op')}` "
            f"out_shapes={report.get('out_shapes')} "
            f"out_dtypes={report.get('out_dtypes')}"
            + (f" operands: {ops}" if ops else ""))
